"""Server process: bootstrap, RPC planes, scheduler loop, event bridge.

Reference: crates/hyperqueue/src/server/bootstrap.rs (init_hq_server),
crates/tako/src/internal/server/rpc.rs (connection handling) and
scheduler/main.rs (Notify-woken, min-delay-throttled scheduler loop). The
whole server is one asyncio event loop — the reference's deliberately
single-threaded design (SURVEY.md §5 race detection) carried over: state is
mutated only from reactor handlers running on this loop, so the scheduler
snapshot needs no locks.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import socket
import time
from pathlib import Path

from hyperqueue_tpu import __version__
from hyperqueue_tpu.ids import task_id_job, task_id_task, make_task_id
from hyperqueue_tpu.models.greedy import GreedyCutScanModel
from hyperqueue_tpu.models.milp import MilpModel
from hyperqueue_tpu.models.multichip import MultichipModel
from hyperqueue_tpu.server import reactor
from hyperqueue_tpu.server.accounting import ACCOUNTED_KINDS, AccountingLedger
from hyperqueue_tpu.server.core import Core
from hyperqueue_tpu.server.ingest import (
    INGEST_CHUNKS,
    INGEST_TASKS,
    IngestPlane,
)
from hyperqueue_tpu.server.fanout import SendPool
from hyperqueue_tpu.server.jobs import JobManager, JobTaskInfo
from hyperqueue_tpu.server.journal_plane import JournalPlane
from hyperqueue_tpu.server.lazy import ArrayChunk
from hyperqueue_tpu.server.protocol import rqv_from_wire, submit_record
from hyperqueue_tpu.scheduler.queues import encode_sched_priority
from hyperqueue_tpu.scheduler.watchdog import SolverWatchdog
from hyperqueue_tpu.server.task import Task, TaskState
from hyperqueue_tpu.server.worker import Worker, WorkerConfiguration
from hyperqueue_tpu.transport.aead import WIRE_BACKEND
from hyperqueue_tpu.utils import chaos
from hyperqueue_tpu.utils import profiler
from hyperqueue_tpu.utils.metrics import REGISTRY
from hyperqueue_tpu.utils.slo import SloEngine
from hyperqueue_tpu.utils.trace import TRACER
from hyperqueue_tpu.transport.auth import (
    ROLE_CLIENT,
    ROLE_SERVER,
    ROLE_WORKER,
    AuthError,
    Connection,
    do_authentication,
)
from hyperqueue_tpu.utils import serverdir
from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.server")

SCHEDULE_MIN_DELAY = 0.01  # seconds; reference msd: 500ms prod / 20ms in benches
# forced worker overview cadence while a dashboard/stream listens
# (reference DEFAULT_WORKER_OVERVIEW_INTERVAL, server/worker.rs:63)
OVERVIEW_OVERRIDE_INTERVAL = 2.0

# module-level instrument: _process_worker_message is the server's hottest
# message path, so the get-or-create lookup must not run per message
_WORKER_MESSAGES_TOTAL = REGISTRY.counter(
    "hq_worker_messages_total",
    "uplink messages processed on the worker plane",
    labels=("op",),
)
_SUBSCRIBERS_DROPPED = REGISTRY.counter(
    "hq_subscribers_dropped_total",
    "subscribe-RPC consumers dropped because their bounded event queue "
    "overflowed (slow consumer)",
)
_SUB_EVENTS_DROPPED = REGISTRY.counter(
    "hq_sub_events_dropped_total",
    "events not delivered to subscribers whose queue had overflowed",
)
_REACTOR_STALLS = REGISTRY.counter(
    "hq_reactor_stalls_total",
    "reactor stall-watchdog captures: a work class held the event loop "
    "past --stall-budget (flight recorder + trace dumped)",
    labels=("plane",),
)
# graceful drain (ISSUE 13): counted under the autoalloc family because the
# elasticity controller is the main driver; `source` separates manual
# `hq worker stop --drain` from controller scale-down
_DRAINS_TOTAL = REGISTRY.counter(
    "hq_autoalloc_drains_total",
    "graceful worker drains initiated (masked from the solve, running "
    "tasks allowed to finish)",
    labels=("source",),
)
_DRAIN_ESCALATIONS_TOTAL = REGISTRY.counter(
    "hq_autoalloc_drain_escalations_total",
    "drains that hit --drain-timeout and escalated to a clean stop "
    "(running tasks requeue without a crash charge — zero task loss)",
)
_DRAIN_SECONDS = REGISTRY.histogram(
    "hq_autoalloc_drain_seconds",
    "drain latency: drain start to the worker being told to stop",
    buckets=(0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 300.0),
)
# queue-age distribution backing the queue-age SLO (utils/slo.py): how
# long each dispatched task sat READY before being assigned. Buckets
# stretch past the default latency decades — queue ages are minutes on
# a saturated cluster, not milliseconds.
_TASK_QUEUE_AGE = REGISTRY.histogram(
    "hq_task_queue_age_seconds",
    "ready -> assigned latency of dispatched tasks",
    buckets=(0.005, 0.025, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0,
             1800.0, 7200.0),
)

# default deadline for a drain nobody bounded explicitly
DRAIN_TIMEOUT_DEFAULT = 120.0

# reusable/stateless, so one instance serves every frame
_NOOP_BATCH = contextlib.nullcontext()


@contextlib.contextmanager
def _journal_batch(journal, fsync: bool, flush: bool):
    """One group-committed journal batch (see _journal_group_commit)."""
    journal.begin_batch()
    try:
        yield
    finally:
        if journal.commit_batch():
            if fsync:
                journal.flush(sync=True)
            elif flush:
                journal.flush()


class CommSender:
    """Per-worker outgoing queues + the scheduling wakeup flag.

    Reference: internal/server/comm.rs (CommSender) — unbounded channel per
    worker so the reactor never blocks on a slow connection.
    """

    def __init__(self):
        self._queues: dict[int, asyncio.Queue] = {}
        self.scheduling_event = asyncio.Event()

    def register_worker(self, worker_id: int) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._queues[worker_id] = q
        return q

    def unregister_worker(self, worker_id: int) -> None:
        self._queues.pop(worker_id, None)

    def _send(self, worker_id: int, message: dict) -> None:
        q = self._queues.get(worker_id)
        if q is not None:
            # the enqueue stamp feeds the fan-out plane's handoff-latency
            # probe (reactor enqueue -> frame on the wire)
            q.put_nowait((clock.monotonic(), message))

    # reactor.Comm protocol
    def send_compute(self, worker_id: int, tasks: list[dict]) -> None:
        # shared/separate split (reference messages/worker.rs:28-54
        # ComputeTasksMsg): tasks of one array share a body OBJECT, so an
        # identity dedup sends each distinct body once per message and the
        # tasks carry an index — at 512-task prefill batches this turns
        # ~512 serialized bodies into 1
        shared: list[dict] = []
        index: dict[int, int] = {}
        # trace ids dedup the same way: one submit's array shares ONE
        # trace id, so the frame carries it once and each task an index —
        # on the pure-python ChaCha fallback the 17-byte id string per
        # task was measurable encryption work at 512-task batches
        shared_traces: list = []
        trace_index: dict[str, int] = {}
        out = []
        for msg in tasks:
            body = msg.get("body")
            key = id(body)
            idx = index.get(key)
            if idx is None:
                idx = len(shared)
                index[key] = idx
                shared.append(body)
            slim = dict(msg)
            del slim["body"]
            slim["b"] = idx
            tr = slim.get("trace")
            if tr is not None:
                ti = trace_index.get(tr[0])
                if ti is None:
                    ti = len(shared_traces)
                    trace_index[tr[0]] = ti
                    shared_traces.append(tr[0])
                slim["trace"] = [ti, tr[1]]
            out.append(slim)
        payload = {"op": "compute", "tasks": out, "shared_bodies": shared}
        if shared_traces:
            payload["shared_traces"] = shared_traces
        self._send(worker_id, payload)

    def send_cancel(self, worker_id: int, task_ids: list[int]) -> None:
        self._send(worker_id, {"op": "cancel", "task_ids": task_ids})

    def send_retract(
        self, worker_id: int, task_refs: list[tuple[int, int]]
    ) -> None:
        self._send(
            worker_id,
            {"op": "retract", "tasks": [list(ref) for ref in task_refs]},
        )

    def send_stop(self, worker_id: int) -> None:
        self._send(worker_id, {"op": "stop"})

    def send_redirect(
        self, worker_id: int, to_shard: int, from_shard: int
    ) -> None:
        # federation worker lending: the worker re-registers with the
        # sibling shard dir (worker/runtime.py handles the op)
        self._send(
            worker_id,
            {"op": "redirect", "shard": to_shard, "from_shard": from_shard},
        )

    def send_overview_override(
        self, worker_id: int, interval: float | None
    ) -> None:
        self._send(
            worker_id, {"op": "set_overview_override", "interval": interval}
        )

    def broadcast_overview_override(self, interval: float | None) -> None:
        for worker_id in list(self._queues):
            self.send_overview_override(worker_id, interval)

    def ask_for_scheduling(self) -> None:
        self.scheduling_event.set()


class _Subscriber:
    """One subscribe-RPC consumer: a BOUNDED event queue plus its filter.

    The reactor never blocks on a subscriber: events are put_nowait into
    the queue, and a full queue marks the subscriber dead (dropped with a
    counter) instead of growing without bound — the backpressure contract
    the autoscaler feed and `hq top` rely on.
    """

    __slots__ = ("queue", "prefixes", "sample_interval", "dropped", "dead")

    def __init__(self, prefixes: tuple, sample_interval: float,
                 buffer: int = 4096):
        self.queue: asyncio.Queue = asyncio.Queue(
            maxsize=min(max(int(buffer), 64), 65536)
        )
        self.prefixes = prefixes
        self.sample_interval = sample_interval
        self.dropped = 0
        self.dead = False


class EventBridge:
    """reactor.EventSink -> jobs layer + waiters (+ journal, task 6)."""

    def __init__(self, server: "Server"):
        self.server = server

    def _record_start_spans(
        self, task, task_id, instance_id, worker_ids, wtrace
    ) -> None:
        """Fold the worker's task_running stamps + the core task's
        lifecycle stamps into the trace store. Deduplicated on
        (span, instance), so a reattach re-reporting the same incarnation
        keeps ONE unbroken trace."""
        traces = self.server.core.traces
        if not traces.enabled or task is None:
            return
        wt = wtrace or {}
        wid = worker_ids[0] if worker_ids else 0
        parent = traces.last_span_id(task_id)
        if task.t_ready and task.t_assigned:
            parent = traces.span(
                task_id, "server/queue", task.t_ready, task.t_assigned,
                "server", instance_id, parent,
            ) or parent
        accepted = wt.get("accepted_at")
        if task.t_assigned and accepted:
            parent = traces.span(
                task_id, "server/dispatch", task.t_assigned, accepted,
                "server", instance_id, parent,
            ) or parent
        launch = wt.get("launch_at")
        if accepted and launch:
            parent = traces.span(
                task_id, "worker/accept", accepted, launch,
                f"worker:{wid}", instance_id, parent,
            ) or parent
        spawned = wt.get("spawned_at")
        if launch and spawned:
            traces.span(
                task_id, "worker/spawn", launch, spawned,
                f"worker:{wid}", instance_id, parent,
            )

    def _record_finish_spans(self, task_id, wtrace) -> None:
        """Completion-side spans (run / uplink / commit) from the worker's
        task_finished/task_failed stamps. The worker re-sends spawned_at so
        a trace whose start event died in a crashed server's lost journal
        tail still closes with the execution span intact."""
        traces = self.server.core.traces
        if not traces.enabled:
            return
        rec = traces.get(task_id)
        task = self.server.core.tasks.get(task_id)
        instance = task.instance_id if task else 0
        if rec is None and task is None:
            return
        wt = wtrace or {}
        now = clock.now()
        # the reactor released resources (assigned_worker = 0) before this
        # sink fires: the worker identity lives in the earlier worker spans
        wid = task.assigned_worker if task else 0
        if not wid and rec is not None:
            for s in reversed(rec["spans"]):
                if s["proc"].startswith("worker:"):
                    wid = s["proc"].partition(":")[2]
                    break
        parent = traces.last_span_id(task_id)
        spawned = wt.get("spawned_at") or (task.t_started if task else 0.0)
        exited = wt.get("exited_at")
        if spawned and exited:
            parent = traces.span(
                task_id, "worker/run", spawned, exited,
                f"worker:{wid}", instance, parent,
            ) or parent
        sent = wt.get("sent_at")
        if sent:
            parent = traces.span(
                task_id, "worker/uplink", sent, now,
                f"worker:{wid}", instance, parent,
            ) or parent
        # commit time == receive time at trace resolution: the journal
        # group-commit covers the whole frame at block exit
        traces.span(
            task_id, "server/commit", now, now, "server",
            instance, parent,
        )
        traces.close(task_id)

    def on_task_started(self, task_id, instance_id, worker_ids, variant=0,
                        wtrace=None):
        task = self.server.core.tasks.get(task_id)
        # the core task's lifecycle stamps ride along: started_at survives a
        # reattach (the task never stopped running through the outage), and
        # queued/assigned let a journal consumer rebuild the full
        # submit->queued->assigned->spawned chain offline
        started_at = task.t_started if task else 0.0
        self.server.jobs.on_task_started(
            task_id_job(task_id), task_id, worker_ids,
            started_at=started_at or None,
        )
        self._record_start_spans(task, task_id, instance_id, worker_ids,
                                 wtrace)
        # fleet trace stitching (ISSUE 15): a task started on a BORROWED
        # worker notes the lend — home shard, host shard — on its trace;
        # the fact also rides the journal event so a restored successor
        # rebuilds the same annotation
        lends = []
        for wid_ in worker_ids:
            w = self.server.core.workers.get(wid_)
            lf = (getattr(w.configuration, "lent_from", -1)
                  if w is not None else -1)
            if lf >= 0:
                lends.append((wid_, lf))
        for wid_, lf in lends:
            self.server.core.traces.annotate(task_id, {
                "kind": "lend",
                "worker": wid_,
                "home_shard": lf,
                "host_shard": self.server.shard_id,
                "instance": instance_id,
                "time": started_at or clock.now(),
            })
        # instance + chosen variant ride along (reference task-started
        # events carry instance/worker/variant, tests/test_events.py
        # test_event_running_variant)
        payload = {
            "job": task_id_job(task_id), "task": task_id_task(task_id),
            "workers": worker_ids, "instance": instance_id,
            "variant": variant,
            "queued_at": task.t_ready if task else 0.0,
            "assigned_at": task.t_assigned if task else 0.0,
            "started_at": started_at,
        }
        # resource amounts (human units) ride the journal record so the
        # accounting fold is journal-self-contained: a restored or
        # migrated-to server charges the same usage without the core
        # task's request tables (server/accounting.py)
        if task is not None:
            names = self.server.core.resource_map.names()
            gang = max(len(worker_ids), 1)
            usage: dict[str, float] = {}
            worker0 = (
                self.server.core.workers.get(worker_ids[0])
                if worker_ids else None
            )
            for rid, amount in self.server.core.variant_amounts(
                task.rq_id, variant, worker0
            ):
                if amount > 0 and rid < len(names):
                    usage[names[rid]] = (
                        usage.get(names[rid], 0.0)
                        + (amount / 10_000) * gang
                    )
            if usage:
                payload["usage"] = usage
            # queue-age SLO input: READY -> ASSIGNED latency (a reattach
            # re-emit carries the original stamps and would re-observe;
            # skip it — instance 0 reattaches are rare enough that the
            # p95 is unaffected, and restarts legitimately re-observe)
            queued, assigned = payload["queued_at"], payload["assigned_at"]
            if queued and assigned and assigned >= queued:
                _TASK_QUEUE_AGE.observe(assigned - queued)
        # the worker-side stamps + trace id ride the journal event so a
        # restored server rebuilds the SAME trace (replay feeds them back
        # through events/restore.py)
        trace_id = self.server.core.traces.trace_id(task_id)
        if trace_id is not None:
            tctx = {"id": trace_id, **(wtrace or {})}
            if lends:
                # all (worker, home_shard) lend pairs ride the journal so
                # restore rebuilds every gang member's annotation, not just
                # the first worker's
                tctx["lends"] = [[wid_, lf] for wid_, lf in lends]
            payload["trace"] = tctx
        self.server.emit_event("task-started", payload)

    def on_task_restarted(self, task_id):
        self.server.jobs.on_task_restarted(task_id_job(task_id), task_id)
        # crash counter + new instance ride along so restore can rebuild
        # both exactly (tests/test_journal.py counter round-trip)
        task = self.server.core.tasks.get(task_id)
        self.server.emit_event(
            "task-restarted",
            {"job": task_id_job(task_id), "task": task_id_task(task_id),
             "crash_count": task.crash_counter if task else 0,
             "instance": task.instance_id if task else 0},
        )

    def _terminal_trace_payload(self, task_id, wtrace) -> dict | None:
        trace_id = self.server.core.traces.trace_id(task_id)
        if trace_id is None:
            return None
        return {"id": trace_id, **(wtrace or {})}

    def _observe_runtime(self, task_id, wtrace) -> None:
        """Feed the runtime predictor (scheduler/policy.py) with this
        task's observed execution time: worker-side spawn/exit stamps when
        they rode the uplink, else the server-side start stamp vs now."""
        policy = self.server.core.policy
        if policy is None or policy.predictor is None:
            return
        job = self.server.jobs.jobs.get(task_id_job(task_id))
        if job is None:
            return
        wt = wtrace or {}
        spawned = wt.get("spawned_at")
        exited = wt.get("exited_at")
        if spawned and exited and exited >= spawned:
            runtime = exited - spawned
        else:
            task = self.server.core.tasks.get(task_id)
            t0 = task.t_started if task else 0.0
            if not t0:
                return
            runtime = clock.now() - t0
        policy.predictor.observe(job.name, runtime)

    def on_task_finished(self, task_id, wtrace=None):
        self.server.reattach_pending.pop(task_id, None)
        self.server.jobs.on_task_finished(task_id_job(task_id), task_id)
        self._record_finish_spans(task_id, wtrace)
        self._observe_runtime(task_id, wtrace)
        payload = {"job": task_id_job(task_id), "task": task_id_task(task_id)}
        trace = self._terminal_trace_payload(task_id, wtrace)
        if trace is not None:
            payload["trace"] = trace
        self.server.emit_event("task-finished", payload)
        self.server.check_job_completion(task_id_job(task_id))

    def on_task_failed(self, task_id, message, wtrace=None):
        self.server.reattach_pending.pop(task_id, None)
        to_cancel = self.server.jobs.on_task_failed(
            task_id_job(task_id), task_id, message
        )
        self._record_finish_spans(task_id, wtrace)
        payload = {"job": task_id_job(task_id), "task": task_id_task(task_id),
                   "error": message}
        trace = self._terminal_trace_payload(task_id, wtrace)
        if trace is not None:
            payload["trace"] = trace
        self.server.emit_event("task-failed", payload)
        if to_cancel:
            self.server.schedule_cancel(to_cancel)
        self.server.check_job_completion(task_id_job(task_id))

    def on_task_canceled(self, task_id):
        self.server.reattach_pending.pop(task_id, None)
        self.server.core.traces.close(task_id)  # eviction candidate
        self.server.jobs.on_task_canceled(task_id_job(task_id), task_id)
        self.server.emit_event(
            "task-canceled",
            {"job": task_id_job(task_id), "task": task_id_task(task_id)},
        )
        self.server.check_job_completion(task_id_job(task_id))

    def on_worker_new(self, worker):
        # resources ride along so report/dashboard can group workers by
        # config (reference report.rs running_workers keyed on ResCount)
        names = self.server.core.resource_map.names()
        resources = {
            names[rid]: amount / 10_000
            for rid, amount in enumerate(worker.resources.amounts)
            if amount > 0 and rid < len(names)
        }
        payload = {
            "id": worker.worker_id,
            "hostname": worker.configuration.hostname,
            "group": worker.group, "resources": resources,
            "alloc_id": worker.configuration.alloc_id,
        }
        lent_from = getattr(worker.configuration, "lent_from", -1)
        if lent_from >= 0:
            # the borrow side of a lend: the fleet feed pairs this with
            # the lender's worker-lost `lent_to` to draw the flow
            payload["lent_from"] = lent_from
        self.server.emit_event("worker-connected", payload)

    def on_worker_lost(self, worker_id, reason):
        # structured loss record: how stale the last heartbeat was, and
        # whether the worker may legitimately come back (a deliberate stop
        # won't; a heartbeat timeout / connection loss might — it would
        # re-register under a new id, its stale tasks fenced by instance)
        past = self.server.past_workers.get(worker_id) or {}
        payload = {"id": worker_id, "reason": reason,
                   "heartbeat_age": past.get("heartbeat_age"),
                   "reattach_eligible": reason != "stopped"}
        if past.get("lent_to") is not None:
            # structured lend target: consumers render lending flows
            # without parsing the human reason string (ISSUE 15)
            payload["lent_to"] = past["lent_to"]
        self.server.emit_event("worker-lost", payload)
        self.server._draining.pop(worker_id, None)
        # crash-loop containment: the autoalloc service tracks how long
        # allocation-spawned workers survived after registration
        autoalloc = getattr(self.server, "autoalloc", None)
        if autoalloc is not None:
            autoalloc.on_worker_lost(worker_id, reason)


class Server:
    def __init__(
        self,
        server_dir: Path,
        host: str | None = None,
        client_port: int = 0,
        worker_port: int = 0,
        disable_client_auth: bool = False,
        disable_worker_auth: bool = False,
        scheduler: str = "auto",
        schedule_min_delay: float = SCHEDULE_MIN_DELAY,
        journal_path: Path | None = None,
        idle_timeout: float = 0.0,
        journal_flush_period: float = 0.0,
        access_file: Path | None = None,
        paranoid_tick: int = 0,
        journal_fsync: str = "never",
        journal_compact_interval: float = 0.0,
        journal_compact_threshold: int = 0,
        journal_salvage: bool = False,
        heartbeat_timeout_factor: float = 4.0,
        reattach_timeout: float = 15.0,
        solver_watchdog_timeout: float = 5.0,
        solver_rearm_ticks: int = 20,
        metrics_port: int | None = None,
        metrics_host: str = "0.0.0.0",
        flight_recorder_ticks: int = 512,
        tick_pipeline: bool = False,
        stall_budget: float = 1.0,
        stall_dumps: int = 8,
        profile_hz: float = 19.0,
        task_trace_capacity: int = 16384,
        client_plane: str = "thread",
        journal_plane: str = "thread",
        fanout_senders: int = 2,
        ingest_window: int = 64,
        ingest_handoff_max: int = 8192,
        lazy_array_threshold: int = 4096,
        shard_id: int = 0,
        shard_count: int = 1,
        federation_root: Path | None = None,
        lease_timeout: float = 15.0,
        promoted: bool = False,
        failover_watch: bool = False,
        memory_transport: bool = False,
        policy_file: Path | None = None,
    ):
        # idle_timeout: default worker idle timeout, adopted at registration
        # by workers that set none (reference ServerStartOpts idle_timeout,
        # tako rpc.rs sync_worker_configuration). journal_flush_period: 0 =
        # flush the journal on every event (stronger than the reference's
        # 30 s default); > 0 = flush on that period instead.
        # journal_fsync: "never" = fsync only on clean close/explicit
        # `hq journal flush` (flush-to-OS still happens per policy above);
        # "periodic" = fsync on the flush period (default 30 s if none);
        # "always" = fsync after every event (survives an OS crash at the
        # cost of one fsync per event).
        self.server_dir = Path(server_dir)
        self.host = host or socket.gethostname()
        self.client_port = client_port
        self.worker_port = worker_port
        self.disable_client_auth = disable_client_auth
        self.disable_worker_auth = disable_worker_auth
        self.access_file = access_file
        self.idle_timeout = idle_timeout
        self.journal_flush_period = journal_flush_period
        if journal_fsync not in ("never", "periodic", "always"):
            raise ValueError(f"unknown journal fsync policy {journal_fsync!r}")
        self.journal_fsync = journal_fsync
        # journal compaction (events/snapshot.py): snapshot live state +
        # GC the superseded journal prefix, every --journal-compact-interval
        # seconds and/or whenever the journal exceeds
        # --journal-compact-threshold bytes (0 = that trigger off)
        self.journal_compact_interval = journal_compact_interval
        self.journal_compact_threshold = journal_compact_threshold
        # --journal-salvage: skip CRC-corrupt mid-file journal records
        # (counted in hq_journal_salvaged_records_total) instead of
        # refusing to start
        self.journal_salvage = journal_salvage
        # boots that have written this journal lineage (server-uid records
        # up to now, self included once start() emits ours): the
        # instance-generation fence base a snapshot must carry
        self.n_boots = 0
        self.last_restore: dict | None = None
        self.last_compaction: dict | None = None
        self._compacting = False
        self.heartbeat_timeout_factor = heartbeat_timeout_factor
        # restored maybe-running tasks wait this long for their pre-crash
        # worker to reconnect and reclaim them before being fenced and
        # requeued (task_id -> monotonic deadline); 0 = requeue immediately
        self.reattach_timeout = reattach_timeout
        self.reattach_pending: dict[int, float] = {}
        # server uids that have written this journal (restored from
        # server-uid records + this instance's own): a reattach claim must
        # name one of them, or the worker's tasks belong to a DIFFERENT
        # server lineage (same dir, different --journal) and task ids could
        # collide at instance 0
        self.journal_uids: set[str] = set()
        self.schedule_min_delay = schedule_min_delay
        # disconnected workers, for `worker list --all` / `worker info` on a
        # dead id (reference keeps them in the HQ State worker map)
        self.past_workers: dict[int, dict] = {}
        self.core = Core()
        # debug: every N ticks, assert the incremental tick assembly is
        # bit-identical to a from-scratch one (scheduler/tick_cache.py
        # paranoid_check; `--paranoid-tick N`)
        self.core.paranoid_tick = paranoid_tick
        # --tick-pipeline: two-stage async ticks (scheduler/pipeline.py) —
        # solve N dispatches without blocking and is mapped at tick N+1,
        # overlapping device execution with the inter-tick host work.
        # Paranoid ticks and watchdog fallbacks force the synchronous path.
        if tick_pipeline:
            from hyperqueue_tpu.scheduler.pipeline import TickPipeline

            self.core.tick_pipeline = TickPipeline()
        # flight recorder: ring of the last N per-tick DecisionRecords +
        # control-plane events (`--flight-recorder-ticks`, 0 = off),
        # dumped by `hq server flight-recorder dump` and joined by
        # `hq task explain` / `hq server trace export`
        from hyperqueue_tpu.utils.flight import FlightRecorder
        from hyperqueue_tpu.utils.trace import LagTracker, TaskTraceStore

        self.core.flight = FlightRecorder(flight_recorder_ticks)
        # per-task distributed traces (`hq task trace`): bounded store,
        # `--task-trace-capacity 0` disables the whole plane (no store, no
        # trace headers on compute messages, no worker stamps)
        self.core.traces = TaskTraceStore(task_trace_capacity)
        # reactor loop-lag tracking + stall watchdog: every work class
        # (rpc/journal/solve/fanout) and the loop's own sleep-overshoot
        # feed hq_reactor_lag_seconds; an observation over --stall-budget
        # seconds auto-captures a flight-recorder + trace dump
        # (`--stall-budget 0` keeps the histograms but never captures)
        self.lag = LagTracker()
        # continuous profiling plane (ISSUE 19): always-on sampling
        # profiler at --profile-hz (0 = off); inert under the simulator —
        # start() never launches the sampler on a memory-transport server
        # and the profiler itself refuses simulated clocks
        self.profile_hz = float(profile_hz)
        self._profiler_started = False
        self.stall_budget = float(stall_budget)
        self.stall_dumps = max(int(stall_dumps), 1)
        self.stalls_captured = 0
        self.last_stall: dict | None = None
        self._last_stall_capture = 0.0
        # subscribe-RPC consumers: bounded per-subscriber queues; slow
        # consumers are dropped (counter), never allowed to grow the queue
        # without bound (the autoscaler/`hq top` feed)
        self._subscribers: list[_Subscriber] = []
        # client-connection plane (server/ingest.py): "thread" (default)
        # moves accept/auth/framing/decode off the reactor loop onto a
        # dedicated thread with a batched handoff; "reactor" keeps the
        # pre-ISSUE-10 in-loop handling (operational escape hatch)
        if client_plane not in ("thread", "reactor"):
            raise ValueError(f"unknown client plane {client_plane!r}")
        self.client_plane = client_plane
        # in-memory transport (the deterministic simulator, sim/): no TCP
        # listeners at all — connections are injected via accept_worker /
        # accept_client over in-memory stream pairs.  Requires the in-loop
        # client plane: the threaded ingest plane owns real sockets on its
        # own thread, which is exactly what a single-threaded
        # deterministic run must not have.
        self.memory_transport = bool(memory_transport)
        if self.memory_transport and client_plane != "reactor":
            raise ValueError(
                "memory_transport requires client_plane='reactor' "
                "(the threaded ingest plane owns real sockets)"
            )
        # connection-handler tasks spawned by accept_worker/accept_client
        # (memory transport only; TCP handlers belong to asyncio.Server).
        # Tracked so a simulated kill -9 can cancel them abruptly.
        self._conn_tasks: set = set()
        # journal plane (server/journal_plane.py): "thread" (default)
        # moves group commit + fsync onto a commit thread with
        # watermark-gated visibility; "reactor" keeps the inline
        # group-commit block (escape hatch, mirrors --client-plane)
        if journal_plane not in ("thread", "reactor"):
            raise ValueError(f"unknown journal plane {journal_plane!r}")
        self.journal_plane = journal_plane
        self.jplane: JournalPlane | None = None
        # fan-out plane (server/fanout.py): N sender threads running the
        # msgpack-encode + AEAD-seal half of every downlink send; 0 keeps
        # encodes inline on the owning loop
        self.fanout_senders = max(int(fanout_senders), 0)
        self.sendpool = SendPool(self.fanout_senders)
        self.ingest_window = ingest_window
        self.ingest_handoff_max = ingest_handoff_max
        self.ingest_plane: IngestPlane | None = None
        self._handoff_wake = asyncio.Event()
        # streaming-op tasks spawned by the ingest drain loop, cancelled
        # at shutdown (legacy plane ties their lifetime to the conn task)
        self._client_tasks: set = set()
        # arrays at/above this size are stored as lazy chunks
        # (server/lazy.py) instead of per-task records; 0 disables
        self.lazy_array_threshold = (
            lazy_array_threshold if lazy_array_threshold > 0 else 1 << 62
        )
        # chunked-submit streams: submit uid -> job id (exactly-once chunk
        # replay lands on the same job across client reconnects/restores)
        self._stream_jobs: dict[str, int] = {}
        # federation (ISSUE 11): this server owns shard `shard_id` of a
        # `shard_count`-way static job-id partition rooted at
        # `federation_root` (None = classic standalone server). The shard
        # dir holds an atomic lease renewed by _lease_renew_loop; losing
        # it to a successor FENCES this instance (it stops immediately).
        if not (0 <= shard_id < max(shard_count, 1)):
            raise ValueError(
                f"shard id {shard_id} outside 0..{shard_count - 1}"
            )
        self.shard_id = shard_id
        self.shard_count = max(int(shard_count), 1)
        self.federation_root = (
            Path(federation_root) if federation_root else None
        )
        self.lease_timeout = float(lease_timeout)
        self.promoted = promoted
        self.lease = None
        self.fenced = False
        # --failover-watch: this shard also volunteers as a successor for
        # dead sibling shards (claims gated on being idle itself)
        self.failover_watch = failover_watch
        self._watcher = None
        # graceful drains in flight (ISSUE 13): wid -> {deadline, started,
        # source}; the drain reaper stops each worker once it settles idle
        # or the deadline escalates the drain to a clean stop
        self._draining: dict[int, dict] = {}
        # cross-shard worker lending: wid -> target shard for workers this
        # shard ordered to re-register elsewhere (coordinator-driven)
        self._lent_workers: dict[int, int] = {}
        self.workers_lent_total = 0
        # elastic resharding (ISSUE 17): jobs this shard exported live to a
        # sibling. migrating_out: job -> {"mig", "to"} while sealed here and
        # the protocol is in flight; migrated_out: job -> new owner once the
        # tombstone is journaled (requests answer wrong-shard from then on);
        # migrations_in: mig uid -> job for imports already applied, so a
        # re-driven import acks dup instead of double-seeding.
        self.migrating_out: dict[int, dict] = {}
        self.migrated_out: dict[int, int] = {}
        self.migrations_in: dict[str, int] = {}
        self.jobs = JobManager()
        self.comm = CommSender()
        self.events = EventBridge(self)
        # production health plane (ISSUE 18): the usage ledger folds the
        # SAME records the journal persists (live emit, replay, and
        # migration import all call observe — bit-equal by construction);
        # the SLO engine judges the metrics registry on sliding windows
        # from _slo_loop and journals alert transitions
        self.accounting = AccountingLedger()
        self.slo = SloEngine()
        # lazy materialization needs the CURRENT job manager (restore may
        # swap it out on a snapshot fallback): bind a getter, not the object
        self.core.lazy.jobs_getter = lambda: self.jobs
        if scheduler == "milp":
            base_model = MilpModel()
        elif scheduler == "multichip":
            base_model = MultichipModel()
        elif scheduler == "greedy-numpy":
            # pinned host/numpy solve: no adaptive host/device selection,
            # so the backend (and the decision records naming it) is
            # identical run-to-run — the simulator's determinism
            # regressions and any deployment that values reproducibility
            # over device offload use this
            base_model = GreedyCutScanModel(backend="numpy")
        elif scheduler == "greedy-fused":
            # fused constraint solve: multi-node gangs become all-or-
            # nothing column groups INSIDE the batched solve
            # (ops/assign.py gang rows) instead of the host-side
            # reservation drain; deterministic like greedy-numpy so the
            # simulator can A/B it against the host gang phase
            base_model = GreedyCutScanModel(backend="numpy")
            self.core.fused_solve = True
        else:
            base_model = GreedyCutScanModel()
        # weighted scheduling objective (--policy-file, scheduler/policy.py):
        # heterogeneity affinity + fairness + runtime prediction on top of
        # the fused dense solve. Gated to greedy-fused — the policy's
        # affinity rows ride the dense snapshot's worker order, and the
        # fused path is the one objective seam every degraded mode shares.
        self.policy_file = policy_file
        if policy_file:
            if scheduler != "greedy-fused":
                raise ValueError(
                    "--policy-file requires --scheduler greedy-fused "
                    f"(got {scheduler!r})"
                )
            from hyperqueue_tpu.scheduler.policy import build_policy

            def _job_label(job_id: int) -> str | None:
                job = self.jobs.jobs.get(job_id)
                return job.name if job is not None else None

            def _live_jobs() -> list[int]:
                return [
                    job_id for job_id, job in self.jobs.jobs.items()
                    if not job.all_tasks_done()
                ]

            self.core.policy = build_policy(
                str(policy_file), ledger=self.accounting,
                job_name=_job_label, live_jobs=_live_jobs,
            )
        # --paranoid-tick also arms the device-resident solve's own
        # bit-exactness guard: every N resident solves re-run from a fresh
        # full upload and assert identical counts (models/greedy.py)
        if paranoid_tick and hasattr(base_model, "paranoid_resident"):
            base_model.paranoid_resident = paranoid_tick
        # every solve runs behind the watchdog: a solver exception or hang
        # degrades that tick to the host greedy fallback instead of killing
        # the scheduling loop (scheduler/watchdog.py)
        self.model = SolverWatchdog(
            base_model,
            timeout_s=solver_watchdog_timeout,
            rearm_ticks=solver_rearm_ticks,
        )
        self.scheduler_kind = scheduler
        self.access: serverdir.AccessRecord | None = None
        self.autoalloc = None
        self.journal = None
        self.journal_path = journal_path
        self._stop_event = asyncio.Event()
        self._job_waiters: dict[int, list[asyncio.Event]] = {}
        self._event_listeners: list[asyncio.Queue] = []
        self._event_seq = 0
        # dashboards/streams that asked for live hardware overviews; while
        # any is attached, workers are forced onto a 2 s overview interval
        # (reference SetOverviewIntervalOverride, control.rs:180-203,
        # DEFAULT_WORKER_OVERVIEW_INTERVAL server/worker.rs:63)
        self._overview_listeners = 0
        self._worker_conns: dict[int, Connection] = {}
        self._tasks: list[asyncio.Task] = []
        self._servers: list[asyncio.base_events.Server] = []
        self.started_at = clock.now()
        # Prometheus exposition endpoint (utils/metrics.py): None = off
        # (the default — recording still happens, it is just not served),
        # 0 = ephemeral port, resolved into self.metrics_port at start()
        # and surfaced through `hq server info`. The endpoint is
        # UNAUTHENTICATED (Prometheus convention) — metrics_host lets a
        # deployment bind 127.0.0.1 behind a scraping sidecar.
        self.requested_metrics_port = metrics_port
        self.metrics_host = metrics_host
        self.metrics_port: int | None = None
        self._metrics_server = None
        self._metrics_hook = None
        # hq_worker_* metric names currently fanned out from piggybacked
        # worker samples (cleared + rebuilt on every scrape)
        self._piggyback_names: set[str] = set()

    # ------------------------------------------------------------------
    async def start(self) -> serverdir.AccessRecord:
        # GC tuning: a tick allocates tens of thousands of short-lived
        # objects (assignments, messages); default thresholds fire gen-0
        # collections mid-tick and add ~30 ms pauses (measured as 20 ms ->
        # 50 ms tick spikes at 1M x 1k). Raised thresholds collect cycles in
        # bigger, rarer batches; startup state (including a restored
        # journal's task graph) is frozen at the END of start().
        import gc

        if not self.memory_transport:
            # simulator runs boot many Server objects per process; the
            # permanent-generation freeze at the end of start() would pin
            # every dead incarnation's state in memory, so sim servers
            # skip the GC tuning entirely
            gc.set_threshold(100_000, 50, 25)

        if self.federation_root is not None:
            import secrets as _secrets

            from hyperqueue_tpu.utils.lease import ShardLease

            existing_fed = serverdir.load_federation(self.federation_root)
            if (
                existing_fed is not None
                and self.shard_count > int(existing_fed["shard_count"])
            ):
                # online shard add (ISSUE 17): booting shard N of an N+1-way
                # count against an N-way root GROWS the federation in place
                # — descriptor rewritten, ownership log records the join,
                # sibling shards keep running untouched
                serverdir.grow_federation(
                    self.federation_root, self.shard_count
                )
            else:
                serverdir.write_federation(
                    self.federation_root, self.shard_count
                )
            # claim the shard BEFORE touching the journal: the lease is
            # what guarantees one journal appender per shard — a double
            # start (or a failover race) must fail here, not interleave
            # records. Raises LeaseHeldError while the holder is alive.
            self.lease = ShardLease(self.server_dir, self.lease_timeout)
            self.lease_owner = f"{socket.gethostname()}:{os.getpid()}:" + (
                _secrets.token_hex(4)
            )
            lease_rec = self.lease.acquire(self.lease_owner)
            logger.info(
                "shard %d/%d lease acquired (epoch %d%s)",
                self.shard_id, self.shard_count, lease_rec["epoch"],
                ", promoted successor" if self.promoted else "",
            )
            # renew from the moment the claim lands: a promotion whose
            # journal restore outlasts --lease-timeout must not look
            # stale to ANOTHER successor mid-restore (two claimants =
            # two journal appenders, the exact thing the lease forbids)
            self._tasks.append(self._spawn_loop(self._lease_renew_loop))

        if self.journal_path is not None:
            from hyperqueue_tpu.events import snapshot as snapshot_mod
            from hyperqueue_tpu.events.journal import Journal
            from hyperqueue_tpu.events.restore import restore_from_journal

            self.journal = Journal(
                self.journal_path, salvage=self.journal_salvage
            )
            # a snapshot alone is restorable (the journal may be freshly
            # rotated away or lost with the tail already folded in)
            if self.journal_path.exists() or snapshot_mod.have_snapshot(
                self.journal_path
            ):
                # off the event loop: nothing else references this Server
                # yet, and a peer shard promoting a dead sibling
                # (--failover-watch) runs THIS start() on its own live
                # reactor — a multi-second journal replay inline would
                # freeze its scheduler, heartbeats, and worker plane
                await asyncio.get_running_loop().run_in_executor(
                    None, restore_from_journal, self
                )
                if self.promoted and self.core.traces.enabled:
                    # fleet trace stitching (ISSUE 15): every trace still
                    # open at promotion lived through the shard death —
                    # stamp the failover (lease epoch) so `hq task trace`
                    # and the fleet export show the seam
                    stamped = self.core.traces.annotate_open({
                        "kind": "failover",
                        "shard": self.shard_id,
                        "lease_epoch": (
                            self.lease.epoch if self.lease else 0
                        ),
                        "time": clock.now(),
                    })
                    if stamped:
                        logger.info(
                            "stamped failover annotation on %d open "
                            "trace(s)", stamped,
                        )
            self.journal.open_for_append()
            if self.journal_plane == "thread":
                self.jplane = JournalPlane(
                    self.journal,
                    fsync_always=self.journal_fsync == "always",
                    flush_each=not self.journal_flush_period,
                    loop=asyncio.get_running_loop(),
                    lag=self.lag,
                    on_fatal=self.stop,
                )
                self.jplane.start()
        # after the restore (which may replace self.jobs): pin this
        # shard's job-id allocator to its congruence class
        self._apply_job_id_partition()

        # pre-shared deployment (reference generate-access + serverdir.rs):
        # an access file pins ports and both plane keys so workers/clients on
        # other sites can be configured before the server starts
        preshared: serverdir.AccessRecord | None = None
        if self.access_file is not None:
            import json as _json

            with open(self.access_file) as f:
                raw = _json.load(f)
            # the server needs BOTH planes: a split client-only/worker-only
            # file (generate-access --client-file/--worker-file) would
            # silently disable auth + bind an ephemeral port on the missing
            # plane — reject it loudly (reference: only FullAccessRecord is
            # accepted by server start)
            missing = [p for p in ("client", "worker") if p not in raw]
            if missing:
                raise ValueError(
                    f"access file {self.access_file} is a split "
                    f"{'/'.join(sorted(set(('client', 'worker')) - set(missing)))}"
                    f"-only record; `server start --access-file` needs the "
                    f"full record (missing plane: {', '.join(missing)})"
                )
            preshared = serverdir.AccessRecord.from_json(raw)
            self.client_port = preshared.client_port
            self.worker_port = preshared.worker_port

        if self.memory_transport:
            # no listeners: the simulator injects connections directly
            # (accept_worker/accept_client); port 0 marks "not reachable
            # over TCP" in the access record
            self._servers = []
        else:
            worker_srv = await asyncio.start_server(
                self._handle_worker_conn, "0.0.0.0", self.worker_port
            )
            self._servers = [worker_srv]
            self.worker_port = worker_srv.sockets[0].getsockname()[1]
        if self.memory_transport:
            pass
        elif self.client_plane == "thread":
            # decoupled connection plane (server/ingest.py): client
            # sockets live on their own thread; decoded messages cross
            # into this loop through the batched handoff drained by
            # _ingest_drain_loop
            self.ingest_plane = IngestPlane(
                lambda: (
                    self.access.client_key_bytes() if self.access else None
                ),
                window=self.ingest_window,
                handoff_max=self.ingest_handoff_max,
                sendpool=self.sendpool,
            )
            self.client_port = self.ingest_plane.start(
                "0.0.0.0", self.client_port,
                asyncio.get_running_loop(), self._handoff_wake.set,
            )
        else:
            client_srv = await asyncio.start_server(
                self._handle_client_conn, "0.0.0.0", self.client_port
            )
            self._servers.append(client_srv)
            self.client_port = client_srv.sockets[0].getsockname()[1]

        self._metrics_hook = self._collect_metrics
        REGISTRY.add_collect_hook(self._metrics_hook)
        if self.requested_metrics_port is not None:
            from hyperqueue_tpu.utils.metrics import start_metrics_server

            self._metrics_server, self.metrics_port = (
                await start_metrics_server(
                    REGISTRY, self.requested_metrics_port,
                    host=self.metrics_host,
                    probes={"/healthz": self._probe_healthz,
                            "/readyz": self._probe_readyz},
                )
            )
            logger.info(
                "metrics endpoint on http://%s:%d/metrics "
                "(+ /healthz /readyz)",
                self.metrics_host, self.metrics_port,
            )

        # continuous profiling plane (ISSUE 19): the reactor thread labels
        # itself, then the sampler starts. Memory-transport (simulator)
        # servers never start it — the profiler is real-wall-clock
        # telemetry and must stay inert under a virtual clock (the
        # profiler's own is_simulated() guard backstops this).
        if not self.memory_transport and self.profile_hz > 0:
            profiler.register_plane("reactor")
            self._profiler_started = profiler.start_profiler(self.profile_hz)
            if self._profiler_started:
                logger.info(
                    "sampling profiler on at %.3g Hz (--profile-hz)",
                    self.profile_hz,
                )

        instance_dir = serverdir.create_instance_dir(self.server_dir)
        self._instance_dir = instance_dir
        if preshared is not None:
            self.access = preshared
        else:
            self.access = serverdir.generate_access(
                self.host,
                self.client_port,
                self.worker_port,
                disable_client_auth=self.disable_client_auth,
                disable_worker_auth=self.disable_worker_auth,
            )
        serverdir.store_access(instance_dir, self.access)
        if self.journal is not None:
            # record this instance's uid in the journal so a future restore
            # can verify that reattaching workers come from this lineage
            self.journal_uids.add(self.access.server_uid)
            self.n_boots += 1
            self.emit_event("server-uid", {"server_uid": self.access.server_uid})

        from hyperqueue_tpu.autoalloc.service import AutoAllocService

        self.autoalloc = AutoAllocService(self, instance_dir / "autoalloc")
        self.autoalloc.start()
        self._tasks.append(self._spawn_loop(self._scheduler_loop))
        self._tasks.append(self._spawn_loop(self._heartbeat_reaper))
        self._tasks.append(self._spawn_loop(self._drain_reaper))
        self._tasks.append(self._spawn_loop(self._loop_lag_monitor))
        self._tasks.append(self._spawn_loop(self._slo_loop))
        if self.federation_root is not None and self.failover_watch:
            # idle-peer successor mode: this shard claims dead siblings,
            # but only while its own ready backlog is empty (a drowning
            # shard leaves the claim to the standby or another peer)
            from hyperqueue_tpu.server.federation import FailoverWatcher

            self._watcher = FailoverWatcher(
                self.federation_root,
                server_kwargs=self.federation_server_kwargs(),
                lease_timeout=self.lease_timeout,
                own_shard=self.shard_id,
                eligible=lambda: self.core.queues.total_ready() == 0,
            )
            self._tasks.append(self._spawn_loop(self._watcher.run))
        if self.ingest_plane is not None:
            self._tasks.append(self._spawn_loop(self._ingest_drain_loop))
        if self.journal is not None and (
            self.journal_flush_period > 0 or self.journal_fsync == "periodic"
        ):
            self._tasks.append(self._spawn_loop(self._journal_flush_loop))
        if self.journal is not None and (
            self.journal_compact_interval > 0
            or self.journal_compact_threshold > 0
        ):
            self._tasks.append(self._spawn_loop(self._journal_compact_loop))
        if self.reattach_pending:
            # journal restore held maybe-running tasks for their pre-crash
            # workers; requeue whatever is unclaimed when the window closes
            self._tasks.append(self._spawn_loop(self._reattach_reaper))
        logger.info(
            "server started uid=%s client=%s:%d worker=%s:%d",
            self.access.server_uid,
            self.host,
            self.client_port,
            self.host,
            self.worker_port,
        )
        # freeze everything allocated so far (including a restored journal's
        # task graph) out of the GC generations: old-gen collections then
        # never re-traverse startup state mid-tick
        if not self.memory_transport:
            gc.collect()
            gc.freeze()
        return self.access

    # --- memory transport (deterministic simulator) ---------------------
    def accept_worker(self, reader, writer) -> "asyncio.Task":
        """Inject a worker connection over an in-memory stream pair —
        the memory-transport equivalent of a TCP accept on the worker
        port.  Runs the REAL connection handler (auth handshake,
        register/reattach, sender + recv loops)."""
        return self._track_conn(self._handle_worker_conn(reader, writer))

    def accept_client(self, reader, writer) -> "asyncio.Task":
        """Inject a client connection (memory-transport equivalent of a
        TCP accept on the client port; in-loop plane)."""
        return self._track_conn(self._handle_client_conn(reader, writer))

    def _track_conn(self, coro) -> "asyncio.Task":
        task = asyncio.get_running_loop().create_task(coro)
        self._conn_tasks.add(task)
        task.add_done_callback(self._conn_tasks.discard)
        return task

    async def run_until_stopped(self) -> None:
        await self._stop_event.wait()
        await self.shutdown()

    def stop(self) -> None:
        self._stop_event.set()

    async def shutdown(self) -> None:
        if getattr(self, "autoalloc", None) is not None:
            self.autoalloc.stop()
            # in-flight qdel/scancel calls finish before the process
            # exits (a lost cancel = a leaked cluster job the journal
            # already believes cancelled)
            await self.autoalloc.drain_background()
        if self._watcher is not None:
            # peer-successor mode: shards this process promoted into are
            # full Servers of their own — stop them with us
            await self._watcher.shutdown()
        if not self.fenced:
            for wid in list(self._worker_conns):
                self.comm.send_stop(wid)
            await asyncio.sleep(0.05)
        # a FENCED instance must NOT stop its workers: they are the
        # promoted successor's fleet now — closing the connections below
        # makes them reconnect (and reattach) to it, a `stop` op would
        # kill them unconditionally
        for t in self._tasks:
            t.cancel()
        for t in list(self._client_tasks):
            t.cancel()
        for t in list(self._conn_tasks):
            t.cancel()
        for srv in self._servers:
            srv.close()
        if self.ingest_plane is not None:
            self.ingest_plane.stop()
        if self._metrics_server is not None:
            self._metrics_server.close()
        if self._metrics_hook is not None:
            REGISTRY.remove_collect_hook(self._metrics_hook)
        if self._profiler_started:
            profiler.stop_profiler()
            self._profiler_started = False
        for conn in self._worker_conns.values():
            conn.close()
        self.sendpool.stop()
        # drain + join the commit thread, then close the appender; a
        # plane that failed to drain keeps the appender open rather
        # than closing the file under a still-writing thread
        plane_drained = self.jplane.stop() if self.jplane is not None \
            else True
        if self.journal is not None and plane_drained:
            self.journal.close()
        if self.lease is not None:
            # clean stop: retire the lease so failover watchers never
            # promote a successor for a deliberately-stopped shard. A
            # FENCED instance skips this implicitly (release() refuses to
            # delete a lease it no longer owns).
            self.lease.release()
        # a clean stop retires the hq-current symlink so clients see "no
        # server" instead of a dead address (reference server stop removes
        # the symlink; test_server.py delete_symlink_after_server_stop).
        # Only if it still points at THIS instance — a newer server owns it
        # otherwise.
        link = self.server_dir / serverdir.CURRENT_LINK
        try:
            instance_dir = getattr(self, "_instance_dir", None)
            if (
                instance_dir is not None
                and link.is_symlink()
                and (self.server_dir / os.readlink(link)).resolve()
                == instance_dir.resolve()
            ):
                link.unlink()
        except OSError:
            pass  # cleanup is best-effort; a dead link is still harmless

    # --- federation (ISSUE 11) ------------------------------------------
    def federation_server_kwargs(self) -> dict:
        """The config subset a promoted sibling Server clones from this
        one (FailoverWatcher in peer-successor mode). Ports and keys are
        NOT cloned — a successor publishes a fresh access record and the
        reconnect machinery re-reads it. Keep in lockstep with the
        standby path's server_kwargs in cli._run_standby."""
        return dict(
            scheduler=self.scheduler_kind,
            schedule_min_delay=self.schedule_min_delay,
            journal_fsync=self.journal_fsync,
            journal_flush_period=self.journal_flush_period,
            journal_compact_interval=self.journal_compact_interval,
            journal_compact_threshold=self.journal_compact_threshold,
            journal_salvage=self.journal_salvage,
            heartbeat_timeout_factor=self.heartbeat_timeout_factor,
            reattach_timeout=self.reattach_timeout,
            idle_timeout=self.idle_timeout,
            client_plane=self.client_plane,
            journal_plane=self.journal_plane,
            fanout_senders=self.fanout_senders,
            policy_file=self.policy_file,
            lazy_array_threshold=(
                self.lazy_array_threshold
                if self.lazy_array_threshold < (1 << 62) else 0
            ),
        )

    def _apply_job_id_partition(self) -> None:
        """Pin the job-id allocator to this shard's congruence class:
        shard k of N allocates ids with (id - 1) % N == k, so shards
        never collide and a job id alone routes a client. Applied after
        the journal restore — the restored watermark is carried into the
        strided counter."""
        if self.shard_count <= 1:
            return
        counter = self.jobs.job_id_counter
        from hyperqueue_tpu.ids import IdCounter

        base_count = self.shard_count
        if self.federation_root is not None:
            fed = serverdir.load_federation(self.federation_root)
            if fed:
                base_count = int(fed.get("base_shard_count",
                                         fed["shard_count"]))
        if self.shard_id >= base_count:
            # shard added online (ISSUE 17): the modulo classes are frozen
            # at base_shard_count, so this shard allocates from its
            # reserved high id block instead — the id alone still routes
            from hyperqueue_tpu.utils.ownership import added_shard_block

            lo, _hi = added_shard_block(self.shard_id, base_count)
            blocked = IdCounter(start=lo + 1, stride=1)
            blocked.ensure_above(counter.peek() - 1)
            self.jobs.job_id_counter = blocked
            return
        strided = IdCounter(
            start=self.shard_id + 1, stride=base_count
        )
        strided.ensure_above(counter.peek() - 1)
        self.jobs.job_id_counter = strided

    async def _lease_renew_loop(self) -> None:
        """Renew this shard's lease on ~timeout/3; a renewal that finds a
        successor's claim means this instance was presumed dead and has
        been FENCED — stop immediately rather than keep a second
        scheduler + journal appender alive."""
        interval = max(self.lease.timeout / 3.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            try:
                ok = self.lease.renew()
            except OSError as e:
                # a transient FS error must not fence a healthy shard;
                # the NEXT renewal either succeeds or the staleness clock
                # runs out honestly
                logger.warning("lease renew failed (%s); retrying", e)
                continue
            if not ok:
                claim = self.lease.read() or {}
                logger.critical(
                    "shard %d lease claimed by successor %r (epoch %s); "
                    "this instance is fenced — stopping",
                    self.shard_id, claim.get("owner"), claim.get("epoch"),
                )
                self.fenced = True
                self.stop()
                return

    def _federation_block(self) -> dict | None:
        """The federation section of `hq server info`/`stats` (None on a
        standalone server)."""
        if self.federation_root is None:
            return None
        lease = (self.lease.read() if self.lease else None) or {}
        borrowed = sum(
            1
            for w in self.core.workers.values()
            if getattr(w.configuration, "lent_from", -1) >= 0
        )
        age = self.lease.age_seconds() if self.lease else None
        return {
            "shard_id": self.shard_id,
            "shard_count": self.shard_count,
            "partition": (
                f"(job_id - 1) % {self.shard_count} == {self.shard_id}"
            ),
            "lease_owner": lease.get("owner"),
            "lease_epoch": lease.get("epoch"),
            "lease_age_seconds": (
                round(age, 3) if age is not None else None
            ),
            "promoted": self.promoted,
            "fenced": self.fenced,
            "workers_lent": self.workers_lent_total,
            "workers_borrowed": borrowed,
            "jobs_migrated_out": len(self.migrated_out),
            "jobs_migrating_out": len(self.migrating_out),
            "jobs_migrated_in": len(self.migrations_in),
        }

    # --- health plane (ISSUE 18) ----------------------------------------
    async def _slo_loop(self) -> None:
        """Periodic SLO evaluation (utils/slo.py): judge the metrics
        registry on sliding windows and JOURNAL every alert transition —
        firing/resolved ride the subscribe plane and the FleetFeed like
        any other event, and a restored server re-derives alert state
        from fresh windows rather than trusting stale ones."""
        while True:
            await asyncio.sleep(self.slo.interval)
            for transition in self.slo.evaluate():
                self.emit_event("slo-alert", transition)

    def _probe_healthz(self) -> tuple[bool, dict]:
        """Liveness: the probe answering at all IS the signal (it runs
        on the reactor loop — a wedged loop cannot reply). Only a fatal
        journal-plane death marks a live process unhealthy: the process
        exists but has lost its durability guarantee."""
        if self.jplane is not None and self.jplane._thread is not None \
                and not self.jplane._thread.is_alive():
            return False, {"reason": "journal plane dead"}
        return True, {"uptime": round(clock.now() - self.started_at, 3)}

    def _probe_readyz(self) -> tuple[bool, dict]:
        """Readiness: should an orchestrator (or the standby/rebalancer)
        route work here? Every check is O(1) reads of live state."""
        checks: dict[str, str] = {}
        ok = True
        if self.jplane is not None:
            alive = (
                self.jplane._thread is not None
                and self.jplane._thread.is_alive()
            )
            checks["journal_plane"] = "ok" if alive else "dead"
            ok = ok and alive
        if self.lease is not None:
            age = self.lease.age_seconds()
            held = (
                not self.fenced
                and age is not None
                and age < self.lease_timeout
            )
            checks["lease"] = (
                "ok" if held else
                ("fenced" if self.fenced else "stale")
            )
            ok = ok and held
        armed = bool(self.model.stats().get("armed"))
        checks["solver"] = "ok" if armed else "degraded"
        ok = ok and armed
        if self.ingest_plane is not None:
            depth = len(self.ingest_plane.handoff)
            below = depth < self.ingest_handoff_max
            checks["ingest"] = (
                "ok" if below else f"backpressure ({depth})"
            )
            ok = ok and below
        paging = self.slo.paging_alerts()
        checks["slo"] = (
            "ok" if not paging else
            "paging: " + ",".join(a["alert"] for a in paging)
        )
        ok = ok and not paging
        return ok, {"checks": checks}

    async def _client_accounting(self, msg: dict) -> dict:
        """Usage ledger query (`hq job accounting` / `hq fleet
        accounting`): per-job rows for an explicit selection, or the
        per-label rollup when none is given."""
        job_ids = msg.get("job_ids")
        out: dict = {"op": "accounting", "shard": self.shard_id}
        if job_ids:
            report = self.accounting.job_report(
                [int(j) for j in job_ids]
            )
            # a LIST (each row carries its job id): the federated client
            # splits a selector across shards and merges responses by
            # list concatenation — a dict keyed by job id would silently
            # keep only the first shard's rows
            out["jobs"] = [
                {"job": j, **row} for j, row in sorted(report.items())
            ]
        else:
            out["rollup"] = self.accounting.rollup()
        return out

    async def _client_alerts(self, msg: dict) -> dict:
        """`hq alerts`: currently-firing SLO alerts + recent transitions
        (fan-out across shards happens client-side, like server_stats)."""
        return {"op": "alerts", "shard": self.shard_id,
                **self.slo.alerts()}

    def _alert_badge(self) -> dict:
        return self.slo.badge()

    async def _client_worker_lend(self, msg: dict) -> dict:
        """Lend an IDLE worker to another shard: order it to re-register
        there (federation coordinator RPC). No task state moves — that is
        the whole point: elasticity without migration."""
        wid = int(msg["worker_id"])
        target = int(msg["to_shard"])
        if self.federation_root is None:
            return {"op": "error", "message": "not a federated server"}
        if not (0 <= target < self.shard_count) or target == self.shard_id:
            return {"op": "error", "message": f"bad target shard {target}"}
        worker = self.core.workers.get(wid)
        if worker is None:
            return {"op": "error", "message": f"worker {wid} not found"}
        if worker.assigned_tasks or worker.prefilled_tasks:
            # never lend a busy worker: its running tasks belong to THIS
            # shard's journal and must finish (or reattach) here
            return {"op": "worker_lend", "lent": False, "reason": "busy"}
        if worker.configuration.on_server_lost != "reconnect":
            # a lent worker must survive the borrower dying (reattach to
            # its successor) — any other policy would make the lend a
            # one-way trip to a worker exit on the first hiccup
            return {"op": "worker_lend", "lent": False, "reason": "policy"}
        self._lent_workers[wid] = target
        self.workers_lent_total += 1
        self.comm.send_redirect(wid, target, self.shard_id)
        logger.info(
            "lending idle worker %d to shard %d", wid, target,
            extra={"worker": wid},
        )
        return {"op": "worker_lend", "lent": True, "to_shard": target}

    # --- live job migration (ISSUE 17) ----------------------------------
    def _migration_barrier(self) -> None:
        """Durability barrier for the migration protocol: the journaled
        migration record must be ON DISK before the RPC reply leaves —
        kill -9 right after the ack must replay to the same decision."""
        if self.journal is None:
            return
        if self.jplane is not None:
            self.jplane.barrier(sync=True)
        else:
            if self.journal.in_batch:
                self.journal.commit_batch()
            self.journal.flush(sync=True)

    def _owned_elsewhere(self, job_id, rid=None) -> dict | None:
        """wrong-shard / migrating guard: an error dict when this shard
        no longer (or not currently) serves the job, else None. `code`
        lets clients tell a redirect (wrong-shard, with the owner hint)
        from a transient seal (migrating — retry here shortly)."""
        if job_id is None:
            return None
        owner = self.migrated_out.get(job_id)
        if owner is not None:
            err = {"op": "error", "code": "wrong-shard", "owner": owner,
                   "message": f"job {job_id} migrated to shard {owner}"}
            if rid is not None:
                err["rid"] = rid
            return err
        if job_id in self.migrating_out:
            err = {"op": "error", "code": "migrating",
                   "message": f"job {job_id} is migrating; retry shortly"}
            if rid is not None:
                err["rid"] = rid
            return err
        return None

    def _guard_job_ids(self, job_ids) -> dict | None:
        """Job-op guard: redirect only when EVERY requested job moved
        (mixed batches fall through — absent jobs are simply omitted
        from the reply, exactly like unknown ids always were)."""
        guards = [self._owned_elsewhere(j) for j in job_ids]
        if guards and all(g is not None for g in guards):
            return guards[0]
        return None

    async def _client_migration_export(self, msg: dict) -> dict:
        """Phase 1 of a live migration (driver RPC): seal + drain the job
        and return a self-contained, versioned migration record.

        Sealing = pause (READY held, lazy chunks detached in chunk form,
        prefilled retracted) + RECALL of ASSIGNED/RUNNING tasks (resources
        released, worker's incarnation canceled, instance bumped — the
        fence). The `migration-out` journal record carries only {mig, to,
        fence}, NOT the record: a source crash after the barrier restores
        the job PAUSED, and a re-driven export rebuilds an equivalent
        record from that state — safe because the sealed job made no
        progress in between."""
        from hyperqueue_tpu.events import snapshot as snapshot_mod

        mig = str(msg.get("mig") or "")
        job_id = int(msg.get("job", 0))
        to_shard = int(msg.get("to", -1))
        if not mig:
            return {"op": "error", "message": "migration_export needs mig"}
        guard = self._owned_elsewhere(job_id)
        if guard is not None and guard.get("code") == "wrong-shard":
            return guard
        out = self.migrating_out.get(job_id)
        if out is not None and out.get("mig") != mig:
            return {"op": "error",
                    "message": f"job {job_id} is sealed by migration "
                               f"{out.get('mig')!r}, not {mig!r}"}
        job = self.jobs.jobs.get(job_id)
        if job is None:
            return {"op": "error", "message": f"unknown job {job_id}"}
        if out is None:
            reactor.pause_jobs(self.core, self.comm, [job_id])
            recall_ids = [
                make_task_id(job_id, info.job_task_id)
                for info in job.tasks.values()
                if info.status in ("waiting", "running")
            ]
            reactor.recall_tasks(self.core, self.comm, recall_ids)
            self.migrating_out[job_id] = {"mig": mig, "to": to_shard}
            fence = self._job_fence(job_id, job)
            self.emit_event(
                "migration-out",
                {"job": job_id, "mig": mig, "to": to_shard, "fence": fence},
            )
            self._migration_barrier()
        bodies: list = []
        body_index: dict = {}
        requests: list = []
        request_index: dict = {}
        record = {
            "version": 1,
            "mig": mig,
            "job": job_id,
            "from": self.shard_id,
            "to": to_shard,
            "fence": self._job_fence(job_id, job),
            "bodies": bodies,
            "requests": requests,
            "job_state": snapshot_mod.capture_job(
                self, job, bodies, body_index, requests, request_index
            ),
            # accrued usage rides the record (ISSUE 18): the destination
            # seeds it from the journaled migration-in, the source drops
            # its row at the migration-out-done tombstone — the ledger
            # moves exactly once, with the job
            "accounting": self.accounting.export_job(job_id),
        }
        return {"op": "migration_export", "mig": mig, "record": record}

    def _job_fence(self, job_id: int, job) -> int:
        """Highest instance id this shard could have issued for the job:
        the destination floors every imported task AT it, so any late
        uplink from this (possibly SIGSTOP'd) shard's workers carries a
        strictly smaller instance id and is discarded over there."""
        fence = int(self.core.instance_fence_floor)
        for info in job.tasks.values():
            task = self.core.tasks.get(
                make_task_id(job_id, info.job_task_id)
            )
            if task is not None:
                fence = max(fence, task.instance_id)
        return fence

    async def _client_migration_import(self, msg: dict) -> dict:
        """Phase 2: durably adopt a migration record. The `migration-in`
        journal record embeds the WHOLE record before any in-memory state
        changes — kill -9 after the barrier replays the import; kill
        before it leaves nothing, and the driver re-sends. Duplicate
        imports (re-driven migrations) ack dup instead of double-seeding
        — same exactly-once discipline as SubmitStream chunk replay."""
        rec = msg.get("record") or {}
        mig = str(msg.get("mig") or rec.get("mig") or "")
        job_id = rec.get("job_state", {}).get("id")
        if not mig or job_id is None:
            return {"op": "error", "message": "malformed migration record"}
        if mig in self.migrations_in or job_id in self.jobs.jobs:
            return {"op": "migration_import", "mig": mig, "dup": True}
        self.emit_event(
            "migration-in", {"job": job_id, "mig": mig, "record": rec}
        )
        self._apply_migration_record(rec)
        self.migrations_in[mig] = job_id
        self._migration_barrier()
        return {"op": "migration_import", "mig": mig, "dup": False}

    async def _client_migration_finalize(self, msg: dict) -> dict:
        """Phase 3 (post-commit): drop the sealed source copy, leaving a
        journaled tombstone for wrong-shard redirects. Idempotent — the
        driver may re-send after a crash on either side."""
        mig = str(msg.get("mig") or "")
        job_id = int(msg.get("job", 0))
        to_shard = int(msg.get("to", -1))
        if job_id in self.migrated_out or job_id not in self.jobs.jobs:
            return {"op": "migration_finalize", "mig": mig, "dup": True}
        self.emit_event(
            "migration-out-done",
            {"job": job_id, "mig": mig, "to": to_shard},
        )
        job = self.jobs.jobs.pop(job_id)
        for job_task_id in job.tasks:
            self.core.tasks.pop(make_task_id(job_id, job_task_id), None)
        self.core.paused_jobs.discard(job_id)
        self.core.paused_held.pop(job_id, None)
        self.core.lazy.forget_job(job_id)
        for uid in job.streams:
            self._stream_jobs.pop(uid, None)
        # job_wait callers must not hang on a job that left: wake them —
        # their follow-up job_info gets the wrong-shard redirect
        for event in self._job_waiters.pop(job_id, ()):
            event.set()
        self.migrating_out.pop(job_id, None)
        self.migrated_out[job_id] = to_shard
        self._migration_barrier()
        return {"op": "migration_finalize", "mig": mig, "dup": False}

    def _apply_migration_record(self, rec: dict) -> None:
        """Install an exported job into the LIVE server (the in-memory
        twin of restore's migration-in replay — events/restore.py
        _seed_migration_record covers the post-crash path). Lazy chunks
        re-register in chunk form: importing a 1M-task lazy array is
        O(chunks), never O(tasks)."""
        jd = rec["job_state"]
        bodies = rec.get("bodies") or []
        requests = rec.get("requests") or []
        job_id = jd["id"]
        # a job can migrate BACK to a shard that once exported it: the
        # old wrong-shard tombstone must die with the import, or this
        # shard keeps redirecting requests for a job it owns again
        self.migrating_out.pop(job_id, None)
        self.migrated_out.pop(job_id, None)
        job = self.jobs.create_job(
            name=jd["name"],
            submit_dir=jd["submit_dir"],
            max_fails=jd["max_fails"],
            is_open=jd["open"],
            job_id=job_id,
        )
        job.submitted_at = jd["submitted_at"]
        job.cancel_reason = jd["cancel_reason"]
        job.submits = list(jd["submits"])
        status_of: dict[int, str] = {}
        for tid, status, error, finished_at, started_at, submitted_at in (
            jd["done"]
        ):
            self.jobs.attach_task(job, tid)
            info = job.tasks[tid]
            info.submitted_at = submitted_at
            info.status = status
            info.error = error
            info.finished_at = finished_at
            if started_at:
                info.started_at = started_at
            job.counters[status] += 1
            status_of[tid] = status
        for uid, s in (jd.get("streams") or {}).items():
            job.streams[uid] = {
                "applied": set(s["applied"]), "sealed": bool(s["sealed"]),
            }
            if not s["sealed"]:
                job.open_streams += 1
            self._stream_jobs[uid] = job_id
        fence = max(
            int(rec.get("fence", 0)), int(self.core.instance_fence_floor)
        )
        new_tasks = []
        for t in jd["pending"]:
            tid = t["id"]
            self.jobs.attach_task(job, tid)
            job.tasks[tid].submitted_at = t["submitted_at"]
            deps = tuple(
                make_task_id(job_id, d)
                for d in t.get("deps", ())
                if status_of.get(d) != "finished"
            )
            if any(
                status_of.get(d) in ("failed", "canceled")
                for d in t.get("deps", ())
            ):
                job.tasks[tid].status = "canceled"
                job.counters["canceled"] += 1
                continue
            task = Task(
                task_id=make_task_id(job_id, tid),
                rq_id=self.core.intern_rqv(rqv_from_wire(
                    requests[t["rq"]], self.core.resource_map
                )),
                priority=(int(t.get("priority", 0)),
                          encode_sched_priority(job_id)),
                body=bodies[t["b"]],
                entry=t.get("entry"),
                deps=deps,
                crash_limit=int(t.get("crash_limit", 5)),
            )
            task.crash_counter = int(t.get("crashes", 0))
            # monotonic across the move: floor at the source's fence,
            # then bump past it — the source's recalled incarnations
            # (and a SIGSTOP'd source's late uplinks) are all stale here
            task.instance_id = int(t.get("instance", 0))
            task.fence_instance(fence)
            new_tasks.append(task)
        if new_tasks:
            reactor.on_new_tasks(self.core, self.comm, new_tasks)
        for spec in jd.get("lazy") or ():
            rqv = rqv_from_wire(
                requests[spec["rq"]], self.core.resource_map
            )
            chunk = ArrayChunk(
                job_id=job_id,
                rq_id=self.core.intern_rqv(rqv),
                priority=(int(spec.get("priority", 0)),
                          encode_sched_priority(job_id)),
                body=bodies[spec["b"]],
                crash_limit=int(spec.get("crash_limit", 5)),
                id_range=(
                    tuple(spec["id_range"]) if "id_range" in spec else None
                ),
                ids=(
                    [int(i) for i in spec["ids"]]
                    if "ids" in spec else None
                ),
                entries=spec.get("entries"),
                submitted_at=float(spec.get("submitted_at") or 0.0),
                ready_at=float(spec.get("ready_at") or 0.0),
                trace=spec.get("trace"),
            )
            self.core.lazy.register(self.core, chunk)
            for dead in spec.get("dead") or ():
                self.core.lazy.drop_id(self.core, job_id, dead)
        self.check_job_completion(job_id)
        self.comm.ask_for_scheduling()

    # --- metrics --------------------------------------------------------
    def _collect_metrics(self) -> None:
        """Refresh cluster-state gauges at scrape time (utils/metrics.py
        collect hook): nothing here runs on a hot path, and everything is
        O(workers + queues), never O(tasks) — walking a million-task map
        per scrape would make the scrape itself a perturbation."""
        core = self.core
        REGISTRY.gauge(
            "hq_workers_connected", "workers currently registered"
        ).set(len(core.workers))
        REGISTRY.gauge(
            "hq_tasks_known", "tasks in the server core (all states)"
        ).set(len(core.tasks))
        REGISTRY.gauge(
            "hq_tasks_ready_queued", "single-node tasks in the ready queues"
        ).set(core.queues.total_ready())
        REGISTRY.gauge(
            "hq_tasks_mn_queued", "multi-node gang tasks awaiting workers"
        ).set(len(core.mn_queue))
        REGISTRY.gauge(
            "hq_jobs_known", "jobs known to the server"
        ).set(len(self.jobs.jobs))
        REGISTRY.gauge(
            "hq_reattach_pending_tasks",
            "restored maybe-running tasks held for worker reattach",
        ).set(len(self.reattach_pending))
        # event stream backpressure: listeners and the deepest unsent queue
        REGISTRY.gauge(
            "hq_event_listeners", "attached event-stream clients"
        ).set(len(self._event_listeners))
        # subscription plane (subscribe RPC) + per-task trace store health
        REGISTRY.gauge(
            "hq_event_subscribers", "attached subscribe-RPC consumers"
        ).set(len(self._subscribers))
        REGISTRY.gauge(
            "hq_sub_queue_depth",
            "deepest per-subscriber backlog of undelivered events",
        ).set(
            max((s.queue.qsize() for s in self._subscribers), default=0)
        )
        # ingest plane + lazy store: depth/client gauges are read here at
        # scrape time (single-writer rule: the counters are bumped by the
        # reactor/ingest threads, never from the scrape)
        lazy_stats = core.lazy.stats()
        REGISTRY.gauge(
            "hq_tasks_lazy",
            "unmaterialized lazy array tasks (registered as chunks, "
            "per-task records deferred to dispatch)",
        ).set(lazy_stats["unmaterialized"])
        if self.jplane is not None:
            REGISTRY.gauge(
                "hq_journal_plane_depth",
                "journal records enqueued to the commit thread, not yet "
                "committed (sustained growth = the disk is the bottleneck)",
            ).set(self.jplane.depth())
        REGISTRY.gauge(
            "hq_fanout_plane_senders",
            "sender-pool threads running the downlink encode+seal "
            "(--fanout-senders; 0 = inline on the owning loop)",
        ).set(self.fanout_senders)
        if self.ingest_plane is not None:
            REGISTRY.gauge(
                "hq_ingest_handoff_depth",
                "decoded client messages queued between the connection "
                "plane and the reactor",
            ).set(len(self.ingest_plane.handoff))
            REGISTRY.gauge(
                "hq_ingest_clients",
                "client connections held by the connection plane",
            ).set(len(self.ingest_plane.clients))
        if self.federation_root is not None:
            fed = self._federation_block() or {}
            REGISTRY.gauge(
                "hq_federation_lease_age_seconds",
                "seconds since this shard's lease was last renewed "
                "(staleness past the timeout makes the shard claimable)",
            ).set(fed.get("lease_age_seconds") or 0.0)
            REGISTRY.counter(
                "hq_federation_workers_lent_total",
                "idle workers this shard ordered to re-register with "
                "another shard (federation coordinator lending)",
            ).set_total(self.workers_lent_total)
            REGISTRY.gauge(
                "hq_federation_workers_borrowed",
                "currently-registered workers lent to this shard by a "
                "sibling (register carried lent_from)",
            ).set(fed.get("workers_borrowed") or 0)
            REGISTRY.counter(
                "hq_federation_jobs_moved_total",
                "jobs this shard finished migrating out (ownership "
                "tombstone journaled; live migration, ISSUE 17)",
            ).set_total(len(self.migrated_out))
            try:
                from hyperqueue_tpu.utils.ownership import OwnershipStore

                REGISTRY.gauge(
                    "hq_federation_ownership_epoch",
                    "last epoch in the federation ownership log (the "
                    "fencing token of the migration protocol)",
                ).set(OwnershipStore(self.federation_root).current_epoch())
            except OSError:
                pass
        # usage accounting rollup (ISSUE 18): per-label resource-time
        # totals from the ledger, rebuilt each scrape so labels whose jobs
        # all migrated away vanish instead of lingering at stale values
        rollup = self.accounting.rollup()
        acct_jobs = REGISTRY.gauge(
            "hq_accounting_jobs",
            "jobs with accrued usage in the ledger, by job label",
            labels=("label",), max_series=256,
        )
        acct_task = REGISTRY.counter(
            "hq_accounting_task_seconds_total",
            "wall-clock task execution seconds accrued, by job label",
            labels=("label",), max_series=256,
        )
        acct_cpu = REGISTRY.counter(
            "hq_accounting_cpu_seconds_total",
            "cpu-seconds accrued (amount x run seconds), by job label",
            labels=("label",), max_series=256,
        )
        acct_gpu = REGISTRY.counter(
            "hq_accounting_gpu_seconds_total",
            "gpu-seconds accrued (amount x run seconds), by job label",
            labels=("label",), max_series=256,
        )
        acct_wait = REGISTRY.counter(
            "hq_accounting_wait_seconds_total",
            "ready -> running wait seconds accrued, by job label",
            labels=("label",), max_series=256,
        )
        acct_crash = REGISTRY.counter(
            "hq_accounting_crash_retries_total",
            "crash-charged task retries, by job label",
            labels=("label",), max_series=256,
        )
        for metric in (acct_jobs, acct_task, acct_cpu, acct_gpu,
                       acct_wait, acct_crash):
            metric.clear()
        for label, agg in rollup["labels"].items():
            acct_jobs.labels(label).set(agg["jobs"])
            acct_task.labels(label).set_total(agg["task_seconds"])
            acct_cpu.labels(label).set_total(agg["cpu_seconds"])
            acct_gpu.labels(label).set_total(agg["gpu_seconds"])
            acct_wait.labels(label).set_total(agg["wait_seconds"])
            acct_crash.labels(label).set_total(agg["crash_retries"])
        trace_stats = core.traces.stats()
        REGISTRY.gauge(
            "hq_task_traces", "tasks with spans in the bounded trace store"
        ).set(trace_stats["tasks"])
        REGISTRY.counter(
            "hq_task_trace_evictions_total",
            "task traces evicted from the bounded store",
        ).set_total(trace_stats["evictions"])
        REGISTRY.gauge(
            "hq_event_stream_depth",
            "deepest per-listener backlog of undelivered events",
        ).set(
            max((q.qsize() for q in self._event_listeners), default=0)
        )
        REGISTRY.counter(
            "hq_events_emitted_total", "server events emitted (journal seq)"
        ).set_total(self._event_seq)
        # solver watchdog: adopt its externally-tracked monotonic counters
        wd = self.model.stats()
        REGISTRY.gauge(
            "hq_solver_armed",
            "1 while the primary solver is armed, 0 while degraded to the "
            "host-greedy fallback",
        ).set(1.0 if wd.get("armed") else 0.0)
        for key in ("failures", "timeouts", "degraded_ticks", "rearms",
                    "skipped_ticks"):
            REGISTRY.counter(
                f"hq_solver_{key}_total",
                f"solver watchdog {key.replace('_', ' ')} "
                "(scheduler/watchdog.py)",
            ).set_total(wd.get(key, 0))
        if self.journal_path is not None:
            # durability-plane gauges: both are one stat() each — the
            # scrape must never walk the journal
            try:
                journal_bytes = float(self.journal_path.stat().st_size)
            except OSError:
                journal_bytes = 0.0
            REGISTRY.gauge(
                "hq_journal_size_bytes",
                "event journal file size (compaction bounds this)",
            ).set(journal_bytes)
            from hyperqueue_tpu.events import snapshot as snapshot_mod

            snap_stats = snapshot_mod.snapshot_stats(self.journal_path)
            REGISTRY.gauge(
                "hq_snapshot_age_seconds",
                "age of the newest journal snapshot (-1 = no snapshot yet)",
            ).set(
                snap_stats["age_seconds"]
                if snap_stats["age_seconds"] is not None
                else -1.0
            )
        cache = core.tick_cache.counters()
        for key in ("full_rebuilds", "incremental_syncs"):
            REGISTRY.counter(
                f"hq_tick_cache_{key}_total",
                f"tick snapshot cache {key.replace('_', ' ')}",
            ).set_total(cache.get(key, 0))
        # solve backend + device-resident state (parallel/resident.py):
        # which backend the last solve ran, how many bytes the device path
        # uploaded (full + delta), and how many rows were dirty last tick
        resident = {}
        get_resident = getattr(self.model, "resident_stats", None)
        if get_resident is not None:
            try:
                resident = get_resident()
            except Exception:  # noqa: BLE001 - metrics must never break
                resident = {}
        backend_gauge = REGISTRY.gauge(
            "hq_solve_backend",
            "1 for the backend the last solve ran on "
            "(host-native/host-numpy/device-jax/device-sharded)",
            labels=("backend",), max_series=8,
        )
        backend_gauge.clear()
        if resident.get("backend"):
            backend_gauge.labels(resident["backend"]).set(1.0)
        if resident:
            REGISTRY.counter(
                "hq_device_upload_bytes_total",
                "bytes uploaded to the solve device (full uploads + "
                "dirty-row deltas + replicated-input placements)",
            ).set_total(resident.get("upload_bytes_total", 0))
            REGISTRY.gauge(
                "hq_tick_dirty_rows",
                "worker rows the device path uploaded last solve "
                "(delta size; W on a full upload)",
            ).set(resident.get("dirty_rows_last", 0))
            for key in ("full_uploads", "delta_uploads", "invalidations",
                        "rep_cache_hits"):
                REGISTRY.counter(
                    f"hq_resident_{key}_total",
                    f"device-resident tick state {key.replace('_', ' ')}",
                ).set_total(resident.get(key, 0))
        pipeline = core.tick_pipeline
        if pipeline is not None:
            ps = pipeline.stats()
            REGISTRY.gauge(
                "hq_tick_pipeline_depth",
                "solves currently in flight in the async tick pipeline "
                "(0 or 1)",
            ).set(ps["depth"])
            for key in ("dispatched", "mapped", "drains"):
                REGISTRY.counter(
                    f"hq_tick_pipeline_{key}_total",
                    f"async tick pipeline: solves {key}",
                ).set_total(ps[key])
        # per-worker gauges: the server's own accounting, plus whatever
        # gauges/counters the worker piggybacked on its last overview
        # message (cluster-wide re-export under a `worker` label)
        assigned = REGISTRY.gauge(
            "hq_worker_assigned_tasks",
            "tasks with accounted resources on each worker",
            labels=("worker",), max_series=4096,
        )
        prefilled = REGISTRY.gauge(
            "hq_worker_prefilled_tasks",
            "tasks queued on each worker beyond current capacity",
            labels=("worker",), max_series=4096,
        )
        assigned.clear()  # departed workers' series must not linger
        prefilled.clear()
        # piggybacked metric series are rebuilt from scratch each scrape so
        # a departed worker's samples vanish with it
        for name in self._piggyback_names:
            metric = REGISTRY.get(name)
            if metric is not None:
                metric.clear()
        self._piggyback_names = set()
        piggybacked = self._piggyback_names
        for w in core.workers.values():
            assigned.labels(w.worker_id).set(len(w.assigned_tasks))
            prefilled.labels(w.worker_id).set(len(w.prefilled_tasks))
            for sample in w.last_metrics:
                name = sample.get("name", "")
                if not name.startswith("hq_worker_"):
                    continue  # only the worker-runtime namespace fans out
                labels = sample.get("labels") or {}
                label_names = (*sorted(labels), "worker")
                make = (
                    REGISTRY.counter
                    if sample.get("type") == "counter"
                    else REGISTRY.gauge
                )
                try:
                    metric = make(
                        name, sample.get("help", ""),
                        labels=label_names, max_series=4096,
                    )
                except ValueError:
                    continue  # type conflict with an existing metric
                if metric.label_names != label_names:
                    continue  # conflicting shape from an older worker
                piggybacked.add(name)
                series = metric.labels(
                    *(labels[k] for k in sorted(labels)), w.worker_id
                )
                if sample.get("type") == "counter":
                    series.set_total(sample.get("value", 0.0))
                else:
                    series.set(sample.get("value", 0.0))

    # control-plane event kinds mirrored into the flight recorder so a
    # dump shows what the cluster DID around each tick; per-task kinds are
    # deliberately excluded (a million-task job must not flush the ring)
    _FLIGHT_EVENT_KINDS = (
        "worker-", "job-submitted", "job-completed", "job-opened",
        "job-closed", "job-paused", "job-resumed", "alloc-", "server-uid",
    )

    # --- events out ----------------------------------------------------
    def _journal_group_commit(self):
        """Context manager: buffer journal writes inside the block and
        commit them as one append (+ one fsync under `--journal-fsync
        always`) at exit. The block MUST NOT await — group commit is
        correct only while no external effect can run before the commit."""
        journal = self.journal
        if journal is None or journal.in_batch or self.jplane is not None:
            # with the journal plane on, the commit thread owns batching
            # (emit_event enqueues; visibility rides the watermark)
            return _NOOP_BATCH
        return _journal_batch(
            journal,
            fsync=self.journal_fsync == "always",
            flush=not self.journal_flush_period,
        )

    def emit_event(self, kind: str, payload: dict) -> None:
        if (
            self.core.flight.enabled
            and kind.startswith(self._FLIGHT_EVENT_KINDS)
            and not kind.startswith("worker-overview")
        ):
            self.core.flight.record_event(
                kind,
                {k: v for k, v in payload.items() if k != "desc"},
            )
        if (
            self.journal is None
            and not self._event_listeners
            and not self._subscribers
        ):
            # nobody persists or streams events; the accounting fold
            # still consumes its kinds (journal-less sim/dev servers)
            if kind in ACCOUNTED_KINDS:
                self.accounting.observe(
                    kind,
                    {"time": clock.now(), "event": kind, **payload},
                )
            return
        record = {"time": clock.now(), "seq": self._event_seq,
                  "event": kind, **payload}
        self._event_seq += 1
        # fold BEFORE the append, on the exact record the journal gets:
        # snapshot capture runs synchronously between emits, so a captured
        # ledger corresponds exactly to `seq < watermark` — live fold and
        # kill -9 replay are bit-identical by construction
        self.accounting.observe(kind, record)
        if self.jplane is not None:
            # journal plane (server/journal_plane.py): the append is an
            # enqueue; the commit thread group-writes (+ flushes/fsyncs
            # per policy) off the loop, and deliveries to listeners/
            # subscribers are released only at the durability watermark
            self.jplane.append(record)
        elif self.journal is not None:
            self.journal.write(record)
            # default: flush to the OS on every event, so a crashed server
            # process restores everything (fsync-against-OS-crash happens on
            # close and `hq journal flush`). With --journal-flush-period the
            # periodic loop flushes instead (reference 30 s default).
            # --journal-fsync always additionally fsyncs per event. Inside
            # a group-commit block the batch commit does all of this once
            # at block exit instead.
            if not self.journal.in_batch:
                if self.journal_fsync == "always":
                    self.journal.flush(sync=True)
                elif not self.journal_flush_period:
                    self.journal.flush()
        if chaos.ACTIVE:
            # kill-at-event-K injection sits AFTER the journal write+flush:
            # a chaos test killing the server here proves exactly what the
            # configured flush/fsync policy persisted. A pending group
            # commit (or the journal plane's in-flight batch) gets a
            # durability barrier first so the guarantee holds at the
            # injection point too.
            if self.jplane is not None:
                self.jplane.barrier(sync=self.journal_fsync == "always")
            elif self.journal is not None and self.journal.in_batch:
                self.journal.flush(sync=self.journal_fsync == "always")
            chaos.fire(
                "server.event", event=kind, shard=self.shard_id, ctx=self
            )
        if self.jplane is not None and (
            self._event_listeners or self._subscribers
        ):
            self.jplane.when_durable(
                lambda r=record, k=kind: self._deliver_event(k, r)
            )
        else:
            self._deliver_event(kind, record)

    def _deliver_event(self, kind: str, record: dict) -> None:
        """Fan one journaled record out to event listeners and
        subscribers. With the journal plane on this runs at the
        durability watermark — a completion a subscriber sees is already
        as durable as the fsync policy promises."""
        for q in self._event_listeners:
            q.put_nowait(record)
        for sub in self._subscribers:
            if sub.dead:
                _SUB_EVENTS_DROPPED.inc()
                continue
            if sub.prefixes and not kind.startswith(sub.prefixes):
                continue
            try:
                sub.queue.put_nowait(record)
            except asyncio.QueueFull:
                # slow consumer: drop IT, not the reactor's latency — its
                # streaming loop notices `dead` and closes the connection
                sub.dead = True
                sub.dropped += 1
                _SUBSCRIBERS_DROPPED.inc()
                _SUB_EVENTS_DROPPED.inc()

    # --- durability-before-visibility gating ---------------------------
    def reply_visible(self, channel, frame: dict) -> None:
        """Queue a client reply, released only once every event emitted
        so far is committed (journal plane) — the watermark gate that
        keeps an ack from outrunning the durability it implies. Without
        the plane the synchronous group-commit block already provides
        the ordering, so the reply goes straight out."""
        if self.jplane is not None:
            self.jplane.when_durable(lambda: channel.reply(frame))
        else:
            channel.reply(frame)

    async def _visibility_barrier(self) -> None:
        """Await the durability watermark (legacy in-loop client plane's
        equivalent of reply_visible)."""
        if self.jplane is None:
            return
        fut = asyncio.get_running_loop().create_future()
        self.jplane.when_durable(
            lambda: fut.done() or fut.set_result(None)
        )
        await fut

    def schedule_cancel(self, task_ids: list[int]) -> None:
        reactor.on_cancel_tasks(self.core, self.comm, self.events, task_ids)

    def _seal_job_streams(self, job) -> None:
        """Force-seal a job's chunk streams AND journal the seal (a
        forced seal has no `last` chunk event to replay from)."""
        sealed = job.seal_streams()
        if sealed:
            self.emit_event(
                "job-streams-sealed", {"job": job.job_id, "uids": sealed}
            )

    def check_job_completion(self, job_id: int) -> None:
        job = self.jobs.jobs.get(job_id)
        if job is None:
            return
        if job.is_terminated():
            self.emit_event(
                "job-completed",
                {"job": job_id, "status": job.status(),
                 "cancel_reason": job.cancel_reason},
            )
            # a terminated job's streams are dead: release their uid
            # mappings and applied-index sets (a long-lived server must
            # not grow per-stream state forever — retried chunks now get
            # a "sealed" error instead of a dup ack, which is fine: the
            # retrying client's stream already failed terminally)
            for uid, stream in job.streams.items():
                self._stream_jobs.pop(uid, None)
                stream["applied"] = set()
        # waiters are satisfied when every task submitted SO FAR is terminal —
        # for open jobs that is the useful "wait" semantics (the job itself
        # terminates only when closed)
        if job.all_tasks_done():
            for event in self._job_waiters.pop(job_id, []):
                event.set()

    # consecutive-crash budget per background loop before the server gives
    # up and stops (so clients fail fast instead of submitting into a
    # server that never schedules); a loop that then stays healthy for
    # LOOP_HEALTHY_SECS earns its budget back
    LOOP_CRASH_RESTARTS = 3
    LOOP_HEALTHY_SECS = 60.0

    def _spawn_loop(self, factory, _restarts: int = 0) -> "asyncio.Task":
        """Background loops must never die silently: an unhandled exception
        in an asyncio task is held unreported while the server keeps a
        reference — the server would turn into a zombie that accepts
        submits but never schedules. Log the crash loudly, restart the loop
        up to LOOP_CRASH_RESTARTS consecutive times, then stop the
        server."""
        started = clock.now()
        task = asyncio.create_task(factory())
        name = getattr(factory, "__name__", repr(factory))

        def _report(t: "asyncio.Task") -> None:
            if t.cancelled():
                return
            exc = t.exception()
            if exc is None:
                return
            logger.critical(
                "server background loop %s crashed", name, exc_info=exc,
            )
            if self._stop_event.is_set():
                # shutting down: a respawn would run against resources
                # shutdown() is already closing
                return
            restarts = (
                0 if clock.now() - started >= self.LOOP_HEALTHY_SECS
                else _restarts
            )
            if restarts < self.LOOP_CRASH_RESTARTS:
                logger.critical(
                    "restarting %s (attempt %d/%d)",
                    name, restarts + 1, self.LOOP_CRASH_RESTARTS,
                )
                self._tasks.append(self._spawn_loop(factory, restarts + 1))
            else:
                logger.critical(
                    "%s exceeded its restart budget; stopping the server",
                    name,
                )
                self.stop()

        task.add_done_callback(_report)
        return task

    # --- scheduler loop ------------------------------------------------
    async def _scheduler_loop(self) -> None:
        while True:
            await self.comm.scheduling_event.wait()
            await asyncio.sleep(self.schedule_min_delay)
            self.comm.scheduling_event.clear()
            t0 = time.perf_counter()
            n = reactor.schedule(self.core, self.comm, self.events, self.model)
            TRACER.record("scheduler/tick", time.perf_counter() - t0)
            # the tick runs synchronously on the loop: its duration IS the
            # solve plane's loop occupancy (stall watchdog included)
            self.note_plane("solve", time.perf_counter() - t0)
            if n:
                logger.debug(
                    "tick assigned %d tasks in %.2f ms",
                    n,
                    (time.perf_counter() - t0) * 1e3,
                    extra={"tick": self.core.tick_counter},
                )

    # --- ingest drain loop (client-connection plane handoff) ------------
    # max handoff items consumed per drain pass: bounds the reactor hold
    # (one pass is one `ingest` lag-plane observation) while still
    # amortizing journal group commits across a burst of submit chunks
    INGEST_DRAIN_BATCH = 256

    async def _ingest_drain_loop(self) -> None:
        """Consume batches of decoded client messages from the connection
        plane (server/ingest.py). Runs of consecutive `submit_chunk`
        messages — across ALL clients — are applied under ONE journal
        group commit, and their acks are queued only after that commit
        lands (durability-before-visibility across chunk boundaries)."""
        plane = self.ingest_plane
        # with the journal plane on, chunk acks (and every other reply)
        # ride the durability watermark instead of an inline group-commit
        # block: the commit thread batches whole runs of chunks on its
        # own, and reply_visible releases the acks in FIFO order once
        # the covering commit lands
        gated = self.jplane is not None
        while True:
            await self._handoff_wake.wait()
            self._handoff_wake.clear()
            while plane.handoff:
                items = plane.pop_batch(self.INGEST_DRAIN_BATCH)
                t0 = time.perf_counter()
                acks: list = []
                batch = None

                def flush_chunks() -> None:
                    nonlocal batch
                    if batch is not None:
                        batch.__exit__(None, None, None)
                        batch = None
                    for ch, resp in acks:
                        ch.reply(resp)
                    acks.clear()

                try:
                    for channel, msg in items:
                        if msg is None:
                            flush_chunks()
                            self._on_channel_gone(channel)
                            continue
                        if not isinstance(msg, dict):
                            # a malformed frame answers THAT client; it
                            # must never crash the drain loop every
                            # other client shares
                            channel.reply({
                                "op": "error",
                                "message": "malformed request frame",
                            })
                            continue
                        op = msg.get("op")
                        if op == "submit_chunk":
                            if batch is None and not gated:
                                batch = self._journal_group_commit()
                                batch.__enter__()
                            try:
                                resp = self._apply_submit_chunk(msg)
                            except Exception as e:  # noqa: BLE001
                                logger.exception("submit_chunk failed")
                                resp = {"op": "error", "message": str(e),
                                        "rid": msg.get("rid")}
                            if gated:
                                self.reply_visible(channel, resp)
                            else:
                                acks.append((channel, resp))
                            continue
                        # any non-chunk op is a durability barrier: commit
                        # the open chunk batch and release its acks first,
                        # preserving per-connection FIFO
                        flush_chunks()
                        if op in ("stream_events", "subscribe"):
                            self._spawn_client_stream(channel, op, msg)
                            continue
                        if op in self._RPC_LAG_EXEMPT:
                            # ops that await external progress (job_wait,
                            # compaction, manager dry-runs) must not stall
                            # the drain loop for every other client
                            self._spawn_client_request(channel, msg)
                            continue
                        response = await self._handle_client_message(msg)
                        if response is not None:
                            self.reply_visible(channel, response)
                finally:
                    flush_chunks()
                self.note_plane("ingest", time.perf_counter() - t0)
                plane.notify_drained()
                # yield between batches: a sustained multi-client flood
                # must round-robin with the scheduler tick and the worker
                # plane, not hold the loop until the handoff runs dry
                await asyncio.sleep(0)

    def _spawn_client_request(self, channel, msg: dict) -> None:
        async def run() -> None:
            response = await self._handle_client_message(msg)
            if response is not None:
                self.reply_visible(channel, response)

        task = asyncio.ensure_future(run())
        self._client_tasks.add(task)
        task.add_done_callback(self._client_tasks.discard)

    def _spawn_client_stream(self, channel, op: str, msg: dict) -> None:
        handler = (
            self._stream_events if op == "stream_events" else self._subscribe
        )
        gone = channel.reactor_gone_event()

        async def run() -> None:
            try:
                await handler(channel.stream_send, gone, msg)
            except (ConnectionError, OSError):
                pass  # consumer went away mid-send
            except Exception:  # noqa: BLE001 - never kill the drain plane
                logger.exception("client stream handler crashed")
            finally:
                # the stream is this connection's terminal op (the legacy
                # plane breaks out of its recv loop the same way)
                channel.close()

        task = asyncio.ensure_future(run())
        channel.stream_task = task
        self._client_tasks.add(task)
        task.add_done_callback(self._client_tasks.discard)

    def _on_channel_gone(self, channel) -> None:
        channel.is_gone = True
        if channel.gone is not None:
            channel.gone.set()

    async def _journal_flush_loop(self) -> None:
        """Flush the journal on --journal-flush-period instead of per event
        (reference bootstrap.rs journal_flush_period, default 30 s there);
        with --journal-fsync periodic/always the periodic flush also
        fsyncs, bounding the OS-crash loss window to one period."""
        period = self.journal_flush_period or 30.0
        while True:
            await asyncio.sleep(period)
            if self.jplane is not None:
                # non-blocking: the commit thread flushes when it drains
                self.jplane.request_flush(
                    sync=self.journal_fsync != "never"
                )
            else:
                self.journal.flush(sync=self.journal_fsync != "never")

    async def _journal_compact_loop(self) -> None:
        """Compact on --journal-compact-interval and/or whenever the
        journal grows past --journal-compact-threshold bytes. The size
        check is a cheap stat on a 5 s poll; compaction itself runs
        through compact_journal (snapshot + GC, heavy work off-loop)."""
        poll = 5.0
        if self.journal_compact_interval > 0:
            poll = min(poll, self.journal_compact_interval)
        last = clock.monotonic()
        while True:
            await asyncio.sleep(poll)
            due = (
                self.journal_compact_interval > 0
                and clock.monotonic() - last >= self.journal_compact_interval
            )
            if not due and self.journal_compact_threshold > 0:
                # a journal whose LIVE-work floor exceeds the threshold
                # must not be recompacted every poll: require the file to
                # have doubled past the last compaction's result before the
                # size trigger fires again (geometric backoff)
                floor = (
                    self.last_compaction["journal_bytes_after"]
                    if self.last_compaction
                    else 0
                )
                try:
                    size = self.journal_path.stat().st_size
                except OSError:
                    size = 0
                due = (
                    size >= self.journal_compact_threshold
                    and size >= 2 * floor
                )
            if not due:
                continue
            try:
                await self.compact_journal(reason="auto")
            except Exception:
                logger.exception("journal compaction failed")
            last = clock.monotonic()

    async def compact_journal(self, reason: str = "manual") -> dict:
        """One snapshot + journal-GC cycle.

        Phases (each kill -9-survivable, chaos site `server.compact`):

        1. **barrier** (sync on the reactor loop): commit + fsync any open
           group-commit batch so every acknowledged event is durable, then
           capture the live state and the event-seq watermark. Nothing can
           interleave — capture is one synchronous block.
        2. **snapshot** (executor thread): serialize + write temp → fsync →
           rotate `.snap` to `.snap.prev` → atomic rename → dir fsync.
           Only after this is the snapshot allowed to supersede anything.
        3. **GC** (executor thread): rewrite the pre-barrier journal region
           into a temp file, keeping live jobs' events (for `--history`),
           server-uid lineage records, and nothing else — completed and
           forgotten jobs' events are dropped. The journal keeps appending
           concurrently; only bytes below the barrier offset are touched.
        4. **swap** (sync on the loop): close the appender, carry over the
           frames appended during the rewrite, atomically publish the GC'd
           journal, fsync the directory, reopen for append.
        """
        from hyperqueue_tpu.events import snapshot as snapshot_mod
        from hyperqueue_tpu.events.journal import Journal

        if self.journal is None:
            raise RuntimeError("server runs without a journal")
        if self._compacting:
            return {"skipped": "compaction already in progress"}
        self._compacting = True
        try:
            t0 = time.perf_counter()
            loop = asyncio.get_running_loop()
            # phase 1: barrier + capture (no awaits until stop_at is read)
            if self.jplane is not None:
                # blocks the loop until the commit thread has everything
                # on disk — the same stop-the-world barrier the inline
                # path gets from commit+fsync below
                self.jplane.barrier(sync=True)
            else:
                if self.journal.in_batch:
                    self.journal.commit_batch()
                self.journal.flush(sync=True)
            state = snapshot_mod.capture_state(self)
            watermark = state["seq"]
            stop_at = self.journal_path.stat().st_size
            keep_jobs = {
                job_id
                for job_id, job in self.jobs.jobs.items()
                if not job.is_terminated()
            }
            bytes_before = stop_at

            # the current .snap becomes .snap.prev — the fallback source if
            # the NEW snapshot later proves corrupt. The GC floor must stay
            # at the fallback's watermark, or events of jobs that completed
            # between the two snapshots would be dropped and a fallback
            # restore would re-execute acknowledged-finished work. Retains
            # at most one compaction window of extra journal.
            def _retained_seq():
                try:
                    return snapshot_mod.read_snapshot(
                        snapshot_mod.snapshot_path(self.journal_path)
                    )["seq"]
                except Exception:
                    return None  # no/corrupt old snapshot: nothing retained

            old_seq = await loop.run_in_executor(None, _retained_seq)
            gc_floor = (
                watermark if old_seq is None else min(watermark, old_seq)
            )
            # phase 2: durable snapshot publish (off-loop)
            snap = await loop.run_in_executor(
                None, snapshot_mod.write_snapshot, self.journal_path, state
            )
            # phase 3: GC rewrite of the superseded prefix (off-loop)
            tmp = Path(str(self.journal_path) + ".gc")
            try:
                kept, dropped = await loop.run_in_executor(
                    None,
                    Journal.gc_rewrite,
                    self.journal_path,
                    tmp,
                    keep_jobs,
                    gc_floor,
                    stop_at,
                    self.journal_salvage,
                )
                if chaos.ACTIVE:
                    chaos.fire("server.compact", event="pre-swap")
                # phase 4: synchronous swap — no awaits, so no event can
                # be appended between close and reopen; the journal
                # plane's commit thread is drained + parked around the
                # handle swap (it keeps appending to the SAME Journal
                # object, which reopens onto the published file)
                if self.jplane is not None:
                    self.jplane.suspend()
                self.journal.close()
                try:
                    Journal.gc_finalize(self.journal_path, tmp, stop_at)
                finally:
                    # whatever happened (ENOSPC mid-carry-over, either file
                    # published), the appender MUST come back or every
                    # subsequent emit_event would crash the handlers
                    self.journal.open_for_append()
                    if self.jplane is not None:
                        self.jplane.resume()
            except BaseException:
                tmp.unlink(missing_ok=True)
                raise
            if chaos.ACTIVE:
                chaos.fire("server.compact", event="post-swap")
            stats = {
                "reason": reason,
                "time": clock.now(),
                "duration_ms": round((time.perf_counter() - t0) * 1e3, 2),
                "watermark": watermark,
                "gc_floor": gc_floor,
                "kept_records": kept,
                "dropped_records": dropped,
                "journal_bytes_before": bytes_before,
                "journal_bytes_after": self.journal_path.stat().st_size,
                "snapshot_bytes": snap.stat().st_size,
                "live_jobs": len(keep_jobs),
            }
            self.last_compaction = stats
            REGISTRY.counter(
                "hq_journal_compactions_total",
                "journal snapshot+GC compaction cycles completed",
            ).inc()
            REGISTRY.counter(
                "hq_journal_gc_dropped_records_total",
                "journal records dropped by compaction GC",
            ).inc(dropped)
            logger.info(
                "journal compacted (%s): %d records kept, %d dropped, "
                "%d -> %d bytes (+%d snapshot) in %.1f ms",
                reason, kept, dropped, bytes_before,
                stats["journal_bytes_after"], stats["snapshot_bytes"],
                stats["duration_ms"],
            )
            return stats
        finally:
            self._compacting = False

    async def _reattach_reaper(self) -> None:
        """Requeue restored maybe-running tasks whose pre-crash worker did
        not reconnect within --reattach-timeout: fence the dead incarnation
        (instance bump) and make the task schedulable again."""
        while True:
            await asyncio.sleep(0.5)
            if not self.reattach_pending:
                continue
            now = clock.monotonic()
            expired = [
                tid for tid, deadline in self.reattach_pending.items()
                if deadline <= now
            ]
            for task_id in expired:
                del self.reattach_pending[task_id]
                task = self.core.tasks.get(task_id)
                if (
                    task is None
                    or task.is_done
                    or task.state is not TaskState.WAITING
                ):
                    continue
                logger.warning(
                    "task %d: no worker reclaimed it within the %.0fs "
                    "reattach window; requeueing",
                    task_id, self.reattach_timeout,
                    extra={"job": task_id_job(task_id),
                           "task": task_id_task(task_id)},
                )
                reactor.requeue_reattach_expired(self.core, self.comm, task)

    # --- graceful drain (ISSUE 13) --------------------------------------
    def start_drain(
        self, worker_ids, timeout: float | None = None, source: str = "cli"
    ) -> list[int]:
        """Begin a graceful drain of `worker_ids`: each worker is masked
        out of the solve/prefill/gang selection (Worker.draining — a
        membership mask like the gang reservation), its queued-but-not-
        started prefilled backlog is retracted, and the drain reaper stops
        it once its running tasks finish — or, past the deadline, stops it
        anyway with clean_stop so anything still running requeues without
        a crash charge (zero task loss either way)."""
        window = float(timeout) if timeout and timeout > 0 \
            else DRAIN_TIMEOUT_DEFAULT
        now = clock.monotonic()
        started: list[int] = []
        for wid in worker_ids:
            worker = self.core.workers.get(wid)
            if worker is None or worker.draining:
                continue
            worker.draining = True
            self.core.bump_membership()
            # retract the queued backlog so the drain is bounded by the
            # currently RUNNING tasks only (same move as the gang drain)
            refs = []
            for tid in sorted(worker.prefilled_tasks):
                task = self.core.tasks[tid]
                if task.retract_pending:
                    continue
                task.retract_pending = True
                refs.append((tid, task.instance_id))
            if refs:
                self.comm.send_retract(wid, refs)
            self._draining[wid] = {
                "deadline": now + window, "started": now, "source": source,
            }
            _DRAINS_TOTAL.labels(source).inc()
            self.emit_event(
                "worker-draining",
                {"id": wid, "timeout": window, "source": source,
                 "running": len(worker.assigned_tasks)},
            )
            started.append(wid)
        return started

    async def _drain_reaper(self) -> None:
        """Stop each draining worker once it settles idle; past the drain
        deadline, escalate to an immediate clean stop (running tasks take
        the normal worker-lost requeue path, no crash charge)."""
        while True:
            await asyncio.sleep(0.2)
            if not self._draining:
                continue
            now = clock.monotonic()
            for wid, rec in list(self._draining.items()):
                worker = self.core.workers.get(wid)
                if worker is None:
                    self._draining.pop(wid, None)
                    continue
                settled = (
                    not worker.assigned_tasks
                    and not worker.prefilled_tasks
                    and worker.mn_task == 0
                )
                escalated = not settled and now >= rec["deadline"]
                if not (settled or escalated):
                    continue
                self._draining.pop(wid, None)
                worker.clean_stop = True
                self.comm.send_stop(wid)
                drain_s = now - rec["started"]
                _DRAIN_SECONDS.observe(drain_s)
                if escalated:
                    _DRAIN_ESCALATIONS_TOTAL.inc()
                    logger.warning(
                        "drain of worker %d hit its %.0fs deadline with %d "
                        "task(s) still running; escalating to stop "
                        "(tasks requeue, no crash charge)",
                        wid, rec["deadline"] - rec["started"],
                        len(worker.assigned_tasks),
                        extra={"worker": wid},
                    )
                self.emit_event(
                    "worker-drained",
                    {"id": wid, "escalated": escalated,
                     "drain_s": round(drain_s, 3), "source": rec["source"]},
                )

    async def _heartbeat_reaper(self) -> None:
        """Drop workers whose heartbeats stopped (beyond TCP-close detection;
        reference server/rpc.rs per-connection heartbeat timeout). The
        timeout is heartbeat_secs x --heartbeat-timeout-factor (floored at
        2 s so one delayed frame never reaps a fast-heartbeat worker)."""
        while True:
            before = clock.monotonic()
            await asyncio.sleep(0.5)
            now = clock.monotonic()
            if now - before > 2.0:
                # the event loop itself stalled (e.g. a solve held at the
                # watchdog deadline): heartbeats are sitting unprocessed in
                # the recv buffers, not missing. Give the recv loops one
                # pass before judging anyone silent.
                continue
            for worker in list(self.core.workers.values()):
                limit = max(
                    worker.configuration.heartbeat_secs
                    * self.heartbeat_timeout_factor,
                    2.0,
                )
                if now - worker.last_heartbeat > limit:
                    logger.warning(
                        "worker %d heartbeat timeout (%.0fs)",
                        worker.worker_id,
                        now - worker.last_heartbeat,
                        extra={"worker": worker.worker_id},
                    )
                    conn = self._worker_conns.pop(worker.worker_id, None)
                    if conn is not None:
                        conn.close()
                    self.comm.unregister_worker(worker.worker_id)
                    self._record_past_worker(
                        worker.worker_id, "heartbeat timeout"
                    )
                    reactor.on_remove_worker(
                        self.core,
                        self.comm,
                        self.events,
                        worker.worker_id,
                        "heartbeat timeout",
                    )

    # --- worker plane ---------------------------------------------------
    async def _handle_worker_conn(self, reader, writer) -> None:
        worker_id = 0
        try:
            conn = await do_authentication(
                reader,
                writer,
                ROLE_SERVER,
                ROLE_WORKER,
                self.access.worker_key_bytes() if self.access else None,
            )
            register = await conn.recv()
            if register.get("op") != "register":
                raise AuthError("expected register message")
            config = WorkerConfiguration.from_wire(register["config"])
            worker = Worker.create(
                self.core.worker_id_counter.next(), config, self.core.resource_map
            )
            worker_id = worker.worker_id
            queue = self.comm.register_worker(worker_id)
            self._worker_conns[worker_id] = conn
            # a reconnecting worker reclaims the restored maybe-running
            # tasks it still executes; everything it reports that the
            # server cannot verify (instance mismatch, already terminal,
            # never held) is echoed back for the worker to kill — both
            # sides agree on exactly one live incarnation per task.
            # Processed BEFORE on_new_worker wakes the scheduler, so a held
            # task can never race onto another worker.
            reattached, discard = self._process_reattach(
                register.get("reattach"), worker
            )
            reactor.on_new_worker(self.core, self.comm, self.events, worker)
            await conn.send(
                {
                    "op": "registered",
                    "worker_id": worker_id,
                    "server_uid": self.access.server_uid if self.access else "",
                    "heartbeat_secs": config.heartbeat_secs,
                    # workers with no own idle timeout adopt the server's
                    # default (reference sync_worker_configuration)
                    "server_idle_timeout": self.idle_timeout,
                    "reattached": reattached,
                    "discard": discard,
                }
            )
            if self._overview_listeners > 0:
                # a dashboard is attached: the new worker starts under the
                # forced overview cadence too
                self.comm.send_overview_override(
                    worker_id, OVERVIEW_OVERRIDE_INTERVAL
                )
            if config.alloc_id and getattr(self, "autoalloc", None):
                self.autoalloc.on_worker_connected(worker_id, config.alloc_id)

            sender = asyncio.create_task(self._worker_sender(conn, queue))
            try:
                await self._worker_recv_loop(conn, worker)
            finally:
                sender.cancel()
        except (
            AuthError,
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
        ) as e:
            logger.info("worker connection ended: %s", e)
        finally:
            if worker_id:
                self._worker_conns.pop(worker_id, None)
                self.comm.unregister_worker(worker_id)
                worker = self.core.workers.get(worker_id)
                if worker is not None:
                    # a requested stop disconnects too — record the true
                    # reason, not a generic connection loss (reference
                    # LostWorkerReason::Stopped vs ConnectionLost); a
                    # redirect-ordered departure is a lend, not a loss
                    lent_to = self._lent_workers.pop(worker_id, None)
                    if worker.clean_stop:
                        reason = "stopped"
                        lent_to = None
                    elif lent_to is not None and not worker.assigned_tasks:
                        # only an IDLE departure is the lend completing; a
                        # worker that picked up work in the lend window
                        # aborts the redirect, so a busy disconnect here
                        # is a genuine loss (its tasks requeue/reattach).
                        # The human string stays for logs; `lent_to` is
                        # the structured field the fleet feed renders
                        # lending flows from (ISSUE 15)
                        reason = f"lent to shard {lent_to}"
                    else:
                        reason = "connection lost"
                        lent_to = None
                    self._record_past_worker(worker_id, reason,
                                             lent_to=lent_to)
                    reactor.on_remove_worker(
                        self.core, self.comm, self.events, worker_id, reason
                    )
            writer.close()

    def _process_reattach(
        self, reattach: dict | None, worker: Worker
    ) -> tuple[list[int], list[int]]:
        """Reclaim a reconnecting worker's still-running tasks.

        A task is reattached iff the journal restore held it for exactly
        this incarnation (server.reattach_pending + matching instance id):
        it becomes RUNNING on the new worker record with resources
        accounted — NOT requeued, no crash-counter charge. Anything else
        the worker reports is stale (already terminal, requeued under a
        newer instance, or this server never knew it) and is returned in
        `discard` for the worker to kill; its messages would be fenced by
        the instance check anyway, but killing stops the side effects.
        """
        if not reattach:
            return [], []
        reattached: list[int] = []
        discard: list[int] = []
        # lineage fence: the claimed server_uid must have written this
        # journal, or the worker's task ids belong to a different server's
        # numbering (same server dir reused with another --journal) and
        # could collide at the common instance 0
        claimed_uid = reattach.get("server_uid") or ""
        uid_ok = claimed_uid in self.journal_uids
        if not uid_ok and reattach.get("running"):
            logger.warning(
                "reconnecting worker claims unknown server lineage %r; "
                "discarding its %d running task(s)",
                claimed_uid, len(reattach.get("running", ())),
            )
        for entry in reattach.get("running", ()):
            task_id = entry.get("id")
            instance = entry.get("instance", 0)
            task = self.core.tasks.get(task_id)
            claimable = (
                uid_ok
                and task is not None
                and not task.is_done
                and task.instance_id == instance
            )
            if claimable and self.reattach_pending.pop(task_id, None) is not None:
                reactor.on_task_reattached(self.core, self.events, task, worker)
                reattached.append(task_id)
            elif (
                claimable
                and task.state is TaskState.READY
                and not self.core.rq_map.get_variants(task.rq_id).is_multi_node
            ):
                # a ready task whose claimed instance matches EXACTLY what
                # the server would re-issue. Since restore fences re-issues
                # to the boot's generation base (core.instance_fence_floor)
                # a prior boot's incarnation can no longer collide here;
                # this branch stays as a safety net — if a matching claim
                # ever does arrive, adopting it out of the ready queue is
                # strictly safer than racing a second execution under the
                # same instance id, invisible to the fence. The journal
                # never saw this start, so the worker's reported variant is
                # the only truth about which resources it occupies.
                variant = int(entry.get("variant", 0))
                if variant < len(
                    self.core.rq_map.get_variants(task.rq_id).variants
                ):
                    task.assigned_variant = variant
                self.core.queues.remove(task.rq_id, task_id)
                reactor.on_task_reattached(self.core, self.events, task, worker)
                reattached.append(task_id)
            else:
                discard.append(task_id)
        # parked-but-never-started tasks are NEVER kept: the server
        # re-issues them (restore saw no task-started), so a silently kept
        # local copy would run alongside the re-issue under one instance id
        for entry in reattach.get("blocked", ()):
            discard.append(entry.get("id"))
        if reattached or discard:
            logger.info(
                "worker %d reconnected from old worker %s: reattached %d "
                "task(s), discarded %d stale",
                worker.worker_id, reattach.get("worker_id"),
                len(reattached), len(discard),
                extra={"worker": worker.worker_id},
            )
        return reattached, discard

    async def _worker_sender(self, conn: Connection, queue: asyncio.Queue):
        """Drain the per-worker queue into batch frames: a tick's burst
        (compute batches, retract fan-out, cancels) leaves as one
        encryption + one syscall instead of one per message — the downlink
        half of the pipelined assignment delivery. The encryption half
        runs on the fan-out sender pool (server/fanout.py) when enabled,
        so N workers' downlinks seal on N threads instead of serializing
        on this loop. Chaos actions apply per LOGICAL message so fault
        plans behave identically under batching."""
        loop = asyncio.get_running_loop()
        pool = self.sendpool
        while True:
            enq_ts, msg = await queue.get()
            batch = [msg]
            while len(batch) < 256:
                try:
                    batch.append(queue.get_nowait()[1])
                except asyncio.QueueEmpty:
                    break
            if chaos.ACTIVE:
                injected = []
                for m in batch:
                    action = await chaos.on_message(
                        "server.send", op=m.get("op")
                    )
                    if action == "drop":
                        continue
                    injected.append(m)
                    if action == "dup":
                        injected.append(m)
                batch = injected
                if not batch:
                    continue
            t0 = time.perf_counter()
            payload = (
                batch[0] if len(batch) == 1
                else {"op": "batch", "msgs": batch}
            )
            data = await pool.encode(loop, conn, payload)
            await conn.send_bytes(data)
            dt = time.perf_counter() - t0
            pool.note_send(len(batch), len(data), dt)
            # re-pointed `fanout` lag probe (ISSUE 12): handoff latency —
            # reactor enqueue to frame-on-the-wire — not loop hold time
            # (the encode no longer holds the loop at all)
            self.lag.observe("fanout", clock.monotonic() - enq_ts)
            if self.stall_budget > 0 and dt >= self.stall_budget:
                self._capture_stall("fanout", dt)

    async def _worker_recv_loop(self, conn: Connection, worker: Worker) -> None:
        while True:
            msg = await conn.recv()
            worker.last_heartbeat = clock.monotonic()
            subs = msg["msgs"] if msg.get("op") == "batch" else [msg]
            if chaos.ACTIVE:
                # conservative path: chaos actions await between messages,
                # so the group-commit block (which must stay synchronous)
                # is skipped and every event keeps its per-event flush
                for sub in subs:
                    action = await chaos.on_message(
                        "server.recv", op=sub.get("op")
                    )
                    if action == "drop":
                        continue
                    if action == "dup":
                        self._process_worker_message(worker, sub)
                    self._process_worker_message(worker, sub)
                continue
            # batched completion plane: the whole frame is processed
            # synchronously (no awaits). With the journal plane on, the
            # events it produced are enqueued to the commit thread and
            # every CLIENT-visible effect (acks, replies, listener/
            # subscriber deliveries) is watermark-gated. Worker-bound
            # messages (cancels/retracts this frame may trigger) are
            # deliberately NOT gated: dispatches were never journaled —
            # the tick already sends compute messages with no durability
            # coupling — and a pre-durable incarnation that dies with
            # the server is fenced + killed at reattach (instance
            # fencing), the same crash semantics as before. With
            # --journal-plane reactor the inline group commit covers the
            # frame as it always did (ONE write + fsync per batch).
            t0 = time.perf_counter()
            if self.jplane is not None:
                for sub in subs:
                    self._process_worker_message(worker, sub)
                # in-loop completion processing (sans journal I/O) is its
                # own lag plane now; `journal` measures handoff latency
                # on the commit thread (see JournalPlane)
                self.note_plane("completion", time.perf_counter() - t0)
            else:
                with self._journal_group_commit():
                    for sub in subs:
                        self._process_worker_message(worker, sub)
                # frame processing + group commit hold the loop
                # synchronously: the journal plane's loop occupancy
                self.note_plane("journal", time.perf_counter() - t0)

    def _process_worker_message(self, worker: Worker, msg: dict) -> None:
            op = msg.get("op")
            _WORKER_MESSAGES_TOTAL.labels(str(op)).inc()
            if op == "task_running":
                reactor.on_task_running(
                    self.core, self.events, msg["id"], msg["instance"],
                    wtrace=msg.get("trace"),
                )
            elif op == "task_finished":
                reactor.on_task_finished(
                    self.core, self.comm, self.events, msg["id"],
                    msg["instance"], wtrace=msg.get("trace"),
                )
            elif op == "task_failed":
                reactor.on_task_failed(
                    self.core,
                    self.comm,
                    self.events,
                    msg["id"],
                    msg["instance"],
                    msg.get("error", "task failed"),
                    wtrace=msg.get("trace"),
                )
            elif op == "retract_response":
                reactor.on_retract_response(
                    self.core, self.comm, msg["id"], msg.get("ok", False),
                    instance_id=msg.get("instance", -1),
                )
            elif op == "heartbeat":
                pass
            elif op == "goodbye":
                # deliberate worker exit (idle/time limit): its running
                # tasks requeue without a crash-counter charge
                worker.clean_stop = True
            elif op == "task_notify":
                task_id = msg.get("id", 0)
                self.emit_event(
                    "task-notify",
                    {
                        "job": task_id_job(task_id),
                        "task": task_id_task(task_id),
                        "payload": msg.get("payload", ""),
                    },
                )
            elif op == "overview":
                worker.last_overview = {
                    "hw": msg.get("hw", {}),
                    "n_running": msg.get("n_running", 0),
                }
                # piggybacked gauge/counter samples feed the cluster-wide
                # Prometheus view (collect hook) and the dashboard stream
                worker.last_metrics = msg.get("metrics") or []
                self.emit_event(
                    "worker-overview",
                    {"id": worker.worker_id, "hw": msg.get("hw", {}),
                     "n_running": msg.get("n_running", 0),
                     "metrics": worker.last_metrics},
                )
            else:
                logger.warning("unknown worker message %r", op)

    # --- client plane ---------------------------------------------------
    async def _handle_client_conn(self, reader, writer) -> None:
        try:
            conn = await do_authentication(
                reader,
                writer,
                ROLE_SERVER,
                ROLE_CLIENT,
                self.access.client_key_bytes() if self.access else None,
            )
            while True:
                msg = await conn.recv()
                if msg.get("op") in ("stream_events", "subscribe"):
                    # adapt the connection to the sink interface shared
                    # with the threaded plane: send = conn.send, and a
                    # watcher task turns the read side's EOF into `gone`
                    gone = asyncio.Event()

                    async def _watch_eof() -> None:
                        try:
                            await conn.recv()
                        except Exception:  # noqa: BLE001 - any end is EOF
                            pass
                        gone.set()

                    watcher = asyncio.ensure_future(_watch_eof())
                    handler = (
                        self._stream_events
                        if msg.get("op") == "stream_events"
                        else self._subscribe
                    )
                    try:
                        await handler(conn.send, gone, msg)
                    finally:
                        if not watcher.done():
                            watcher.cancel()
                            try:
                                await watcher
                            except (asyncio.CancelledError, Exception):
                                pass
                    break
                response = await self._handle_client_message(msg)
                if response is not None:
                    # durability gate (journal plane): the reply leaves
                    # only at/below the committed watermark
                    await self._visibility_barrier()
                    await conn.send(response)
        except (
            AuthError,
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
        ) as e:
            logger.debug("client connection ended: %s", e)
        finally:
            writer.close()

    # client ops that legitimately await external progress (job completion,
    # executor-offloaded compaction, manager dry-runs): their wall time is
    # waiting, not loop occupancy, so they stay out of the rpc lag plane
    _RPC_LAG_EXEMPT = frozenset({
        "job_wait", "journal_compact", "journal_prune", "alloc_add",
        "alloc_dry_run", "alloc_remove",
    })

    async def _handle_client_message(self, msg: dict) -> dict | None:
        if not isinstance(msg, dict):
            return {"op": "error", "message": "malformed request frame"}
        op = msg.get("op")
        if not isinstance(op, str):
            return {"op": "error", "message": f"malformed operation {op!r}"}
        handler = getattr(self, f"_client_{op.replace('-', '_')}", None)
        if handler is None:
            return {"op": "error", "message": f"unknown operation {op!r}"}
        t0 = time.perf_counter()
        try:
            return await handler(msg)
        except Exception as e:  # noqa: BLE001 - client errors must not kill the server
            logger.exception("error handling client %r", op)
            return {"op": "error", "message": str(e)}
        finally:
            if op not in self._RPC_LAG_EXEMPT:
                self.note_plane("rpc", time.perf_counter() - t0)

    async def _client_server_info(self, msg: dict) -> dict:
        return {
            "op": "server_info",
            "server_uid": self.access.server_uid if self.access else "",
            "version": __version__,
            "host": self.host,
            "server_dir": str(self.server_dir),
            "client_port": self.client_port,
            "worker_port": self.worker_port,
            "started_at": self.started_at,
            "n_workers": len(self.core.workers),
            "n_jobs": len(self.jobs.jobs),
            "scheduler": self.scheduler_kind,
            "metrics_port": self.metrics_port,
            "federation": self._federation_block(),
            # ISSUE 12: which AEAD implementation seals this server's
            # wire, and where the journal/fan-out work runs
            "wire_backend": WIRE_BACKEND,
            "journal_plane": (
                self.journal_plane if self.journal is not None else None
            ),
            "fanout_senders": self.fanout_senders,
        }

    async def _client_server_stats(self, msg: dict) -> dict:
        """Scheduler telemetry: per-phase tick latency breakdown plus the
        incremental snapshot-cache counters (`hq server stats`).  The
        phase split attributes a tick-latency regression to batches /
        assemble / solve-dispatch / device-sync / mapping instead of one
        opaque number."""
        return {
            "op": "server_stats",
            "tick": self.core.tick_stats.snapshot(),
            # phase -> fraction of tick time: the blame denominator bench
            # smokes store next to the profiler's plane shares (ISSUE 19)
            "tick_shares": self.core.tick_stats.shares(),
            "tick_cache": self.core.tick_cache.counters(),
            "paranoid_tick": self.core.paranoid_tick,
            "scheduler": self.scheduler_kind,
            # ISSUE 20: active weighted-objective policy (None = flat
            # placement-count objective)
            "policy": (
                self.core.policy.stats()
                if self.core.policy is not None else None
            ),
            "solve_backend": getattr(self.model, "last_backend", None),
            "solve_backend_reason": getattr(
                self.model, "last_backend_reason", None
            ),
            "shape_allocations": getattr(
                self.model, "shape_allocations", None
            ),
            "resident": (
                self.model.resident_stats()
                if hasattr(self.model, "resident_stats") else None
            ),
            "pipeline": (
                self.core.tick_pipeline.stats()
                if self.core.tick_pipeline is not None else None
            ),
            "watchdog": self.model.stats(),
            "reattach_pending": len(self.reattach_pending),
            "journal": await self._journal_stats_brief(),
            "trace": TRACER.snapshot(recent=0),
            # ISSUE 8: loop-lag per plane, stall captures, trace store +
            # subscription plane health
            "lag": self.lag.snapshot(),
            "stalls": {
                "budget_s": self.stall_budget,
                "captured": self.stalls_captured,
                "last": self.last_stall,
            },
            "task_traces": self.core.traces.stats(),
            # ISSUE 19: per-plane CPU attribution from the sampling
            # profiler (the CPU twin of the lag block above)
            "profile": profiler.PROFILER.snapshot(),
            "subscribers": len(self._subscribers),
            # ISSUE 10: connection-plane + lazy-materialization health
            "ingest": self._ingest_stats(),
            # ISSUE 11: shard identity, lease health, lending counters
            "federation": self._federation_block(),
            # ISSUE 12: journal commit thread + fan-out sender pool
            "journal_plane": (
                self.jplane.stats() if self.jplane is not None
                else {"mode": self.journal_plane}
            ),
            "fanout": self._fanout_stats(),
        }

    def _fanout_stats(self) -> dict:
        from hyperqueue_tpu.server.fanout import (
            FANOUT_BYTES,
            FANOUT_FRAMES,
            FANOUT_STALLS,
        )

        return {
            "senders": self.fanout_senders,
            "wire_backend": WIRE_BACKEND,
            "frames_total": int(FANOUT_FRAMES.labels().value),
            "bytes_total": int(FANOUT_BYTES.labels().value),
            "send_stalls": int(FANOUT_STALLS.labels().value),
        }

    def _ingest_stats(self) -> dict:
        plane = self.ingest_plane
        out = {
            "plane": self.client_plane,
            "lazy": self.core.lazy.stats(),
            "open_streams": sum(
                j.open_streams for j in self.jobs.jobs.values()
            ),
        }
        if plane is not None:
            out.update(
                clients=len(plane.clients),
                handoff_depth=len(plane.handoff),
                window=plane.window,
                chunks_total=int(INGEST_CHUNKS.labels().value),
                tasks_total=int(INGEST_TASKS.labels().value),
            )
        return out

    async def _journal_stats_brief(self) -> dict | None:
        """Compact journal/snapshot block for `hq server stats` (stat-only;
        `hq journal info` is the full view)."""
        if self.journal_path is None:
            return None
        from hyperqueue_tpu.events import snapshot as snapshot_mod

        try:
            journal_bytes = self.journal_path.stat().st_size
        except OSError:
            journal_bytes = 0
        snap = snapshot_mod.snapshot_stats(self.journal_path)
        return {
            "journal_bytes": journal_bytes,
            "segments": int(journal_bytes > 0) + int(snap["bytes"] > 0)
            + int(snap["prev_bytes"] > 0),
            "snapshot_bytes": snap["bytes"],
            "snapshot_age_seconds": (
                round(snap["age_seconds"], 1)
                if snap["age_seconds"] is not None
                else None
            ),
            "last_compaction": self.last_compaction,
            "last_restore": self.last_restore,
        }

    async def _client_reset_metrics(self, msg: dict) -> dict:
        """Zero the metrics plane (registry values, tracer spans, tick-phase
        aggregates) so benchmarks can measure a steady-state window:
        reset, run, scrape. Registrations survive — only values clear.
        Externally-tracked telemetry the collect hook re-adopts (watchdog
        counters, tick-cache counters) is zeroed at its source too;
        hq_events_emitted_total is exempt — it mirrors the journal seq,
        which is functional state."""
        from hyperqueue_tpu.scheduler.tick_cache import TickPhaseStats

        REGISTRY.reset()
        TRACER.reset()
        # the rolling per-plane lag SpanStats live OUTSIDE the registry
        # (they feed `hq server stats` + stall dumps) and must clear with
        # the rest of the window, like the hq_span_seconds SpanStats do —
        # a steady-state measurement must not inherit startup lag maxima
        self.lag.reset()
        self.core.tick_stats = TickPhaseStats()
        self.model.reset_stats()
        self.core.tick_cache.full_rebuilds = 0
        self.core.tick_cache.incremental_syncs = 0
        # SLO windows + alert state clear with the measurement window
        # (ISSUE 18): steady-state burn rates must not inherit a breach
        # that happened before the reset
        self.slo.reset()
        # profiler aggregates (ISSUE 19): folded trie, CPU-share window
        # and the stall sample ring all belong to the measurement window
        profiler.PROFILER.reset()
        return {"op": "ok"}

    async def _client_profile(self, msg: dict) -> dict:
        """Folded stacks + per-plane CPU shares from the sampling
        profiler (`hq server profile [--seconds N]`). With the
        continuous sampler on, `--seconds N` diffs the folded trie
        across the window (the cumulative view is seconds 0); on a
        `--profile-hz 0` server a throwaway burst sampler covers the
        window instead, so the command always answers."""
        seconds = min(max(float(msg.get("seconds") or 0.0), 0.0), 120.0)
        prof = profiler.PROFILER
        if prof.running:
            if seconds > 0:
                before = prof.folded_counts()
                passes0 = prof.passes
                await asyncio.sleep(seconds)
                counts = profiler.diff_counts(prof.folded_counts(), before)
                window_passes = prof.passes - passes0
            else:
                counts = prof.folded_counts()
                window_passes = prof.passes
            return {
                "op": "profile",
                "mode": "continuous",
                "shard": self.shard_id,
                "hz": prof.hz,
                "seconds": seconds,
                "passes": window_passes,
                "folded": profiler.render_folded(counts),
                "profile": prof.snapshot(),
            }
        if self.memory_transport or clock.is_simulated():
            return {"op": "error",
                    "message": "profiling is unavailable on a simulated "
                               "server (real wall-clock telemetry only)"}
        # --profile-hz 0: sample a temporary burst for the window
        seconds = seconds or 2.0
        burst = profiler.SamplingProfiler(hz=max(self.profile_hz, 0)
                                          or profiler.DEFAULT_HZ)
        if not burst.start():
            return {"op": "error", "message": "profiler failed to start"}
        try:
            await asyncio.sleep(seconds)
        finally:
            burst.stop()
        return {
            "op": "profile",
            "mode": "burst",
            "shard": self.shard_id,
            "hz": burst.hz,
            "seconds": seconds,
            "passes": burst.passes,
            "folded": burst.folded(),
            "profile": burst.snapshot(),
        }

    async def _client_metrics_render(self, msg: dict) -> dict:
        """The full Prometheus exposition over the client plane — the
        fleet metrics proxy (ISSUE 15) scrapes shards through this RPC so
        one federated scrape needs no per-shard --metrics-port wiring."""
        return {"op": "metrics", "text": REGISTRY.render()}

    async def _client_job_timeline(self, msg: dict) -> dict:
        """Per-task lifecycle timeline of one job, aggregated server-side:
        submit -> queued -> assigned -> spawned -> finished timestamps
        folded into per-phase percentiles plus a slowest-task drill-down
        (`hq job timeline`). Phase chains are clamped monotonic, so the
        four phase durations of a finished task sum EXACTLY to its
        finished-submitted wall time."""
        job = self.jobs.jobs.get(msg["job_id"])
        if job is None:
            return {"op": "error", "message": f"job {msg['job_id']} not found"}
        rows = []
        for info in job.tasks.values():
            task = self.core.tasks.get(
                make_task_id(job.job_id, info.job_task_id)
            )
            pts = [
                info.submitted_at,
                task.t_ready if task else 0.0,
                task.t_assigned if task else 0.0,
                info.started_at,
                info.finished_at,
            ]
            # forward-clamp the chain: a missing middle stamp (e.g. a
            # restore dropped t_ready for a reattached task) collapses its
            # phase to zero instead of corrupting the neighbours
            for i in range(1, len(pts)):
                if pts[i] <= 0 or pts[i] < pts[i - 1]:
                    pts[i] = pts[i - 1]
            rows.append({
                "id": info.job_task_id,
                "status": info.status,
                "submitted": pts[0],
                "queued": pts[1],
                "assigned": pts[2],
                "started": pts[3],
                "finished": pts[4] if info.finished_at else 0.0,
                "phases": {
                    "pending": pts[1] - pts[0],
                    "queued": pts[2] - pts[1],
                    "dispatch": pts[3] - pts[2],
                    "run": pts[4] - pts[3],
                } if info.finished_at else None,
            })
        # unmaterialized lazy array tasks: pending since their CHUNK's
        # submit stamp (per-chunk clocks keep phase sums exact for open
        # jobs appending chunks over time)
        for seg in self.core.lazy.segments_of(job.job_id):
            chunk_submitted = seg.chunk.submitted_at
            for tid in seg.remaining_ids():
                rows.append({
                    "id": tid, "status": "waiting",
                    "submitted": chunk_submitted,
                    "queued": chunk_submitted, "assigned": 0.0,
                    "started": 0.0, "finished": 0.0, "phases": None,
                })
        finished = [r for r in rows if r["phases"] is not None]

        def pct(sorted_vals: list, q: float) -> float:
            if not sorted_vals:
                return 0.0
            idx = min(
                len(sorted_vals) - 1,
                int(q * (len(sorted_vals) - 1) + 0.5),
            )
            return sorted_vals[idx]

        phases_out = {}
        for name in ("pending", "queued", "dispatch", "run"):
            values = sorted(r["phases"][name] for r in finished)
            phases_out[name] = {
                "count": len(values),
                "total": round(sum(values), 6),
                "mean": round(sum(values) / len(values), 6) if values else 0.0,
                "p50": round(pct(values, 0.50), 6),
                "p95": round(pct(values, 0.95), 6),
                "max": round(values[-1], 6) if values else 0.0,
            }
        makespan = 0.0
        if finished:
            makespan = max(r["finished"] for r in finished) - min(
                r["submitted"] for r in finished
            )
        slowest = sorted(
            finished, key=lambda r: r["finished"] - r["submitted"],
            reverse=True,
        )[:5]
        out = {
            "op": "job_timeline",
            "job": job.job_id,
            "n_tasks": len(rows),
            "n_finished": len(finished),
            "makespan": round(makespan, 6),
            "phases": phases_out,
            "slowest": slowest,
        }
        if msg.get("detail"):
            out["tasks"] = rows
        return out

    async def _client_stop_server(self, msg: dict) -> dict:
        asyncio.get_running_loop().call_soon(self.stop)
        return {"op": "ok"}

    async def _client_submit(self, msg: dict) -> dict:
        recv_at = clock.now()
        job_desc = msg["job"]
        job_id = job_desc.get("job_id")
        if job_id is not None and job_id in self.jobs.jobs:
            job = self.jobs.jobs[job_id]
            if not job.is_open:
                return {"op": "error", "message": f"job {job_id} is not open"}
        else:
            job = self.jobs.create_job(
                name=job_desc.get("name", "job"),
                submit_dir=job_desc.get("submit_dir", os.getcwd()),
                max_fails=job_desc.get("max_fails"),
                is_open=job_desc.get("open", False),
                job_id=job_id,
            )
        # trace-context (ISSUE 8): the client stamped a trace id + its send
        # clock; every task of this submit joins that trace, and the ids
        # ride the journal event so restore rebuilds the SAME trace
        from hyperqueue_tpu.transport.framing import read_trace
        from hyperqueue_tpu.utils.trace import new_trace_id

        tctx = read_trace(msg) or {}
        trace_id = tctx.get("id") or new_trace_id()
        sent_at = float(tctx.get("sent_at") or 0.0)
        trace = {"id": trace_id, "sent_at": sent_at, "recv_at": recv_at,
                 "commit_at": clock.now()}
        array = job_desc.get("array")
        if array:
            n_new = self._ingest_array_desc(
                job, array, submitted_at=recv_at, trace=trace
            )
        else:
            new_tasks = self._build_tasks(job, job_desc)
            n_new = len(new_tasks)
        job.submits.append(submit_record(job_desc, n_new))
        self.emit_event(
            "job-submitted", {"job": job.job_id, "desc": job_desc,
                              "n_tasks": n_new,
                              "trace": {"id": trace_id, "sent_at": sent_at,
                                        "recv_at": recv_at}}
        )
        if not array:
            self._begin_submit_traces(new_tasks, trace)
            reactor.on_new_tasks(self.core, self.comm, new_tasks)
        return {"op": "submit_response", "job_id": job.job_id,
                "n_tasks": n_new}

    def _begin_submit_traces(self, new_tasks, trace: dict) -> None:
        """Open each task's distributed trace with the client/submit and
        server/submit spans (eager path; lazy chunks replay the same
        stamps at materialization — server/lazy.py)."""
        traces = self.core.traces
        if not traces.enabled:
            return
        sent_at = trace["sent_at"]
        recv_at = trace["recv_at"]
        commit_at = trace.get("commit_at") or recv_at
        for task in new_tasks:
            traces.begin(task.task_id, trace["id"])
            parent = None
            if sent_at:
                parent = traces.span(
                    task.task_id, "client/submit", sent_at, recv_at,
                    "client",
                )
            traces.span(
                task.task_id, "server/submit", recv_at, commit_at,
                "server", parent=parent,
            )

    @staticmethod
    def _wire_array_ids(array: dict):
        """(ids, id_range) from a wire array description. Chunked clients
        send contiguous runs as "id_range": [start, stop) — O(1) on the
        wire and in the lazy store; explicit id lists must be sorted."""
        id_range = array.get("id_range")
        if id_range is not None:
            lo, hi = int(id_range[0]), int(id_range[1])
            if hi <= lo:
                raise ValueError(f"empty or inverted id_range {id_range}")
            return None, (lo, hi)
        ids = list(array["ids"])
        if any(b <= a for a, b in zip(ids, ids[1:])):
            ids = sorted(set(ids))
        return ids, None

    def _check_array_ids(self, job, ids, id_range) -> None:
        """Duplicate-id guard in O(materialized + chunks), not O(array).

        Against lazy chunks the check is by chunk BOUNDS: an append whose
        id span overlaps an earlier chunk's span is rejected even if the
        earlier chunk had holes the new ids would fit — precise hole
        tracking would cost the O(tasks) scan laziness exists to avoid.
        """
        lo = id_range[0] if id_range else ids[0]
        hi = id_range[1] if id_range else ids[-1] + 1
        for seg in self.core.lazy.per_job.get(job.job_id, ()):
            chunk = seg.chunk
            if lo <= chunk.max_id() and chunk.min_id() < hi:
                raise ValueError(
                    f"task ids [{lo}, {hi}) overlap an earlier array "
                    f"chunk [{chunk.min_id()}, {chunk.max_id()}] of job "
                    f"{job.job_id}"
                )
        # iterate whichever side is SMALLER: a long stream of eager
        # chunks (--lazy-array-threshold 0) must stay O(chunk) per chunk,
        # not O(materialized-so-far) — quadratic over a 1M-line stdin
        n_new = (hi - lo) if id_range is not None else len(ids)
        if n_new < len(job.tasks):
            tasks = job.tasks
            for tid in (range(lo, hi) if id_range is not None else ids):
                if tid in tasks:
                    raise ValueError(f"duplicate task id {tid}")
        else:
            id_set = None if id_range is not None else set(ids)
            for tid in job.tasks:
                if lo <= tid < hi and (id_set is None or tid in id_set):
                    raise ValueError(f"duplicate task id {tid}")

    def _ingest_array_desc(self, job, array: dict, submitted_at: float,
                           trace: dict | None) -> int:
        """Ingest one wire array description — the JASDA atomization seam.

        Arrays at/above --lazy-array-threshold (single-node only) register
        ONE ArrayChunk: O(1) allocations here, per-task records deferred
        to dispatch (server/lazy.py). Smaller arrays keep the eager path.
        Reference: server/client/submit.rs build_tasks_array; the
        shared/separate wire split (messages/worker.rs:28-54) means a
        million-task array never ships a million bodies either way.
        """
        ids, id_range = self._wire_array_ids(array)
        n = (id_range[1] - id_range[0]) if id_range else len(ids)
        self._check_array_ids(job, ids, id_range)
        rqv = rqv_from_wire(
            array.get("request") or {}, self.core.resource_map
        )
        rq_id = self.core.intern_rqv(rqv)
        shared_body = array.get("body", {})
        entries = array.get("entries")
        priority = (int(array.get("priority", 0)),
                    encode_sched_priority(job.job_id))
        crash_limit = int(array.get("crash_limit", 5))
        if not rqv.is_multi_node and n >= self.lazy_array_threshold:
            chunk = ArrayChunk(
                job_id=job.job_id,
                rq_id=rq_id,
                priority=priority,
                body=shared_body,
                crash_limit=crash_limit,
                id_range=id_range,
                ids=ids,
                entries=list(entries) if entries is not None else None,
                submitted_at=submitted_at,
                ready_at=clock.now(),
                trace=dict(trace) if trace else None,
            )
            held = job.job_id in self.core.paused_jobs
            self.core.lazy.register(self.core, chunk, held=held)
            if not held:
                self.comm.ask_for_scheduling()
            return n
        # eager path: per-task records now, stamped with THIS submit's
        # clock (per-chunk submitted_at keeps `hq job timeline` exact for
        # open jobs appending chunks over time)
        new_tasks: list[Task] = []
        ids_iter = ids if ids is not None else range(*id_range)
        for i, job_task_id in enumerate(ids_iter):
            if job_task_id in job.tasks:
                raise ValueError(f"duplicate task id {job_task_id}")
            job.tasks[job_task_id] = JobTaskInfo(
                job_task_id=job_task_id, submitted_at=submitted_at
            )
            new_tasks.append(
                Task(
                    task_id=make_task_id(job.job_id, job_task_id),
                    rq_id=rq_id,
                    priority=priority,
                    body=shared_body,  # one dict for the whole array
                    entry=entries[i] if entries is not None else None,
                    crash_limit=crash_limit,
                )
            )
        if trace:
            self._begin_submit_traces(new_tasks, trace)
        reactor.on_new_tasks(self.core, self.comm, new_tasks)
        return len(new_tasks)

    def _apply_submit_chunk(self, msg: dict) -> dict:
        """One streamed submit chunk (op=submit_chunk), applied
        synchronously so the ingest drain loop can group-commit a whole
        run of chunks as ONE journal append+fsync.

        Exactly-once across retries and restarts: every chunk is keyed
        (stream uid, chunk index); applied indexes are journaled with the
        chunk's job-submitted event and replayed into Job.streams, so a
        client re-sending an unacked chunk after a server crash gets an
        idempotent duplicate ack instead of duplicate tasks."""
        from hyperqueue_tpu.transport.framing import read_trace
        from hyperqueue_tpu.utils.trace import new_trace_id

        recv_at = clock.now()
        uid = msg.get("uid")
        rid = msg.get("rid")
        if not isinstance(uid, str) or not uid:
            return {"op": "error", "rid": rid,
                    "message": "submit_chunk requires a stream uid"}
        index = int(msg.get("i", 0))
        header = msg.get("job") or {}
        # elastic resharding (ISSUE 17): a stream whose job moved (or is
        # mid-move) answers a coded error — the client re-resolves the
        # owner and replays its unacked chunks there (the destination
        # imported the stream's applied-index set, so the replay dedups)
        probe_id = self._stream_jobs.get(uid)
        if probe_id is None:
            probe_id = header.get("job_id")
        guard = self._owned_elsewhere(probe_id, rid=rid)
        if guard is not None:
            return guard
        job_id = self._stream_jobs.get(uid)
        if job_id is not None:
            job = self.jobs.jobs.get(job_id)
            if job is None:
                return {"op": "error", "rid": rid,
                        "message": f"stream {uid}: job {job_id} vanished"}
        else:
            jid = header.get("job_id")
            if jid is not None and jid in self.jobs.jobs:
                job = self.jobs.jobs[jid]
                if not job.is_open and uid not in job.streams:
                    return {"op": "error", "rid": rid,
                            "message": f"job {jid} is not open"}
            else:
                job = self.jobs.create_job(
                    name=header.get("name", "job"),
                    submit_dir=header.get("submit_dir", os.getcwd()),
                    max_fails=header.get("max_fails"),
                    is_open=bool(header.get("open", False)),
                    job_id=jid,
                )
            self._stream_jobs[uid] = job.job_id
        stream = job.streams.get(uid)
        if stream is None:
            stream = job.streams[uid] = {"applied": set(), "sealed": False}
            job.open_streams += 1
        if index in stream["applied"]:
            # ack replay (client retry after a lost ack): idempotent
            return {"op": "chunk_ack", "rid": rid, "job_id": job.job_id,
                    "i": index, "n_tasks": 0, "dup": True}
        if stream["sealed"]:
            return {"op": "error", "rid": rid,
                    "message": f"stream {uid} is already sealed"}
        tctx = read_trace(msg) or {}
        trace = {
            "id": tctx.get("id") or new_trace_id(),
            "sent_at": float(tctx.get("sent_at") or 0.0),
            "recv_at": recv_at,
            "commit_at": clock.now(),
        }
        desc: dict = {
            "name": job.name, "submit_dir": job.submit_dir,
            "max_fails": job.max_fails, "open": job.is_open,
        }
        array = msg.get("array")
        graph_tasks = msg.get("tasks")
        n_new = 0
        try:
            if array:
                n_new = self._ingest_array_desc(
                    job, array, submitted_at=recv_at, trace=trace
                )
                desc["array"] = array
            elif graph_tasks:
                new_tasks = self._build_tasks(job, {"tasks": graph_tasks})
                n_new = len(new_tasks)
                desc["tasks"] = graph_tasks
                self._begin_submit_traces(new_tasks, trace)
                reactor.on_new_tasks(self.core, self.comm, new_tasks)
        except Exception as e:  # noqa: BLE001 - bad chunk answers the client
            # a rejected chunk BREAKS the stream: seal it (journaled, so
            # restore cannot resurrect it open) so the job can still
            # terminate — the client aborts on the error and must
            # restart with a fresh stream uid
            if not stream["sealed"]:
                stream["sealed"] = True
                job.open_streams = max(job.open_streams - 1, 0)
                self.emit_event(
                    "job-streams-sealed",
                    {"job": job.job_id, "uids": [uid]},
                )
                self.check_job_completion(job.job_id)
            return {"op": "error", "rid": rid,
                    "message": f"chunk {index} rejected: {e}"}
        stream["applied"].add(index)
        last = bool(msg.get("last"))
        if last:
            stream["sealed"] = True
            job.open_streams = max(job.open_streams - 1, 0)
        if n_new:
            job.submits.append(submit_record(desc, n_new))
        self.emit_event(
            "job-submitted",
            {"job": job.job_id, "desc": desc, "n_tasks": n_new,
             "chunk": {"uid": uid, "i": index, "last": last},
             "trace": {"id": trace["id"], "sent_at": trace["sent_at"],
                       "recv_at": recv_at}},
        )
        INGEST_CHUNKS.inc()
        if n_new:
            INGEST_TASKS.inc(n_new)
        if last:
            # the stream seal may be what lets the job terminate
            self.check_job_completion(job.job_id)
        return {"op": "chunk_ack", "rid": rid, "job_id": job.job_id,
                "i": index, "n_tasks": n_new, "dup": False}

    async def _client_submit_chunk(self, msg: dict) -> dict:
        """submit_chunk over the legacy in-loop client plane
        (--client-plane reactor): apply one chunk under its own group
        commit. The threaded plane batches chunk runs in the drain loop
        instead and never reaches this handler."""
        with self._journal_group_commit():
            return self._apply_submit_chunk(msg)

    def _build_tasks(self, job, job_desc: dict) -> list[Task]:
        """Convert a GRAPH submit description into core tasks (arrays go
        through _ingest_array_desc).

        Reference: server/client/submit.rs build_tasks_graph.
        """
        new_tasks: list[Task] = []
        used = set(job.tasks)
        for t in job_desc.get("tasks", []):
            job_task_id = t.get("id")
            if job_task_id is None:
                job_task_id = (max(used) + 1) if used else 0
                # write the assigned id back into the desc: the desc is
                # journaled verbatim by _client_submit, and restore replays
                # it through this same path — without the id every such task
                # would collapse to id 0 on replay
                t["id"] = job_task_id
            if job_task_id in used or self.core.lazy.owns(
                job.job_id, job_task_id
            ):
                raise ValueError(f"duplicate task id {job_task_id}")
            used.add(job_task_id)
            rqv = rqv_from_wire(t.get("request") or {}, self.core.resource_map)
            rq_id = self.core.intern_rqv(rqv)
            task_id = self.jobs.attach_task(job, job_task_id)
            deps = tuple(
                make_task_id(job.job_id, d) for d in t.get("deps", ())
            )
            new_tasks.append(
                Task(
                    task_id=task_id,
                    rq_id=rq_id,
                    priority=(int(t.get("priority", 0)),
                              encode_sched_priority(job.job_id)),
                    body=t.get("body", {}),
                    deps=deps,
                    crash_limit=int(t.get("crash_limit", 5)),
                )
            )
        return new_tasks

    def _job_pending_reasons(self, job_id: int) -> dict[str, int]:
        """Reason-code -> pending-task count for one job, joined from the
        latest DecisionRecord plus the pause ledger (`hq job info`
        "37 tasks waiting: 30 insufficient-capacity, 7 gang-incomplete")."""
        from hyperqueue_tpu.scheduler import decision as decision_mod

        reasons: dict[str, int] = {}
        held = self.core.paused_held.get(job_id)
        if held:
            reasons[decision_mod.REASON_QUEUE_PAUSED] = len(held)
        if job_id in self.core.paused_jobs:
            # the pause supersedes whatever the last pre-pause tick said
            return reasons
        latest = self.core.flight.latest()
        if latest:
            for entry in latest.get("unplaced") or ():
                if (
                    entry.get("job") == job_id
                    and entry.get("reason")
                    != decision_mod.REASON_QUEUE_PAUSED
                ):
                    reasons[entry["reason"]] = (
                        reasons.get(entry["reason"], 0) + entry["count"]
                    )
        return reasons

    async def _client_job_list(self, msg: dict) -> dict:
        jobs = []
        for j in self.jobs.jobs.values():
            info = j.to_info()
            info["paused"] = j.job_id in self.core.paused_jobs
            jobs.append(info)
        return {"op": "job_list", "jobs": jobs}

    def _job_detail(self, job) -> dict:
        """job.to_detail() plus synthesized rows for unmaterialized lazy
        array tasks (status "waiting" — they have no per-task state yet,
        which is the point)."""
        detail = job.to_detail()
        if job.n_lazy:
            rows = detail["tasks"]
            for seg in self.core.lazy.segments_of(job.job_id):
                for tid in seg.remaining_ids():
                    rows.append({
                        "id": tid, "status": "waiting", "error": "",
                        "workers": [], "started_at": 0.0,
                        "finished_at": 0.0,
                    })
            rows.sort(key=lambda r: r["id"])
        return detail

    async def _client_job_info(self, msg: dict) -> dict:
        guard = self._guard_job_ids(msg["job_ids"])
        if guard is not None:
            return guard
        out = []
        for job_id in msg["job_ids"]:
            job = self.jobs.jobs.get(job_id)
            if job is not None:
                detail = self._job_detail(job)
                detail["paused"] = job_id in self.core.paused_jobs
                if job.n_waiting() - job.counters["running"] > 0:
                    detail["pending_reasons"] = self._job_pending_reasons(
                        job_id
                    )
                out.append(detail)
        return {"op": "job_info", "jobs": out}

    async def _client_job_wait(self, msg: dict) -> dict:
        guard = self._guard_job_ids(msg["job_ids"])
        if guard is not None:
            return guard
        events = []
        for job_id in msg["job_ids"]:
            job = self.jobs.jobs.get(job_id)
            if job is None or job.all_tasks_done():
                continue
            event = asyncio.Event()
            self._job_waiters.setdefault(job_id, []).append(event)
            events.append(event)
        if events:
            await asyncio.gather(*(e.wait() for e in events))
        return await self._client_job_info(msg)

    async def _client_job_cancel(self, msg: dict) -> dict:
        guard = self._guard_job_ids(msg["job_ids"])
        if guard is not None:
            return guard
        canceled = []
        for job_id in msg["job_ids"]:
            job = self.jobs.jobs.get(job_id)
            if job is None:
                continue
            # lazy array tasks must exist to be canceled (per-task events,
            # counters); a cancel is O(tasks) with or without laziness
            if job.n_lazy:
                self.core.lazy.materialize_job(self.core, job_id)
            # cancel implies the client gave up on any in-flight chunk
            # stream: seal so the job can reach a terminal state — and
            # JOURNAL the forced seal, or a restore would resurrect the
            # stream as open and the job could never terminate
            self._seal_job_streams(job)
            task_ids = [
                make_task_id(job_id, t.job_task_id)
                for t in job.tasks.values()
                if t.status in ("waiting", "running")
            ]
            if task_ids:
                job.cancel_reason = "canceled by user"
            out = reactor.on_cancel_tasks(
                self.core, self.comm, self.events, task_ids
            )
            canceled.append({"job": job_id, "n_canceled": len(out)})
            self.check_job_completion(job_id)
        return {"op": "job_cancel", "result": canceled}

    async def _client_job_forget(self, msg: dict) -> dict:
        guard = self._guard_job_ids(msg["job_ids"])
        if guard is not None:
            return guard
        forgotten = 0
        for job_id in msg["job_ids"]:
            job = self.jobs.jobs.get(job_id)
            if job is None or not job.is_terminated():
                continue
            del self.jobs.jobs[job_id]
            for job_task_id in job.tasks:
                self.core.tasks.pop(make_task_id(job_id, job_task_id), None)
            self.core.paused_jobs.discard(job_id)
            self.core.paused_held.pop(job_id, None)
            self.core.lazy.forget_job(job_id)
            for uid in job.streams:
                self._stream_jobs.pop(uid, None)
            forgotten += 1
        return {"op": "job_forget", "forgotten": forgotten}

    async def _client_open_job(self, msg: dict) -> dict:
        job = self.jobs.create_job(
            name=msg.get("name", "job"),
            submit_dir=msg.get("submit_dir", os.getcwd()),
            max_fails=msg.get("max_fails"),
            is_open=True,
        )
        self.emit_event("job-opened", {"job": job.job_id, "name": job.name})
        return {"op": "open_job", "job_id": job.job_id}

    async def _client_close_job(self, msg: dict) -> dict:
        closed = []
        for job_id in msg["job_ids"]:
            job = self.jobs.jobs.get(job_id)
            if job is not None and (job.is_open or job.open_streams):
                job.is_open = False
                # a close also seals abandoned chunk streams (a client
                # that died mid-stream must not wedge the job forever);
                # the job-closed record seals them again on replay
                job.seal_streams()
                closed.append(job_id)
                self.emit_event("job-closed", {"job": job_id})
                self.check_job_completion(job_id)
        return {"op": "close_job", "closed": closed}

    # --- autoalloc ops ---------------------------------------------------
    async def _client_alloc_add(self, msg: dict) -> dict:
        from hyperqueue_tpu.autoalloc.state import QueueParams

        params = QueueParams.from_wire(msg["params"])
        if params.manager not in ("pbs", "slurm", "local"):
            return {"op": "error",
                    "message": f"unknown manager {params.manager!r}"}
        # the local handler has no external manager to probe — a probe
        # would spawn (and instantly kill) a real worker for nothing
        if not msg.get("no_dry_run") and params.manager != "local":
            error = await self.autoalloc.probe_submit(params)
            if error is not None:
                return {"op": "error",
                        "message": f"allocation dry-run failed: {error} "
                                   "(use --no-dry-run to skip this check)"}
        queue = self.autoalloc.state.add_queue(params)
        self.emit_event(
            "alloc-queue-created",
            {"queue_id": queue.queue_id, "manager": params.manager,
             # full params ride the journal: restore rebuilds the queue
             # exactly (allocation-exact restore, ISSUE 13)
             "params": params.to_wire()},
        )
        return {"op": "alloc_add", "queue_id": queue.queue_id}

    async def _client_alloc_list(self, msg: dict) -> dict:
        return {
            "op": "alloc_list",
            "queues": [q.to_wire() for q in self.autoalloc.state.queues.values()],
        }

    async def _client_alloc_remove(self, msg: dict) -> dict:
        queue = self.autoalloc.state.queues.get(msg["queue_id"])
        if queue is None:
            return {"op": "error", "message": "allocation queue not found"}
        cancels = [
            # journals the cancellation + cancels the manager job
            self.autoalloc.cancel_allocation(
                queue, alloc, reason="queue-removed"
            )
            for alloc in queue.active_allocations()
        ]
        self.autoalloc.state.queues.pop(msg["queue_id"], None)
        self.autoalloc.forget_queue(msg["queue_id"])
        self.emit_event("alloc-queue-removed", {"queue_id": msg["queue_id"]})
        if cancels:
            # the reply must not outrun the manager cancels: a script
            # doing `alloc remove && server stop` would otherwise exit
            # with live batch jobs the journal believes cancelled
            await asyncio.gather(*cancels, return_exceptions=True)
        return {"op": "ok"}

    async def _client_alloc_pause(self, msg: dict) -> dict:
        queue = self.autoalloc.state.queues.get(msg["queue_id"])
        if queue is None:
            return {"op": "error", "message": "allocation queue not found"}
        queue.state = "paused" if msg.get("pause", True) else "running"
        if queue.state == "running":
            queue.consecutive_failures = 0
            queue.next_submit_at = 0.0
            # operator resume also lifts a quarantine and forgets its
            # backoff history
            queue.clear_quarantine()
        # journaled so a restore keeps the operator's pause/resume
        self.emit_event(
            "alloc-queue-paused" if queue.state == "paused"
            else "alloc-queue-resumed",
            {"queue_id": msg["queue_id"], "from": "operator"},
        )
        return {"op": "ok", "state": queue.state}

    async def _client_alloc_events(self, msg: dict) -> dict:
        """Scale decision records: why the controller did / did not act
        (`hq alloc events`)."""
        return {
            "op": "alloc_events",
            "decisions": self.autoalloc.controller.to_wire(),
        }

    async def _client_alloc_log(self, msg: dict) -> dict:
        """Locate an allocation so the client can read its manager-captured
        stdout/stderr (reference commands/autoalloc.rs print_allocation_output
        via AutoAllocRequest::GetAllocationInfo)."""
        _queue, alloc = self.autoalloc.state.find_allocation(msg["allocation_id"])
        if alloc is None:
            return {
                "op": "error",
                "message": f"allocation {msg['allocation_id']} not found",
            }
        return {"op": "alloc_log", "allocation": alloc.to_wire()}

    async def _client_alloc_dry_run(self, msg: dict) -> dict:
        from hyperqueue_tpu.autoalloc.state import QueueParams

        params = QueueParams.from_wire(msg["params"])
        result = await self.autoalloc.dry_run(params)
        return {"op": "alloc_dry_run", **result}

    async def _client_task_explain(self, msg: dict) -> dict:
        """Why is this task (not) running? Reference server/explain.rs:11-98 —
        per worker x per variant, which constraints block — joined with the
        latest DecisionRecord (scheduler/decision.py) for the verdict:
        reason code, human detail, and how many consecutive ticks the
        task's class has been deferred (utils/flight.py)."""
        from hyperqueue_tpu.scheduler import decision as decision_mod

        job_id = msg["job_id"]
        job = self.jobs.jobs.get(job_id)
        job_task_id = msg.get("task_id")
        if job_task_id is None:
            # `hq task explain <job>` without a task: pick the job's first
            # still-pending task (else its first task at all)
            if job is None:
                return {"op": "error", "message": f"job {job_id} not found"}
            pending = sorted(
                t.job_task_id for t in job.tasks.values()
                if t.status in ("waiting", "running")
            )
            if pending:
                job_task_id = pending[0]
            elif job.n_lazy:
                # first LIVE lazy id (the chunk min may already have
                # materialized — or finished — past the segment cursor)
                job_task_id = min(
                    next(iter(seg.remaining_ids()))
                    for seg in self.core.lazy.segments_of(job_id)
                )
            elif job.tasks:
                job_task_id = min(job.tasks)
            else:
                return {"op": "error",
                        "message": f"job {job_id} has no tasks"}
        task = self.core.tasks.get(make_task_id(job_id, job_task_id))
        if task is None and self.core.lazy.owns(job_id, job_task_id):
            # materialize the ONE asked-about lazy task so the explain
            # walk sees exactly what an eager submit would have produced
            # (it re-enters the queues at its priority level's tail)
            task = self.core.lazy.extract(self.core, job_id, job_task_id)
            if task is not None:
                if job_id in self.core.paused_jobs:
                    self.core.paused_held.setdefault(
                        job_id, set()
                    ).add(task.task_id)
                else:
                    self.core.queues.add(
                        task.rq_id, task.priority, task.task_id
                    )
        if task is None:
            if job is not None and job_task_id in job.tasks:
                info = job.tasks[job_task_id]
                return {
                    "op": "task_explain",
                    "job": job_id,
                    "task": job_task_id,
                    "state": info.status,
                    "workers": [],
                    "n_waiting_deps": 0,
                    "reason": None,
                    "reason_detail": f"task is {info.status}",
                    "deferred_ticks": 0,
                }
            return {"op": "error", "message": "task not found"}
        rqv = self.core.rq_map.get_variants(task.rq_id)
        workers = []
        for w in self.core.workers.values():
            variants = []
            for vi, variant in enumerate(rqv.variants):
                blocked = []
                if variant.is_multi_node:
                    group_size = sum(
                        1 for x in self.core.workers.values()
                        if x.group == w.group
                    )
                    if group_size < variant.n_nodes:
                        blocked.append(
                            f"group '{w.group}' has {group_size} < "
                            f"{variant.n_nodes} workers"
                        )
                else:
                    for entry in variant.entries:
                        name = self.core.resource_map.name_of(entry.resource_id)
                        have_total = w.resources.amount(entry.resource_id)
                        have_free = (
                            w.free[entry.resource_id]
                            if entry.resource_id < len(w.free)
                            else 0
                        )
                        if have_total < entry.amount:
                            blocked.append(
                                f"needs {entry.amount / 10_000:g} {name}, "
                                f"worker has {have_total / 10_000:g}"
                            )
                        elif have_free < entry.amount:
                            blocked.append(
                                f"waiting for {name} "
                                f"(free {have_free / 10_000:g} of "
                                f"{entry.amount / 10_000:g} needed)"
                            )
                if variant.min_time_secs and (
                    w.lifetime_secs() < variant.min_time_secs
                ):
                    blocked.append(
                        f"needs {variant.min_time_secs:g}s but worker has "
                        f"{w.lifetime_secs()}s left"
                    )
                variants.append({"variant": vi, "blocked": blocked})
            workers.append(
                {
                    "id": w.worker_id,
                    "hostname": w.configuration.hostname,
                    "variants": variants,
                    "runnable": any(not v["blocked"] for v in variants),
                }
            )

        # --- verdict: reason code + deferral from the flight recorder ---
        reason = None
        detail = ""
        deferred = 0
        decision_tick = None
        paused = job_id in self.core.paused_jobs
        if task.state is TaskState.WAITING:
            reason = decision_mod.REASON_WAITING_DEPS
            detail = (
                f"waiting for {task.unfinished_deps} unfinished "
                f"dependenc{'y' if task.unfinished_deps == 1 else 'ies'}"
            )
        elif task.state is TaskState.READY:
            held = self.core.paused_held.get(job_id)
            if paused and held and task.task_id in held:
                reason = decision_mod.REASON_QUEUE_PAUSED
                detail = (
                    f"job {job_id} is paused; "
                    f"`hq job resume {job_id}` to release it"
                )
            else:
                rec = self.core.flight.reason_for(task.rq_id, job_id)
                if rec is not None:
                    reason = rec["reason"]
                    detail = rec.get("detail") or ""
                    deferred = rec["deferred_ticks"]
                    decision_tick = rec["tick"]
                elif rqv.is_multi_node:
                    reason = decision_mod.REASON_GANG_INCOMPLETE
                else:
                    # no DecisionRecord covers it (no tick yet, or the
                    # recorder is off): classify live against the pool
                    reason = decision_mod.classify_class(
                        self.core, task.rq_id, rqv
                    )
            if not detail:
                n_capable = sum(
                    1 for w in self.core.workers.values()
                    if w.resources.is_capable_of_rqv(rqv)
                )
                detail = {
                    decision_mod.REASON_NO_MATCHING_WORKER: (
                        f"none of the {len(self.core.workers)} connected "
                        "worker(s) provides the requested resources"
                    ),
                    decision_mod.REASON_INSUFFICIENT_CAPACITY: (
                        f"{n_capable} capable worker(s), all currently "
                        "occupied"
                    ),
                    decision_mod.REASON_WORKER_LIFETIME: (
                        f"{n_capable} capable worker(s), but none has "
                        "enough remaining lifetime for the requested "
                        "--time-request"
                    ),
                    decision_mod.REASON_SOLVER_DEFERRED: (
                        "capacity was free but the solver deferred the "
                        "class this tick (priority interleaving or "
                        "reservation drain)"
                    ),
                    decision_mod.REASON_WATCHDOG_FALLBACK: (
                        "the tick ran on the watchdog's host-greedy "
                        "fallback after the primary solver failed "
                        "(see `hq server stats`)"
                    ),
                    decision_mod.REASON_GANG_INCOMPLETE: (
                        "waiting for enough idle same-group workers to "
                        "host the gang"
                    ),
                    decision_mod.REASON_FAIRNESS_DEFERRED: (
                        "a fairness/prediction-boosted job overtook this "
                        "class's priority this tick (--policy-file; "
                        "active policy under `hq server stats`)"
                    ),
                }.get(reason, "")
        # the latest tick's solver verdict: which backend solved (and WHY
        # that backend was chosen — the adaptive cost model's reason), so
        # "why did this tick solve on the host?" is answerable from here
        latest = self.core.flight.latest()
        solver = (latest or {}).get("solver") or {}
        return {
            "op": "task_explain",
            "job": job_id,
            "task": job_task_id,
            "state": task.state.value,
            "n_waiting_deps": task.unfinished_deps,
            "reason": reason,
            "reason_detail": detail,
            "deferred_ticks": deferred,
            "decision_tick": decision_tick,
            "paused": paused,
            "solver_backend": solver.get("backend"),
            "solver_backend_reason": solver.get("backend_reason"),
            "solver_pipelined": bool(solver.get("pipelined")),
            # active weighted objective (--policy-file): weight-matrix
            # source, predictor hit-rate, boost range — None when flat
            "policy": (
                self.core.policy.stats()
                if self.core.policy is not None else None
            ),
            "workers": workers,
        }

    async def _client_flight_recorder_dump(self, msg: dict) -> dict:
        """The flight recorder's rings: last N DecisionRecords + recent
        control-plane events (`hq server flight-recorder dump`)."""
        return {"op": "flight_recorder", **self.core.flight.dump()}

    async def _client_job_pause(self, msg: dict) -> dict:
        """Hold the selected jobs' READY tasks out of the scheduler queues
        (running/assigned tasks are not preempted)."""
        paused = []
        for job_id in msg["job_ids"]:
            job = self.jobs.jobs.get(job_id)
            if job is None or job.is_terminated():
                continue
            held, retracted = reactor.pause_jobs(
                self.core, self.comm, [job_id]
            )
            paused.append(
                {"job": job_id, "held": held, "retracted": retracted}
            )
            self.emit_event(
                "job-paused",
                {"job": job_id, "held": held, "retracted": retracted},
            )
        if paused:
            # wake the scheduler so the next DecisionRecord reflects the
            # pause (and freed prefill budgets can shift to other jobs)
            self.comm.ask_for_scheduling()
        return {"op": "job_pause", "paused": paused}

    async def _client_job_resume(self, msg: dict) -> dict:
        released = []
        for job_id in msg["job_ids"]:
            if job_id not in self.core.paused_jobs:
                continue
            n = reactor.resume_jobs(self.core, self.comm, [job_id])
            released.append({"job": job_id, "released": n})
            self.emit_event("job-resumed", {"job": job_id, "released": n})
        return {"op": "job_resume", "resumed": released}

    async def _client_trace_export(self, msg: dict) -> dict:
        """Chrome trace-event JSON of the run so far: one scheduler row
        built from the flight recorder's tick ring, one row per worker
        carrying its task spans (lifecycle stamps), loadable in Perfetto
        (`hq server trace export out.json`)."""
        events: list[dict] = []
        now = clock.now()
        events.append({
            "ph": "M", "pid": 0, "tid": 0, "name": "process_name",
            "args": {"name": f"hq-server {self.host}"},
        })
        events.append({
            "ph": "M", "pid": 0, "tid": 0, "name": "thread_name",
            "args": {"name": "scheduler"},
        })
        seen_workers: set[int] = set()

        def name_worker(wid: int, hostname: str = "") -> None:
            if wid in seen_workers or not wid:
                return
            seen_workers.add(wid)
            label = f"worker {wid}"
            if hostname:
                label += f" ({hostname})"
            events.append({
                "ph": "M", "pid": 0, "tid": wid, "name": "thread_name",
                "args": {"name": label},
            })

        for w in self.core.workers.values():
            name_worker(w.worker_id, w.configuration.hostname)
        for wid, past in self.past_workers.items():
            name_worker(wid, past.get("hostname", ""))

        # scheduler row: one slice per recorded tick + a ready-queue counter
        ticks = self.core.flight.ticks()
        for rec in ticks:
            ts = rec["time"] * 1e6
            events.append({
                "ph": "X", "pid": 0, "tid": 0, "ts": ts,
                "dur": max(rec.get("duration_ms", 0.0) * 1e3, 1.0),
                "cat": "tick", "name": f"tick {rec['tick']}",
                "args": {
                    "solver": rec.get("solver"),
                    "counts": rec.get("counts"),
                    "phases": rec.get("phases"),
                    "unplaced": rec.get("unplaced"),
                },
            })
            events.append({
                "ph": "C", "pid": 0, "tid": 0, "ts": ts,
                "name": "ready_tasks",
                "args": {
                    "ready": rec.get("counts", {}).get("ready_left", 0)
                },
            })

        # solver row (pid 1): one slice per solve, placed by its RECORDED
        # dispatch/readback wall stamps. Under --tick-pipeline, tick k+1
        # maps the solve DISPATCHED at tick k — charging its solve_ms to
        # the mapping tick's row misattributes the span (it shows the
        # readback wait at the wrong time and hides the overlapped device
        # execution).  The wall stamps render the true execution window;
        # sync solves draw inside their own tick with solve_ms.
        events.append({
            "ph": "M", "pid": 1, "tid": 0, "name": "process_name",
            "args": {"name": "hq-solver"},
        })
        events.append({
            "ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
            "args": {"name": "solve plane"},
        })
        for rec in ticks:
            solver = rec.get("solver") or {}
            solve_ms = solver.get("solve_ms") or 0.0
            if solver.get("pipelined"):
                disp = solver.get("dispatched_at_wall") or 0.0
                mapped = solver.get("mapped_at_wall") or 0.0
                if disp and mapped:
                    events.append({
                        "ph": "X", "pid": 1, "tid": 0, "ts": disp * 1e6,
                        "dur": max((mapped - disp) * 1e6, 1.0),
                        "cat": "solve",
                        "name": f"solve → tick {rec['tick']}",
                        "args": {
                            "pipelined": True,
                            "backend": solver.get("backend"),
                            # the tick-critical-path cost vs the full
                            # dispatch->map window (DecisionRecord
                            # solve_ms vs inflight_ms)
                            "readback_wait_ms": solve_ms,
                            "inflight_ms": solver.get("inflight_ms"),
                            "objective": solver.get("objective"),
                        },
                    })
            elif solve_ms:
                events.append({
                    "ph": "X", "pid": 1, "tid": 0, "ts": rec["time"] * 1e6,
                    "dur": max(solve_ms * 1e3, 1.0),
                    "cat": "solve", "name": f"solve tick {rec['tick']}",
                    "args": {
                        "pipelined": False,
                        "backend": solver.get("backend"),
                        "solve_ms": solve_ms,
                        "objective": solver.get("objective"),
                    },
                })

        # worker rows: one slice per task execution span, linked to the
        # scheduler row with flow events (the per-task causal trace made
        # visible: dispatch on the scheduler row flows into the execution
        # slice on the worker row)
        for job in self.jobs.jobs.values():
            for info in job.tasks.values():
                if not info.started_at:
                    continue
                wid = info.worker_ids[0] if info.worker_ids else 0
                name_worker(wid)
                end = info.finished_at or now
                task_id = make_task_id(job.job_id, info.job_task_id)
                core_task = self.core.tasks.get(task_id)
                trace_rec = self.core.traces.get(task_id)
                events.append({
                    "ph": "X", "pid": 0, "tid": wid,
                    "ts": info.started_at * 1e6,
                    "dur": max((end - info.started_at) * 1e6, 1.0),
                    "cat": "task",
                    "name": f"{job.job_id}.{info.job_task_id}",
                    "args": {
                        "status": info.status,
                        "submitted_at": info.submitted_at,
                        "queued_at": core_task.t_ready if core_task else 0.0,
                        "assigned_at": (
                            core_task.t_assigned if core_task else 0.0
                        ),
                        "workers": info.worker_ids,
                        "trace_id": (
                            trace_rec["trace_id"] if trace_rec else None
                        ),
                    },
                })
                assigned_at = core_task.t_assigned if core_task else 0.0
                if assigned_at and wid:
                    flow = {
                        "cat": "dispatch", "name": "dispatch",
                        "id": task_id,
                    }
                    events.append({
                        "ph": "s", "pid": 0, "tid": 0,
                        "ts": assigned_at * 1e6, **flow,
                    })
                    events.append({
                        "ph": "f", "bp": "e", "pid": 0, "tid": wid,
                        "ts": info.started_at * 1e6, **flow,
                    })

        # profiler counter tracks (ISSUE 19): one CPU-cores counter per
        # plane, bucketed from the sampling ring — the same Perfetto file
        # now answers "which plane was burning CPU" next to ticks, solves
        # and task spans
        prof = profiler.PROFILER
        if prof.running:
            events.append({
                "ph": "M", "pid": 2, "tid": 0, "name": "process_name",
                "args": {"name": "hq-profiler"},
            })
            for plane, series in sorted(prof.counter_track().items()):
                for t, cores in series:
                    events.append({
                        "ph": "C", "pid": 2, "tid": 0, "ts": t * 1e6,
                        "name": f"cpu {plane}", "args": {"cores": cores},
                    })
        return {"op": "trace_export", "traceEvents": events}

    def _record_past_worker(self, worker_id: int, reason: str,
                            lent_to: int | None = None) -> None:
        w = self.core.workers.get(worker_id)
        if w is None:
            return
        self.past_workers[worker_id] = {
            "id": worker_id,
            "hostname": w.configuration.hostname,
            "group": w.group,
            "status": "offline",
            "n_running": 0,
            "resources": {},
            "overview": None,
            "lost_at": clock.now(),
            "reason": reason,
            # structured lend target (None for a genuine loss): the fleet
            # feed and `hq top` render lending flows from this field, the
            # human `reason` string stays for logs (ISSUE 15)
            "lent_to": lent_to,
            # age of the last heartbeat at loss time — for a heartbeat
            # timeout this is how long the worker was silent
            "heartbeat_age": round(clock.monotonic() - w.last_heartbeat, 3),
        }
        while len(self.past_workers) > 1000:  # bound server memory
            self.past_workers.pop(next(iter(self.past_workers)))

    async def _client_worker_list(self, msg: dict) -> dict:
        workers = [
            {
                "id": w.worker_id,
                "hostname": w.configuration.hostname,
                "group": w.group,
                "alloc_id": w.configuration.alloc_id,
                "status": "draining" if w.draining else "running",
                "n_running": len(w.assigned_tasks),
                "resources": {
                    self.core.resource_map.name_of(i): amount
                    for i, amount in enumerate(w.resources.amounts)
                    if amount
                },
                "overview": w.last_overview,
            }
            for w in self.core.workers.values()
        ]
        if msg.get("all"):
            workers.extend(self.past_workers.values())
        return {"op": "worker_list", "workers": workers}

    async def _client_worker_info(self, msg: dict) -> dict:
        w = self.core.workers.get(msg["worker_id"])
        if w is None:
            past = self.past_workers.get(msg["worker_id"])
            if past is not None:
                return {"op": "worker_info", "worker": past}
            return {"op": "error", "message": "worker not found"}
        return {
            "op": "worker_info",
            "worker": {
                "id": w.worker_id,
                "hostname": w.configuration.hostname,
                "group": w.group,
                "manager": w.configuration.manager,
                "manager_job_id": w.configuration.manager_job_id,
                "alloc_id": w.configuration.alloc_id,
                "draining": w.draining,
                "time_limit_secs": w.configuration.time_limit_secs,
                "lifetime_secs": w.lifetime_secs(),
                "descriptor": w.configuration.descriptor.to_dict(),
                "free": {
                    self.core.resource_map.name_of(i): amount
                    for i, amount in enumerate(w.free)
                },
                "running_tasks": sorted(
                    f"{task_id_job(t)}@{task_id_task(t)}"
                    for t in w.assigned_tasks
                ),
                "overview": w.last_overview,
            },
        }

    async def _client_server_debug_dump(self, msg: dict) -> dict:
        """Full server state dump (reference control.rs:207-210 /
        core.rs:472-481 ServerDebugDump)."""
        state_counts: dict[str, int] = {}
        for task in self.core.tasks.values():
            state_counts[task.state.value] = (
                state_counts.get(task.state.value, 0) + 1
            )
        return {
            "op": "server_debug_dump",
            "trace": TRACER.snapshot(),
            "tasks": {
                "total": len(self.core.tasks),
                "by_state": state_counts,
                "ready_queued": self.core.queues.total_ready(),
                "mn_queued": len(self.core.mn_queue),
            },
            "workers": [
                {
                    "id": w.worker_id,
                    "group": w.group,
                    "free": list(w.free),
                    "nt_free": w.nt_free,
                    "assigned": len(w.assigned_tasks),
                    "mn_task": w.mn_task,
                    "mn_reserved": w.mn_reserved,
                }
                for w in self.core.workers.values()
            ],
            "rq_classes": len(self.core.rq_map),
            "resources": self.core.resource_map.names(),
            "jobs": [j.to_info() for j in self.jobs.jobs.values()],
            "autoalloc": [
                q.to_wire() for q in self.autoalloc.state.queues.values()
            ] if self.autoalloc else [],
        }

    async def _client_worker_stop(self, msg: dict) -> dict:
        if msg.get("drain"):
            # graceful: mask + let running tasks finish under the deadline
            started = self.start_drain(
                msg["worker_ids"], timeout=msg.get("timeout"), source="cli"
            )
            return {"op": "worker_stop", "stopped": started, "drain": True}
        stopped = []
        for wid in msg["worker_ids"]:
            worker = self.core.workers.get(wid)
            if worker is not None:
                worker.clean_stop = True  # crash counters stay untouched
                self.comm.send_stop(wid)
                stopped.append(wid)
        return {"op": "worker_stop", "stopped": stopped}

    async def _client_task_list(self, msg: dict) -> dict:
        job = self.jobs.jobs.get(msg["job_id"])
        if job is None:
            return {"op": "error", "message": f"job {msg['job_id']} not found"}
        return {"op": "task_list", "job": self._job_detail(job)}

    async def _stream_events(self, send, gone: asyncio.Event,
                             msg: dict) -> None:
        """Stream events to this client until it disconnects.

        Reference: event/streamer.rs fan-out with EventFilterFlags
        (streamer.rs:36-44); `history=True` first replays the journal.
        `send` is the connection sink (conn.send on the legacy in-loop
        plane, ClientChannel.stream_send on the threaded plane — both
        apply backpressure to this handler); `gone` fires on disconnect.
        """
        prefixes = tuple(msg.get("filter") or ())
        queue: asyncio.Queue = asyncio.Queue()
        # register BEFORE the replay so no live event is missed, then use the
        # record seq to drop events that were appended to the journal while
        # the replay was await-ing sends (they arrive on both paths)
        self._event_listeners.append(queue)
        wants_overviews = bool(msg.get("overviews"))
        if wants_overviews:
            self._overview_listeners += 1
            if self._overview_listeners == 1:
                self.comm.broadcast_overview_override(
                    OVERVIEW_OVERRIDE_INTERVAL
                )
        replayed_seq = -1
        try:
            if msg.get("history") and self.journal_path is not None:
                from hyperqueue_tpu.events.journal import Journal

                if self.jplane is not None:
                    # sync=True: the replay re-reads the FILE, so the
                    # commit thread's buffered tail must be on disk
                    # (sync=False only guarantees the appender saw it)
                    self.jplane.barrier(sync=True)
                else:
                    self.journal.flush()
                for record in Journal.read_all(self.journal_path):
                    seq = record.get("seq")
                    if isinstance(seq, int) and seq > replayed_seq:
                        replayed_seq = seq
                    if not prefixes or record.get("event", "").startswith(prefixes):
                        await send({"op": "event", "record": record})
            await send({"op": "stream_live"})
            # the stream is send-only from here: watch the disconnect
            # event so a client detach is noticed IMMEDIATELY (not at the
            # next failed send, which for an overview listener can lag two
            # cadences and leave workers sampling hw after the dashboard
            # is gone)
            eof = asyncio.ensure_future(gone.wait())
            try:
                while True:
                    getter = asyncio.ensure_future(queue.get())
                    done, _pending = await asyncio.wait(
                        (getter, eof), return_when=asyncio.FIRST_COMPLETED
                    )
                    if eof in done:
                        getter.cancel()
                        break
                    record = getter.result()
                    if record.get("seq", -1) <= replayed_seq:
                        continue  # already sent during the history replay
                    if not prefixes or record.get("event", "").startswith(
                        prefixes
                    ):
                        await send({"op": "event", "record": record})
            finally:
                if not eof.done():
                    eof.cancel()
                    # consume the cancellation so it never surfaces as an
                    # un-retrieved exception in the loop's log
                    try:
                        await eof
                    except (asyncio.CancelledError, Exception):
                        pass
        finally:
            self._event_listeners.remove(queue)
            if wants_overviews:
                self._overview_listeners -= 1
                if self._overview_listeners == 0:
                    self.comm.broadcast_overview_override(None)

    # --- live subscription plane (ISSUE 8b) ---------------------------
    def _build_sample(self) -> dict:
        """One metric sample pushed to subscribers: the cluster signals the
        autoscaler (ROADMAP item 4) and `hq top` need without polling.
        O(workers + queues), never O(tasks)."""
        core = self.core
        workers = []
        running_total = 0
        borrowed = 0
        for w in core.workers.values():
            running_total += len(w.assigned_tasks)
            hw = (w.last_overview or {}).get("hw") or {}
            row = {
                "id": w.worker_id,
                "hostname": w.configuration.hostname,
                "running": len(w.assigned_tasks),
                "prefilled": len(w.prefilled_tasks),
                "draining": w.draining,
                "cpu": hw.get("cpu_usage_percent"),
            }
            lent_from = getattr(w.configuration, "lent_from", -1)
            if lent_from >= 0:
                row["lent_from"] = lent_from
                borrowed += 1
            # worker per-plane CPU attribution (ISSUE 19): the shares the
            # worker piggybacked on its last overview — `hq top` fleet
            # view renders them with no per-worker scrape
            planes = {
                s["labels"]["plane"]: s["value"]
                for s in (w.last_metrics or ())
                if s.get("name") == "hq_worker_profile_plane_cpu_share"
                and (s.get("labels") or {}).get("plane")
            }
            if planes:
                row["planes"] = planes
            workers.append(row)
        latest = core.flight.latest() or {}
        pending_reasons: dict[str, int] = {}
        for entry in latest.get("unplaced") or ():
            reason = entry.get("reason")
            if reason:
                pending_reasons[reason] = (
                    pending_reasons.get(reason, 0) + entry.get("count", 0)
                )
        jobs = self.jobs.jobs
        job_counts: dict[str, int] = {}
        for job in jobs.values():
            status = job.status()
            job_counts[status] = job_counts.get(status, 0) + 1
        sample = {
            "op": "sample",
            "time": clock.now(),
            "uptime": round(clock.now() - self.started_at, 1),
            "event_seq": self._event_seq,
            "workers": workers,
            "n_workers": len(core.workers),
            "n_jobs": len(jobs),
            "job_counts": job_counts,
            "tasks_known": len(core.tasks),
            "ready": core.queues.total_ready(),
            "mn_queued": len(core.mn_queue),
            "running": running_total,
            "pending_reasons": pending_reasons,
            "tick": core.tick_counter,
            "tick_last_ms": (core.tick_stats.snapshot().get("phases") or {})
            .get("total", {}).get("last_ms"),
            "lag": self.lag.snapshot(),
            "stalls": self.stalls_captured,
            "subscribers": len(self._subscribers),
            # health plane (ISSUE 18): usage totals + alert badge ride
            # every sample so `hq top` / the FleetFeed render both
            # without extra RPCs
            "accounting": self.accounting.brief(),
            "alerts": self._alert_badge(),
        }
        if profiler.PROFILER.running:
            # per-plane CPU shares ride every sample (ISSUE 19) so
            # `hq top` renders the CPU block push-fed, like the lag block
            sample["profile"] = {
                plane: agg["cpu"]
                for plane, agg in profiler.PROFILER.plane_shares().items()
            }
        if self.federation_root is not None:
            # fleet view context (ISSUE 15) — all in-memory reads, no
            # lease-file I/O on the sample path (self.lease.epoch is the
            # holder's authoritative copy)
            sample["federation"] = {
                "shard_id": self.shard_id,
                "shard_count": self.shard_count,
                "lease_epoch": self.lease.epoch if self.lease else 0,
                "promoted": self.promoted,
                "workers_lent": self.workers_lent_total,
                "workers_borrowed": borrowed,
            }
        autoalloc = getattr(self, "autoalloc", None)
        if autoalloc is not None and autoalloc.state.queues:
            sample["alloc_quarantined"] = sum(
                1 for q in autoalloc.state.queues.values()
                if q.state == "quarantined"
            )
        return sample

    async def _subscribe(self, send, gone: asyncio.Event,
                         msg: dict) -> None:
        """Stream lifecycle events + periodic metric samples to one client
        over the existing framing until it disconnects or falls behind.

        Backpressure contract: the per-subscriber queue is bounded; a
        consumer that cannot keep up is DROPPED (final `sub_dropped`
        frame, counted in hq_subscribers_dropped_total) rather than
        allowed to hold server memory or reactor latency hostage."""
        # validate the filter: emit_event runs kind.startswith(prefixes)
        # on the reactor's hottest paths, where a non-str element would
        # raise out of the WORKER recv loop — one malformed subscriber
        # must not tear down worker connections. A bare string is treated
        # as one prefix, not a tuple of characters.
        raw_filter = msg.get("filter") or ()
        if isinstance(raw_filter, str):
            raw_filter = (raw_filter,)
        sub = _Subscriber(
            prefixes=tuple(p for p in raw_filter if isinstance(p, str)),
            sample_interval=max(float(msg.get("sample_interval") or 0.0), 0.0),
            buffer=msg.get("buffer") or 4096,
        )
        self._subscribers.append(sub)
        wants_overviews = bool(msg.get("overviews"))
        if wants_overviews:
            self._overview_listeners += 1
            if self._overview_listeners == 1:
                self.comm.broadcast_overview_override(
                    OVERVIEW_OVERRIDE_INTERVAL
                )
        try:
            await send({"op": "sub_live", "seq": self._event_seq})
            if sub.sample_interval:
                await send(self._build_sample())
            next_sample = (
                clock.monotonic() + sub.sample_interval
                if sub.sample_interval else None
            )
            eof = asyncio.ensure_future(gone.wait())
            try:
                while not sub.dead:
                    timeout = (
                        max(next_sample - clock.monotonic(), 0.0)
                        if next_sample is not None else None
                    )
                    getter = asyncio.ensure_future(sub.queue.get())
                    done, _pending = await asyncio.wait(
                        (getter, eof),
                        timeout=timeout,
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                    if eof in done:
                        getter.cancel()
                        return
                    if getter in done:
                        # coalesce a burst into one frame (one encryption +
                        # one syscall, like the downlink batcher)
                        records = [getter.result()]
                        while len(records) < 128:
                            try:
                                records.append(sub.queue.get_nowait())
                            except asyncio.QueueEmpty:
                                break
                        await send(
                            {"op": "events", "records": records}
                        )
                    else:
                        getter.cancel()
                    if (
                        next_sample is not None
                        and clock.monotonic() >= next_sample
                    ):
                        await send(self._build_sample())
                        next_sample = clock.monotonic() + sub.sample_interval
                # fell behind: say so, then hang up
                await send(
                    {"op": "sub_dropped", "dropped": sub.dropped}
                )
            finally:
                if not eof.done():
                    eof.cancel()
                    try:
                        await eof
                    except (asyncio.CancelledError, Exception):
                        pass
        except (ConnectionError, OSError):
            pass  # consumer went away mid-send
        finally:
            self._subscribers.remove(sub)
            if wants_overviews:
                self._overview_listeners -= 1
                if self._overview_listeners == 0:
                    self.comm.broadcast_overview_override(None)

    # --- task traces (ISSUE 8a) ---------------------------------------
    async def _client_task_trace(self, msg: dict) -> dict:
        """The assembled causal trace of one task: every recorded span
        from client submit through worker spawn to completion commit
        (`hq task trace <job>.<task>`)."""
        job_id = msg["job_id"]
        job_task_id = msg.get("task_id") or 0
        task_id = make_task_id(job_id, job_task_id)
        rec = self.core.traces.get(task_id)
        if rec is None:
            if not self.core.traces.enabled:
                return {"op": "error",
                        "message": "task tracing is disabled "
                                   "(--task-trace-capacity 0)"}
            return {"op": "error",
                    "message": f"no trace recorded for task "
                               f"{job_id}.{job_task_id} (evicted, or the "
                               "task predates this server's trace store)"}
        from hyperqueue_tpu.utils.trace import REQUIRED_HOPS, SPAN_ORDER

        order = {name: i for i, name in enumerate(SPAN_ORDER)}
        spans = sorted(
            rec["spans"],
            key=lambda s: (s["instance"], s["t0"], order.get(s["name"], 99)),
        )
        t0 = min((s["t0"] for s in spans), default=0.0)
        t1 = max((s["t1"] for s in spans), default=0.0)
        names = {s["name"] for s in spans}
        return {
            "op": "task_trace",
            "job": job_id,
            "task": job_task_id,
            "trace_id": rec["trace_id"],
            "closed": bool(rec.get("done")),
            "complete": rec.get("done") and REQUIRED_HOPS <= names,
            "missing_hops": sorted(REQUIRED_HOPS - names),
            "wall_s": round(max(t1 - t0, 0.0), 6),
            "span_sum_s": round(
                sum(s["t1"] - s["t0"] for s in spans), 6
            ),
            "spans": spans,
            # fleet annotations (ISSUE 15): lend / failover stamps
            "annotations": list(rec.get("notes") or ()),
        }

    # --- reactor lag + stall watchdog (ISSUE 8c) ----------------------
    STALL_CAPTURE_MIN_INTERVAL = 5.0

    def note_plane(self, plane: str, dt: float) -> None:
        """Record how long one work class held the event loop; past the
        stall budget, auto-capture a diagnosis dump."""
        self.lag.observe(plane, dt)
        if self.stall_budget > 0 and dt >= self.stall_budget:
            self._capture_stall(plane, dt)

    def _capture_stall(self, plane: str, duration_s: float) -> None:
        now = clock.monotonic()
        _REACTOR_STALLS.labels(plane).inc()
        self.core.flight.record_event(
            "reactor-stall",
            {"plane": plane, "duration_s": round(duration_s, 4),
             "budget_s": self.stall_budget},
        )
        if now - self._last_stall_capture < self.STALL_CAPTURE_MIN_INTERVAL:
            self.stalls_captured += 1
            return  # rate-limit the (file-writing) capture, keep counting
        self._last_stall_capture = now
        self.stalls_captured += 1
        dump = {
            "time": clock.now(),
            "plane": plane,
            "duration_s": round(duration_s, 4),
            "budget_s": self.stall_budget,
            "tick": self.core.tick_counter,
            "lag": self.lag.snapshot(),
            "trace": TRACER.snapshot(),
            # profile-on-stall (ISSUE 19): the aggregated stack burst the
            # sampler captured during the stall window itself — what every
            # plane was executing while the budget was being blown (the
            # stall is detected only after the blocking work returns, so
            # the ring is the only honest source of this)
            "profile": profiler.PROFILER.stall_burst(
                duration_s + 1.0
            ) if profiler.PROFILER.running else [],
            "queues": {
                "ready": self.core.queues.total_ready(),
                "mn_queued": len(self.core.mn_queue),
                "workers": len(self.core.workers),
                "event_listeners": len(self._event_listeners),
                "subscribers": len(self._subscribers),
            },
            "flight": self.core.flight.dump(),
        }
        self.last_stall = {
            k: dump[k] for k in ("time", "plane", "duration_s", "tick")
        }
        instance_dir = getattr(self, "_instance_dir", None)
        if instance_dir is None:
            return  # stalled before start() finished; counted, not dumped
        stall_dir = Path(instance_dir) / "stalls"
        try:
            import json as _json

            stall_dir.mkdir(exist_ok=True)
            out = stall_dir / f"stall-{self.stalls_captured:04d}.json"
            out.write_text(_json.dumps(dump, default=str))
            self.last_stall["dump"] = str(out)

            def seq_of(p: Path) -> int:
                # numeric, not lexicographic: past capture 9999 the name
                # outgrows the padding and a string sort would prune the
                # NEWEST dumps
                try:
                    return int(p.stem.rpartition("-")[2])
                except ValueError:
                    return -1

            dumps = sorted(stall_dir.glob("stall-*.json"), key=seq_of)
            for old in dumps[: max(len(dumps) - self.stall_dumps, 0)]:
                old.unlink(missing_ok=True)
        except OSError:
            logger.exception("stall dump write failed")
        logger.critical(
            "reactor stall: %s plane held the loop %.3fs (budget %.3fs); "
            "diagnosis dumped to %s",
            plane, duration_s, self.stall_budget,
            self.last_stall.get("dump", "<memory only>"),
        )

    async def _loop_lag_monitor(self) -> None:
        """Measure the event loop's own scheduling lag: the overshoot of a
        short sleep is exactly how long other work held the loop. Feeds
        the `loop` plane of hq_reactor_lag_seconds and the stall
        watchdog (a long stall shows up here even when the blocking work
        class was never instrumented)."""
        interval = 0.1
        while True:
            before = clock.monotonic()
            await asyncio.sleep(interval)
            overshoot = clock.monotonic() - before - interval
            self.note_plane("loop", max(overshoot, 0.0))

    async def _client_journal_flush(self, msg: dict) -> dict:
        if self.journal is None:
            return {"op": "error", "message": "server runs without a journal"}
        if self.jplane is not None:
            self.jplane.barrier(sync=True)
        else:
            self.journal.flush(sync=True)
        return {"op": "ok"}

    async def _client_journal_prune(self, msg: dict) -> dict:
        """Drop completed jobs from the journal (reference journal/prune.rs)."""
        if self.journal is None:
            return {"op": "error", "message": "server runs without a journal"}
        if self._compacting:
            return {"op": "error",
                    "message": "journal compaction in progress; retry"}
        from hyperqueue_tpu.events import snapshot as snapshot_mod
        from hyperqueue_tpu.events.journal import Journal

        live = {
            job_id
            for job_id, job in self.jobs.jobs.items()
            if not job.is_terminated()
        }
        if snapshot_mod.have_snapshot(self.journal_path):
            # a snapshot supersedes the journal prefix: a bare prune would
            # drop post-watermark terminal events of completed jobs while
            # leaving the stale snapshot in place — the next restore would
            # resurrect and re-execute them. Compaction IS the
            # snapshot-aware prune, so delegate.
            stats = await self.compact_journal(reason="prune")
            if stats.get("skipped"):
                return {"op": "error", "message": stats["skipped"]}
            return {"op": "ok", "kept_records": stats["kept_records"],
                    "live_jobs": sorted(live)}
        # quiesce the commit thread around the close/rewrite/reopen (no
        # awaits in between — see JournalPlane.suspend)
        if self.jplane is not None:
            self.jplane.suspend()
        try:
            self.journal.close()
            kept = Journal.prune(self.journal_path, live,
                                 salvage=self.journal_salvage)
            self.journal.open_for_append()
        finally:
            if self.jplane is not None:
                self.jplane.resume()
        # live jobs' submit events survived the prune; re-log nothing
        return {"op": "ok", "kept_records": kept, "live_jobs": sorted(live)}

    async def _client_journal_compact(self, msg: dict) -> dict:
        """Snapshot + GC now (`hq journal compact`)."""
        if self.journal is None:
            return {"op": "error", "message": "server runs without a journal"}
        stats = await self.compact_journal(reason="cli")
        return {"op": "journal_compact", **stats}

    async def _client_journal_info(self, msg: dict) -> dict:
        """Journal/snapshot sizes, lineage, restore + compaction stats
        (`hq journal info`)."""
        if self.journal_path is None:
            return {"op": "error", "message": "server runs without a journal"}
        from hyperqueue_tpu.events import snapshot as snapshot_mod

        if self.jplane is not None:
            # sync=True so the size/segment stats below see the full tail
            self.jplane.barrier(sync=True)
        else:
            self.journal.flush()
        journal_bytes = (
            self.journal_path.stat().st_size
            if self.journal_path.exists()
            else 0
        )
        snap = snapshot_mod.snapshot_stats(self.journal_path)
        segments = int(journal_bytes > 0) + int(snap["bytes"] > 0) + int(
            snap["prev_bytes"] > 0
        )
        return {
            "op": "journal_info",
            "path": str(self.journal_path),
            "journal_bytes": journal_bytes,
            "segments": segments,
            "event_seq": self._event_seq,
            "n_boots": self.n_boots,
            "snapshot": snap,
            "fsync_policy": self.journal_fsync,
            "compact_interval": self.journal_compact_interval,
            "compact_threshold": self.journal_compact_threshold,
            "salvage": self.journal_salvage,
            "last_restore": self.last_restore,
            "last_compaction": self.last_compaction,
        }


async def run_server(**kwargs) -> None:
    server = Server(**kwargs)
    await server.start()
    await server.run_until_stopped()
