"""Server-side view of a connected worker.

Reference: crates/tako/src/internal/server/worker.rs:30-63 — tracks assigned
tasks, free resources (dense, mirrors the solver's columns), capability
checks, time-limit and heartbeat state. The free/nt_free fields are exactly
the WorkerRow the tick snapshot copies out (scheduler/tick.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from hyperqueue_tpu.utils.constants import INF_TIME
from hyperqueue_tpu.resources.descriptor import ResourceDescriptor
from hyperqueue_tpu.resources.map import ResourceIdMap
from hyperqueue_tpu.resources.worker_resources import WorkerResources
from hyperqueue_tpu.utils import clock


@dataclass
class WorkerConfiguration:
    descriptor: ResourceDescriptor
    hostname: str = "localhost"
    group: str = "default"
    heartbeat_secs: float = 8.0
    time_limit_secs: float = 0.0  # 0 = unlimited
    idle_timeout_secs: float = 0.0
    on_server_lost: str = "stop"  # stop | finish-running | reconnect
    # with on_server_lost=reconnect: give up after this many seconds of
    # failed reconnect attempts (0 = keep retrying forever)
    reconnect_timeout_secs: float = 60.0
    overview_interval_secs: float = 0.0
    # Scheduler only plans tasks here while at least min_utilization x cpus
    # would be busy afterwards — all-or-nothing per tick (reference worker
    # configuration.rs:52, enforced in solver.rs:479-518 add_min_utilization;
    # used by autoalloc so allocation-spawned workers pack-or-idle).
    min_utilization: float = 0.0
    listen_address: str = ""
    # autoalloc linkage: batch manager + allocation id (HQ_ALLOC_ID env)
    manager: str = "none"
    manager_job_id: str = ""
    alloc_id: str = ""
    # warm runner pool width: -1 = auto-size to CPU capacity, 0 = disable
    # (every task spawns through the in-loop asyncio path)
    runner_pool: int = -1
    # bounded coalescing delay of the uplink send drainer: completions
    # within the window share one frame (0 = send-as-ready)
    uplink_flush_secs: float = 0.002
    # federation: home shard this worker was lent FROM after a coordinator
    # redirect (-1 = not a borrowed worker); lets the borrowing shard
    # count its borrowed pool in `hq server stats`
    lent_from: int = -1

    def to_wire(self) -> dict:
        return {
            "descriptor": self.descriptor.to_dict(),
            "hostname": self.hostname,
            "group": self.group,
            "heartbeat_secs": self.heartbeat_secs,
            "time_limit_secs": self.time_limit_secs,
            "idle_timeout_secs": self.idle_timeout_secs,
            "on_server_lost": self.on_server_lost,
            "reconnect_timeout_secs": self.reconnect_timeout_secs,
            "overview_interval_secs": self.overview_interval_secs,
            "min_utilization": self.min_utilization,
            "listen_address": self.listen_address,
            "manager": self.manager,
            "manager_job_id": self.manager_job_id,
            "alloc_id": self.alloc_id,
            "runner_pool": self.runner_pool,
            "uplink_flush_secs": self.uplink_flush_secs,
            "lent_from": self.lent_from,
        }

    @classmethod
    def from_wire(cls, data: dict) -> "WorkerConfiguration":
        return cls(
            descriptor=ResourceDescriptor.from_dict(data["descriptor"]),
            hostname=data.get("hostname", "localhost"),
            group=data.get("group", "default"),
            heartbeat_secs=data.get("heartbeat_secs", 8.0),
            time_limit_secs=data.get("time_limit_secs", 0.0),
            idle_timeout_secs=data.get("idle_timeout_secs", 0.0),
            on_server_lost=data.get("on_server_lost", "stop"),
            reconnect_timeout_secs=data.get("reconnect_timeout_secs", 60.0),
            overview_interval_secs=data.get("overview_interval_secs", 0.0),
            min_utilization=data.get("min_utilization", 0.0),
            listen_address=data.get("listen_address", ""),
            manager=data.get("manager", "none"),
            manager_job_id=data.get("manager_job_id", ""),
            alloc_id=data.get("alloc_id", ""),
            runner_pool=data.get("runner_pool", -1),
            uplink_flush_secs=data.get("uplink_flush_secs", 0.002),
            lent_from=data.get("lent_from", -1),
        )


@dataclass
class Worker:
    worker_id: int
    configuration: WorkerConfiguration
    resources: WorkerResources
    started_at: float = field(default_factory=clock.monotonic)

    # dense scheduling state (the tick snapshot reads these directly)
    free: list[int] = field(default_factory=list)
    nt_free: int = 0
    assigned_tasks: set[int] = field(default_factory=set)
    # tasks pushed beyond current capacity (queue on the worker; no resource
    # accounting until they report running)
    prefilled_tasks: set[int] = field(default_factory=set)
    # multi-node: task id this worker is running a gang for (0 = none)
    mn_task: int = 0
    # multi-node: pending gang task this worker is DRAINING for (0 = none).
    # A reserved worker takes no new sn work (excluded from the dense solve
    # and prefill) so it converges to idle and the gang can eventually claim
    # it even under a continuous stream of small tasks (anti-starvation; the
    # reference achieves this inside one MILP via per-group count variables
    # plus blocking variables, solver.rs:177-209,479-518).
    mn_reserved: int = 0
    last_heartbeat: float = field(default_factory=clock.monotonic)
    last_overview: dict = field(default_factory=dict)
    # gauge/counter samples piggybacked on the worker's last overview
    # message; fanned out (with a `worker` label) by the server's metrics
    # collect hook for the cluster-wide Prometheus view
    last_metrics: list = field(default_factory=list)
    # the worker is going away deliberately (`hq worker stop`, idle/time
    # limit): its tasks requeue WITHOUT a crash-counter increment
    # (reference gateway.rs CrashLimit doc: stops don't count)
    clean_stop: bool = False
    # graceful drain (ISSUE 13): the worker is masked out of the solve,
    # prefill and gang selection (a membership mask like mn_reserved) so it
    # converges to idle; running tasks finish normally, then the server
    # stops it. Set by `hq worker stop --drain` and the elasticity
    # controller's scale-down path; every flip MUST bump core membership.
    draining: bool = False
    # dirty-tracking epoch for the persistent tick snapshot
    # (scheduler/tick_cache.TickStateCache): every mutation of the dense
    # scheduling state (free/nt_free) MUST bump this, or the cache serves
    # a stale row.  assign/unassign are the only such mutation funnel.
    epoch: int = 0

    @classmethod
    def create(
        cls,
        worker_id: int,
        configuration: WorkerConfiguration,
        resource_map: ResourceIdMap,
    ) -> "Worker":
        resources = WorkerResources.from_descriptor(
            configuration.descriptor, resource_map
        )
        worker = cls(
            worker_id=worker_id,
            configuration=configuration,
            resources=resources,
        )
        worker.free = list(resources.amounts)
        worker.nt_free = resources.task_max_count()
        return worker

    @property
    def group(self) -> str:
        return self.configuration.group

    def lifetime_secs(self) -> int:
        limit = self.configuration.time_limit_secs
        if limit <= 0:
            return int(INF_TIME)
        remaining = limit - (clock.monotonic() - self.started_at)
        return max(int(remaining), 0)

    def cpu_floor(self) -> int:
        """Cpu fractions this tick must still fill for min_utilization.

        floor = ceil(mu x all_cpus) - used_cpus = mu x all - (all - free);
        0 for normal workers or once enough is already running (reference
        solver.rs:493-498). Resource id 0 is the cpus column by convention
        (reference CPU_RESOURCE_ID)."""
        mu = self.configuration.min_utilization
        if mu <= 0.001 or not self.free:
            return 0
        all_cpus = self.resources.amount(0)
        if all_cpus <= 0:
            return 0
        import math

        floor = math.ceil(mu * all_cpus) - (all_cpus - self.free[0])
        return max(floor, 0)

    def assign(self, task_id: int, amounts: list[tuple[int, int]]) -> None:
        """amounts: [(resource_id, fraction_amount)] of the chosen variant."""
        self.assigned_tasks.add(task_id)
        for rid, amount in amounts:
            if rid < len(self.free):
                self.free[rid] -= amount
        self.nt_free -= 1
        self.epoch += 1

    def unassign(self, task_id: int, amounts: list[tuple[int, int]]) -> None:
        self.assigned_tasks.discard(task_id)
        for rid, amount in amounts:
            if rid < len(self.free):
                self.free[rid] += amount
        self.nt_free += 1
        self.epoch += 1

    def is_idle(self) -> bool:
        return (
            not self.assigned_tasks
            and not self.prefilled_tasks
            and self.mn_task == 0
        )
