"""Federated control plane: shard failover + cross-shard worker lending.

ISSUE 11 / ROADMAP item 3. A federation is N server shards, each owning a
static partition of the job-id space with its own journal, snapshot
lineage, solve loop, and ports (utils/serverdir.py federation layout).
This module adds the two cross-shard actors:

``FailoverWatcher`` — runs inside a warm standby (``hq server start
--standby``) or an idle peer shard (``--failover-watch``). It polls every
shard's lease; a stale lease means the owning process died (kill -9
included). The watcher claims the shard through the atomic lease protocol
(utils/lease.py — exactly one of many racing watchers wins), then boots a
full Server over the dead shard's dir: the existing two-phase restore
(events/restore.py) replays its journal+snapshot, n_boots/server-uid
lineage bumps fence the dead incarnation, and publishing a fresh instance
dir + access record triggers the whole reconnect choreography PRs 2/6/9
built — workers ``--on-server-lost reconnect`` and REATTACH their running
tasks, client SubmitStreams replay unacked chunks exactly-once, and
subscribers resume.

``FederationCoordinator`` — the thin elasticity loop: one subscribe feed
per shard (PR 8's sample stream: backlog depth, insufficient-capacity
pending reasons, per-worker idleness) drives ``plan_lending``, a pure
function mapping shard samples to (lender, worker, borrower) moves; each
move is a ``worker_lend`` RPC ordering an idle worker to re-register with
the starved shard. No task state migrates — capacity moves, tasks stay
with their journal (Gavel, arxiv 2008.09213; JASDA's scheduler-driven
atomization, arxiv 2510.14599, motivates chunks as the cross-shard unit).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from pathlib import Path

from hyperqueue_tpu.utils import serverdir
from hyperqueue_tpu.utils.lease import (
    LeaseHeldError,
    LeaseRaceLost,
    ShardLease,
)
from hyperqueue_tpu.utils.metrics import REGISTRY
from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.federation")

_FAILOVERS = REGISTRY.counter(
    "hq_federation_failovers_total",
    "dead shards claimed and promoted by this process's failover watcher",
)

# exported from the WATCHER/coordinator process (standby or
# --failover-watch peer), not the shard itself: the per-shard
# hq_federation_lease_age_seconds gauge vanishes exactly when the shard
# dies — this one survives the death it reports (ISSUE 15)
_SHARD_UP = REGISTRY.gauge(
    "hq_federation_shard_up",
    "1 while the shard's lease is held (live owner), 0 while it is "
    "stale or absent — set by the failover watcher's lease scan",
    labels=("shard",),
)

JOURNAL_NAME = "journal.bin"


def shard_journal_path(root: Path, shard_id: int) -> Path:
    """Federated shards journal at a FIXED path inside their shard dir so
    a successor knows where to restore from without out-of-band config."""
    return serverdir.shard_path(root, shard_id) / JOURNAL_NAME


# --------------------------------------------------------------- lending
#: a sample older than this many seconds is dead data — never lend on it
SAMPLE_FRESH_SECS = 10.0
#: per-borrower cooldown: one lend, then wait for the next samples to
#: reflect it before lending again (prevents thrash on a slow feed)
LEND_COOLDOWN_SECS = 3.0

# pending reasons that mean "more workers would help" (scheduler/
# decision.py REASON_*); anything else (paused, dependencies, matching)
# is not solved by capacity
_CAPACITY_REASONS = ("insufficient-capacity", "worker-lifetime")


def _idle_workers(sample: dict) -> list[int]:
    return [
        w["id"]
        for w in sample.get("workers") or ()
        if not w.get("running") and not w.get("prefilled")
    ]


def _backlog(sample: dict) -> int:
    # ready counts only what still sits in SERVER queues — the solver
    # prefills deep per-worker batches, so a hot shard's whole backlog
    # can live in worker prefill queues while total_ready() reads 0.
    # Waiting work is waiting work wherever it queues: count both, or
    # the rebalancer sees a drowning shard as balanced.
    queued_on_workers = sum(
        int(w.get("prefilled") or 0) for w in sample.get("workers") or ()
    )
    return (int(sample.get("ready") or 0)
            + int(sample.get("mn_queued") or 0) + queued_on_workers)


def _wants_capacity(sample: dict) -> bool:
    if _backlog(sample) <= 0:
        return False
    if not sample.get("n_workers"):
        return True  # backlog and literally nobody to run it
    if _idle_workers(sample):
        return False  # transient: it has idle capacity of its own
    reasons = sample.get("pending_reasons") or {}
    return any(reasons.get(r) for r in _CAPACITY_REASONS)


def plan_lending(samples: dict[int, dict | None],
                 exclude=frozenset()) -> list[dict]:
    """Map the latest per-shard samples to worker moves.

    Pure and deterministic (unit-testable): neediest borrowers first
    (deepest backlog), one worker per borrower per round, drawn from the
    lender with the most idle workers and no backlog of its own. Shards
    without a fresh sample neither lend nor borrow. `exclude` holds
    (shard, worker_id) pairs the lender refused recently (wrong
    --on-server-lost policy, raced busy) — without it the planner would
    re-pick the same doomed worker every round and starve the borrower
    even though a lendable sibling idles right next to it.
    """
    now = clock.now()
    fresh = {
        k: s
        for k, s in samples.items()
        if s is not None and now - float(s.get("time") or 0.0) <= (
            SAMPLE_FRESH_SECS
        )
    }
    borrowers = sorted(
        (k for k, s in fresh.items() if _wants_capacity(s)),
        key=lambda k: -_backlog(fresh[k]),
    )
    idle_pool = {}
    for k, s in fresh.items():
        if _backlog(s) != 0:
            continue
        idle = [w for w in _idle_workers(s) if (k, w) not in exclude]
        if idle:
            idle_pool[k] = idle
    moves: list[dict] = []
    for borrower in borrowers:
        lenders = sorted(
            (k for k in idle_pool if k != borrower and idle_pool[k]),
            key=lambda k: -len(idle_pool[k]),
        )
        if not lenders:
            break
        lender = lenders[0]
        moves.append({
            "from": lender,
            "worker_id": idle_pool[lender].pop(),
            "to": borrower,
        })
    return moves


# ------------------------------------------------------------- migration
# ISSUE 17: exactly-once live job migration. The driver (coordinator
# side) runs a 5-phase protocol; every phase is idempotent on both shards
# AND in the ownership log, so a crashed driver re-runs the same mig uid
# from the top and converges. The chaos site `federation.migration` fires
# BETWEEN phases with shard=-1 ("the coordinator") so a kill matrix can
# land a kill -9 at every protocol boundary.

_MIGRATIONS = REGISTRY.counter(
    "hq_federation_migrations_total",
    "live job migrations driven to completion by this process",
)
_MIGRATION_SECONDS = REGISTRY.histogram(
    "hq_federation_migration_seconds",
    "end-to-end duration of one live job migration (claim to done)",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0),
)
_JOBS_MOVED = REGISTRY.counter(
    "hq_federation_jobs_moved_total",
    "jobs whose ownership transferred to another shard (rebalancer and "
    "manual `hq fleet migrate` moves both count)",
)


class MigrationError(RuntimeError):
    """A migration RPC returned an error the driver cannot retry past."""


def _shard_rpc(root: Path, shard_id: int, msg: dict,
               retry_window: float = 5.0) -> dict:
    from hyperqueue_tpu.client.connection import ClientSession

    shard_dir = serverdir.shard_path(root, shard_id)
    with ClientSession(shard_dir, retry_window=retry_window) as session:
        return session.request(msg)


async def drive_migration_async(root: Path, job_id: int, to_shard: int,
                                *, mig: str | None = None, store=None,
                                rpc=None, from_shard: int | None = None,
                                ) -> dict:
    """Run the migration protocol for one job; returns the move record.

    Re-entrant: pass the same ``mig`` uid to resume a crashed driver's
    protocol. Phases (ownership.log is the source of truth throughout):

    1. ``claim``     append migration-intent (a double claim of the same
                     job by a DIFFERENT mig raises MigrationClaimed);
    2. ``export``    source seals + drains the job and hands back a
                     self-contained record (journaled `migration-out`
                     + barrier on the source first);
    3. ``import``    destination journals `migration-in` (embedding the
                     record) + barrier, then acks — or acks dup;
    4. ``commit``    append migration-commit: THE linearization point of
                     the ownership transfer;
    5. ``finalize``  source drops its sealed copy behind a journaled
                     tombstone (`migration-out-done`), then
                     migration-done retires the log entry.

    Kill -9 of source / destination / driver at ANY point leaves exactly
    one durable owner: before commit the source still owns the job (an
    un-finalized destination import is unreachable — routing still says
    source — and a re-driven import acks dup); after commit the
    destination owns it and finalize merely garbage-collects the sealed
    source copy, which answers wrong-shard from its tombstone on."""
    from hyperqueue_tpu.utils import chaos
    from hyperqueue_tpu.utils.ownership import OwnershipStore
    from hyperqueue_tpu.utils.trace import new_trace_id

    store = store or OwnershipStore(root)
    if rpc is None:
        async def rpc(shard, msg):  # noqa: ANN001 - local default
            return _shard_rpc(root, shard, msg)
    if from_shard is None:
        from_shard = store.load().shard_for_job(job_id)
    mig = mig or f"mig-{new_trace_id()}"
    t0 = time.perf_counter()
    intent = store.begin_migration(job_id, from_shard, to_shard, mig)
    from_shard, to_shard = int(intent["from"]), int(intent["to"])
    chaos.fire("federation.migration", op="claim", shard=-1,
               ctx="coordinator")
    if mig not in store.load().committed:
        resp = await rpc(from_shard, {
            "op": "migration_export", "mig": mig, "job": int(job_id),
            "to": to_shard,
        })
        if resp.get("op") == "error":
            # the source says the job already lives elsewhere (a PRIOR
            # finalized migration) — this claim is moot; abort it
            store.abort_migration(mig, reason=resp.get("message", ""))
            raise MigrationError(
                f"export of job {job_id} failed: {resp.get('message')}"
            )
        chaos.fire("federation.migration", op="export", shard=-1,
                   ctx="coordinator")
        resp = await rpc(to_shard, {
            "op": "migration_import", "mig": mig,
            "record": resp["record"],
        })
        if resp.get("op") == "error":
            raise MigrationError(
                f"import of job {job_id} failed: {resp.get('message')}"
            )
        chaos.fire("federation.migration", op="import", shard=-1,
                   ctx="coordinator")
        store.commit_migration(mig)
    chaos.fire("federation.migration", op="commit", shard=-1,
               ctx="coordinator")
    resp = await rpc(from_shard, {
        "op": "migration_finalize", "mig": mig, "job": int(job_id),
        "to": to_shard,
    })
    if resp.get("op") == "error":
        raise MigrationError(
            f"finalize of job {job_id} failed: {resp.get('message')}"
        )
    chaos.fire("federation.migration", op="finalize", shard=-1,
               ctx="coordinator")
    store.finish_migration(mig)
    seconds = time.perf_counter() - t0
    _MIGRATIONS.inc()
    _JOBS_MOVED.inc()
    _MIGRATION_SECONDS.observe(seconds)
    logger.info(
        "migrated job %d: shard %d -> shard %d (%s, %.3fs)",
        job_id, from_shard, to_shard, mig, seconds,
    )
    return {"mig": mig, "job": int(job_id), "from": from_shard,
            "to": to_shard, "seconds": round(seconds, 4)}


def drive_migration(root: Path, job_id: int, to_shard: int, *,
                    mig: str | None = None, store=None, rpc=None,
                    from_shard: int | None = None) -> dict:
    """Synchronous twin of :func:`drive_migration_async` (CLI and
    coordinator threads; the simulator awaits the async form on its own
    loop with a memory-transport rpc)."""
    sync_rpc = rpc

    async def arpc(shard, msg):
        # ClientSession drives a PRIVATE event loop; calling it on the
        # thread already running asyncio.run's loop would nest loops
        # (RuntimeError) — hop to an executor thread for each sync RPC
        loop = asyncio.get_running_loop()
        if sync_rpc is not None:
            return await loop.run_in_executor(None, sync_rpc, shard, msg)
        return await loop.run_in_executor(
            None, _shard_rpc, root, shard, msg
        )

    return asyncio.run(drive_migration_async(
        root, job_id, to_shard, mig=mig, store=store, rpc=arpc,
        from_shard=from_shard,
    ))


def recover_migrations(root: Path, store=None, rpc=None) -> list[dict]:
    """Re-drive every in-flight migration intent in the ownership log
    (coordinator start / `hq fleet migrate --recover`): a pre-commit
    intent restarts from export (the sealed source re-exports, the
    destination dedups), a committed one skips straight to finalize."""
    from hyperqueue_tpu.utils.ownership import OwnershipStore

    store = store or OwnershipStore(root)
    out = []
    for rec in store.load().in_flight():
        try:
            out.append(drive_migration(
                root, int(rec["job"]), int(rec["to"]), mig=rec["mig"],
                store=store, rpc=rpc, from_shard=int(rec["from"]),
            ))
        except Exception as e:  # noqa: BLE001 - recover what can be
            logger.warning("re-driving migration %s failed: %s",
                           rec.get("mig"), e)
    return out


# ------------------------------------------------------------ rebalancer
#: a rebalance fires only while max(backlog) exceeds mean(backlog) by
#: this ratio — the hysteresis band that keeps near-balanced fleets still
REBALANCE_RATIO = 1.5
#: and only this often per donor shard (migrations are heavier than
#: lends; give the moved job's backlog time to show up in the samples)
REBALANCE_COOLDOWN_SECS = 10.0


def plan_rebalance(samples: dict[int, dict | None],
                   min_ratio: float = REBALANCE_RATIO) -> dict | None:
    """Pick one hot->cold whole-job move from per-shard backlog samples,
    or None while the fleet is balanced. Pure and deterministic.

    Hysteresis: no move unless the hottest shard's backlog exceeds the
    fleet mean by ``min_ratio`` AND beats the coldest by more than one
    job's worth of slack (moving a job between near-equal shards would
    just oscillate). The coldest shard receives — idle added shards have
    backlog 0 and become immediate receivers, which is exactly how
    `--shards N -> N+1` drains the hot shard onto the new one."""
    now = clock.now()
    fresh = {
        k: s for k, s in samples.items()
        if s is not None
        and now - float(s.get("time") or 0.0) <= SAMPLE_FRESH_SECS
    }
    if len(fresh) < 2:
        return None
    backlogs = {k: _backlog(s) for k, s in fresh.items()}
    mean = sum(backlogs.values()) / len(backlogs)
    if mean <= 0:
        return None
    hot = max(sorted(backlogs), key=lambda k: backlogs[k])
    cold = min(sorted(backlogs), key=lambda k: backlogs[k])
    if hot == cold or backlogs[hot] < min_ratio * mean:
        return None
    if backlogs[hot] - backlogs[cold] < 2:
        return None
    return {
        "from": hot, "to": cold,
        "ratio": round(backlogs[hot] / mean, 3),
        "backlogs": dict(sorted(backlogs.items())),
    }


class FederationCoordinator:
    """Thread-based lending loop: one subscribe feed per shard feeding
    ``plan_lending``; each move becomes a ``worker_lend`` RPC against the
    lender. Shard death is routine here — a dead feed clears its sample
    and keeps retrying until the shard's successor comes up.

    With ``rebalance=True`` a second control thread turns the same
    samples into WHOLE-JOB moves (ISSUE 17): largest-pending job first,
    hottest shard to coldest, each move one exactly-once
    :func:`drive_migration` run, each verdict appended to the ownership
    log for `hq fleet` to show."""

    def __init__(self, root: Path, sample_interval: float = 1.0,
                 cooldown: float = LEND_COOLDOWN_SECS,
                 rebalance: bool = False,
                 rebalance_ratio: float = REBALANCE_RATIO,
                 rebalance_cooldown: float = REBALANCE_COOLDOWN_SECS):
        self.root = Path(root)
        self.sample_interval = sample_interval
        self.cooldown = cooldown
        self.rebalance = rebalance
        self.rebalance_ratio = rebalance_ratio
        self.rebalance_cooldown = rebalance_cooldown
        self.migrations_done = 0
        self.last_verdict: dict | None = None
        self._last_rebalance: dict[int, float] = {}
        self.samples: dict[int, dict | None] = {}
        self.moves_issued = 0
        self._last_lend: dict[int, float] = {}
        # (shard, worker_id) the lender refused, with expiry stamps: a
        # 'policy' worker stays unlendable, but worker ids churn and a
        # 'busy' race clears, so entries age out instead of pinning
        self._refused: dict[tuple[int, int], float] = {}
        self.refusal_ttl = 60.0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # --- feeds ----------------------------------------------------------
    def _feed(self, shard_id: int) -> None:
        from hyperqueue_tpu.client import connection

        shard_dir = serverdir.shard_path(self.root, shard_id)
        while not self._stop.is_set():
            try:
                for frame in connection.subscribe(
                    shard_dir, filters=("__samples_only__",),
                    sample_interval=self.sample_interval,
                ):
                    if self._stop.is_set():
                        return
                    if frame.get("op") == "sample":
                        self.samples[shard_id] = frame
            except Exception as e:  # noqa: BLE001 - shard down is routine
                logger.debug("shard %d feed down (%s)", shard_id, e)
            # the feed ended (shard died or dropped us): its last sample
            # is no longer trustworthy
            self.samples[shard_id] = None
            self._stop.wait(min(self.sample_interval, 1.0))

    def _control(self) -> None:
        while not self._stop.wait(self.sample_interval):
            try:
                now = clock.monotonic()
                self._refused = {
                    key: t for key, t in self._refused.items()
                    if now - t < self.refusal_ttl
                }
                moves = plan_lending(
                    dict(self.samples), exclude=set(self._refused)
                )
                for move in moves:
                    if now - self._last_lend.get(move["to"], 0.0) < (
                        self.cooldown
                    ):
                        continue
                    if self._issue(move):
                        self._last_lend[move["to"]] = now
                        self.moves_issued += 1
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("lending pass failed")

    # --- rebalancing (ISSUE 17) -----------------------------------------
    def _rebalance_control(self) -> None:
        import os

        from hyperqueue_tpu.utils.ownership import OwnershipStore

        store = OwnershipStore(self.root)
        try:
            # a coordinator that died mid-protocol left intents behind:
            # converge them before planning anything new
            recover_migrations(self.root, store=store)
        except Exception:  # noqa: BLE001 - recovery must not kill the loop
            logger.exception("migration recovery failed")
        # HQ_REBALANCE_INTERVAL decouples the rebalancer's tick from the
        # sampling interval: bench.py --reshard-smoke drives it fast and
        # deterministically instead of sleeping for the sampler's cadence
        try:
            interval = float(
                os.environ.get("HQ_REBALANCE_INTERVAL", "") or
                self.sample_interval
            )
        except ValueError:
            interval = self.sample_interval
        while not self._stop.wait(interval):
            try:
                self._rebalance_pass(store)
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("rebalance pass failed")

    def _rebalance_pass(self, store) -> None:
        plan = plan_rebalance(
            dict(self.samples), min_ratio=self.rebalance_ratio
        )
        if plan is None:
            return
        now = clock.monotonic()
        if now - self._last_rebalance.get(plan["from"], 0.0) < (
            self.rebalance_cooldown
        ):
            return
        backlogs = plan["backlogs"]
        job_id = self._pick_job(
            plan["from"], cap=backlogs[plan["from"]] - backlogs[plan["to"]]
        )
        if job_id is None:
            self.last_verdict = store.record_verdict({
                "moved": None, "from": plan["from"], "to": plan["to"],
                "reason": f"imbalance {plan['ratio']}x but no movable job",
            })
            self._last_rebalance[plan["from"]] = now
            return
        try:
            move = drive_migration(
                self.root, job_id, plan["to"], store=store,
                from_shard=plan["from"],
            )
        except Exception as e:  # noqa: BLE001 - verdict either way
            logger.warning("rebalance migration of job %d failed: %s",
                           job_id, e)
            self.last_verdict = store.record_verdict({
                "moved": None, "from": plan["from"], "to": plan["to"],
                "job": job_id, "reason": f"migration failed: {e}",
            })
        else:
            self.migrations_done += 1
            self.last_verdict = store.record_verdict({
                "moved": job_id, "from": plan["from"], "to": plan["to"],
                "mig": move["mig"], "seconds": move["seconds"],
                "reason": f"backlog imbalance {plan['ratio']}x "
                          f"{plan['backlogs']}",
            })
        self._last_rebalance[plan["from"]] = now

    def _pick_job(self, shard_id: int,
                  cap: float = float("inf")) -> int | None:
        """Largest-pending-first: the job whose move shifts the most
        backlog in one migration. Open jobs are skipped (a mid-stream
        SubmitStream CAN follow a move, but the planner prefers moves
        that cannot even need a redirect); so are terminated ones.

        ``cap`` is the hot-cold backlog gap: moving a job with pending
        >= the gap would leave the RECEIVER at least as hot as the donor
        was — the next pass would just move it back. Requiring a strict
        peak improvement is what makes the rebalancer convergent instead
        of ping-ponging one indivisible job between two shards."""
        try:
            resp = _shard_rpc(self.root, shard_id, {"op": "job_list"})
        except Exception as e:  # noqa: BLE001 - shard may just have died
            logger.debug("job_list on shard %d failed: %s", shard_id, e)
            return None
        best, best_pending = None, 0
        for info in resp.get("jobs", ()):
            c = info.get("counters") or {}
            pending = int(info.get("n_tasks", 0)) - (
                int(c.get("finished", 0)) + int(c.get("failed", 0))
                + int(c.get("canceled", 0))
            )
            if info.get("is_open"):
                continue
            if best_pending < pending < cap:
                best, best_pending = int(info["id"]), pending
        return best

    def _issue(self, move: dict) -> bool:
        from hyperqueue_tpu.client.connection import ClientSession

        lender_dir = serverdir.shard_path(self.root, move["from"])
        try:
            with ClientSession(lender_dir, retry_window=2.0) as session:
                resp = session.request({
                    "op": "worker_lend",
                    "worker_id": move["worker_id"],
                    "to_shard": move["to"],
                })
            lent = bool(resp.get("lent"))
            if lent:
                logger.info(
                    "lent worker %d: shard %d -> shard %d",
                    move["worker_id"], move["from"], move["to"],
                )
            else:
                # a refused worker (policy/busy) must not be re-picked
                # every pass while lendable siblings idle beside it
                self._refused[(move["from"], move["worker_id"])] = (
                    clock.monotonic()
                )
                logger.info(
                    "shard %d refused to lend worker %d (%s)",
                    move["from"], move["worker_id"],
                    resp.get("reason", "?"),
                )
            return lent
        except Exception as e:  # noqa: BLE001 - lender may just have died
            logger.debug("worker_lend to shard %d failed: %s",
                         move["from"], e)
            return False

    # --- lifecycle ------------------------------------------------------
    def start(self) -> None:
        fed = serverdir.load_federation(self.root)
        if fed is None:
            raise ValueError(f"no federation at {self.root}")
        for k in range(fed["shard_count"]):
            t = threading.Thread(
                target=self._feed, args=(k,), daemon=True,
                name=f"hq-fed-feed-{k}",
            )
            t.start()
            self._threads.append(t)
        ctl = threading.Thread(
            target=self._control, daemon=True, name="hq-fed-coordinator"
        )
        ctl.start()
        self._threads.append(ctl)
        if self.rebalance:
            reb = threading.Thread(
                target=self._rebalance_control, daemon=True,
                name="hq-fed-rebalancer",
            )
            reb.start()
            self._threads.append(reb)

    def stop(self) -> None:
        self._stop.set()


# -------------------------------------------------------------- failover
class FailoverWatcher:
    """Scan shard leases; claim and promote stale ones.

    ``server_kwargs`` seeds each promoted Server (scheduler kind, fsync
    policy, ...); server_dir/shard identity/journal/lease settings are
    filled in per shard. ``own_shard`` (peer-shard mode) is never
    scanned, and ``eligible`` — when given — gates claiming (an idle-peer
    policy hook: a shard drowning in its own backlog should leave the
    claim to the standby).
    """

    def __init__(
        self,
        root: Path,
        server_kwargs: dict | None = None,
        lease_timeout: float = 15.0,
        poll: float | None = None,
        own_shard: int = -1,
        eligible=None,
    ):
        self.root = Path(root)
        self.server_kwargs = dict(server_kwargs or {})
        self.lease_timeout = float(lease_timeout)
        self.poll = poll if poll is not None else max(lease_timeout / 3, 0.1)
        self.own_shard = own_shard
        self.eligible = eligible
        self.promoted: dict[int, object] = {}
        self._promoted_tasks: dict[int, asyncio.Task] = {}
        # /readyz input (ISSUE 18): monotonic stamp of the last scan that
        # COMPLETED (a scan that raised does not count as a heartbeat) —
        # distinguishes a standby whose lease-scan loop died or wedged
        # from a healthy idle one
        self.last_scan: float = 0.0
        # optional SLO engine (utils/slo.py): the standby is where
        # hq_federation_shard_up lives, so shard-availability burn rates
        # are evaluated here, piggybacked on the scan cadence
        self.slo = None

    async def run(self) -> None:
        while True:
            await asyncio.sleep(self.poll)
            try:
                await self.scan_once()
                self.last_scan = clock.monotonic()
            except Exception:  # noqa: BLE001 - watcher must outlive scans
                logger.exception("failover scan failed")
            if self.slo is not None:
                try:
                    for transition in self.slo.evaluate():
                        logger.warning(
                            "slo %s [%s]: %s (burn %.2f over %s)",
                            transition["slo"], transition["severity"],
                            transition["state"], transition["burn_rate"],
                            transition["window"][0],
                        )
                except Exception:  # noqa: BLE001 - alerting is advisory
                    logger.exception("slo evaluation failed")

    async def scan_once(self) -> None:
        fed = serverdir.load_federation(self.root)
        if fed is None:
            return
        # a promoted server that has since stopped (operator `server
        # stop`, a fence, a crash of its own) no longer covers its shard:
        # prune it so a LATER death of that shard is claimable again
        for shard_id, task in list(self._promoted_tasks.items()):
            if task.done():
                self.promoted.pop(shard_id, None)
                del self._promoted_tasks[shard_id]
        for shard_id in range(fed["shard_count"]):
            shard_dir = serverdir.shard_path(self.root, shard_id)
            lease = ShardLease(shard_dir, self.lease_timeout)
            state = lease.state()
            # liveness gauge for EVERY shard (own shard included): the
            # scan is the one place that reads all leases anyway, and a
            # scraper needs the dead shard's 0 from a surviving process
            _SHARD_UP.labels(shard_id).set(1.0 if state == "held" else 0.0)
            if shard_id == self.own_shard or shard_id in self.promoted:
                continue
            if state != "stale":
                # "absent" = never started or cleanly stopped: an operator
                # decision, not a death — nothing to fail over
                continue
            if self.eligible is not None and not self.eligible():
                logger.info(
                    "shard %d lease is stale but this peer is busy; "
                    "leaving the claim to another successor", shard_id,
                )
                continue
            await self.promote(shard_id, fed["shard_count"])

    async def promote(self, shard_id: int, shard_count: int) -> None:
        """Claim + boot a Server over the dead shard's dir. The Server's
        own start() performs the atomic lease acquisition (so a lost race
        aborts before any journal access) and the two-phase restore."""
        from hyperqueue_tpu.server.bootstrap import Server

        shard_dir = serverdir.shard_path(self.root, shard_id)
        kwargs = dict(self.server_kwargs)
        kwargs.update(
            server_dir=shard_dir,
            shard_id=shard_id,
            shard_count=shard_count,
            federation_root=self.root,
            lease_timeout=self.lease_timeout,
            journal_path=shard_journal_path(self.root, shard_id),
            promoted=True,
        )
        server = Server(**kwargs)
        t0 = time.perf_counter()
        try:
            await server.start()
        except (LeaseHeldError, LeaseRaceLost) as e:
            logger.info(
                "shard %d claim lost to a racing successor (%s); backing "
                "off", shard_id, e,
            )
            return
        except Exception:
            # claimed but could not finish promotion: tear down whatever
            # start() already brought up (the lease RENEW loop included —
            # a leaked renewer would keep the claim alive forever) and
            # release, so the next scan can try again instead of waiting
            # a full staleness window
            logger.exception("shard %d promotion failed", shard_id)
            try:
                await server.shutdown()
            except Exception:  # noqa: BLE001 - release is what matters
                logger.exception("shard %d promotion cleanup failed",
                                 shard_id)
                if server.lease is not None:
                    server.lease.release()
            return
        _FAILOVERS.inc()
        self.promoted[shard_id] = server
        self._promoted_tasks[shard_id] = asyncio.create_task(
            server.run_until_stopped()
        )
        logger.warning(
            "promoted to shard %d/%d in %.2fs (restore: %s)",
            shard_id, shard_count, time.perf_counter() - t0,
            server.last_restore,
        )

    async def shutdown(self) -> None:
        for server in self.promoted.values():
            server.stop()
        for task in self._promoted_tasks.values():
            try:
                await asyncio.wait_for(task, timeout=5.0)
            except (asyncio.TimeoutError, Exception):  # noqa: BLE001
                task.cancel()


async def standby_main(
    root: Path,
    server_kwargs: dict | None = None,
    lease_timeout: float = 15.0,
    poll: float | None = None,
    coordinate: bool = True,
    sample_interval: float = 1.0,
    metrics_port: int | None = None,
    metrics_host: str = "0.0.0.0",
    rebalance: bool = False,
) -> None:
    """`hq server start --standby`: a warm successor process.

    Waits for the federation descriptor, then watches every shard's
    lease and promotes into dead shards; optionally also runs the
    lending coordinator (the federation needs exactly one — run it on
    the standby, the one process with no shard of its own to favor).
    The process stays warm: the server modules, solver stack, and jax
    are already imported, so a promotion pays restore + bind time only.
    """
    while serverdir.load_federation(root) is None:
        await asyncio.sleep(0.25)
    # warm the heavy imports up front, not at promotion time
    from hyperqueue_tpu.server import bootstrap  # noqa: F401

    fed = serverdir.load_federation(root)
    coordinator = None
    if coordinate:
        coordinator = FederationCoordinator(
            root, sample_interval=sample_interval, rebalance=rebalance
        )
        coordinator.start()
    watcher = FailoverWatcher(
        root,
        server_kwargs=server_kwargs,
        lease_timeout=lease_timeout,
        poll=poll,
    )
    # the standby's registry is where hq_federation_shard_up lives, so
    # the shard-availability SLO is evaluated here (riding the scan
    # loop); transitions land in hq_slo_* gauges on this endpoint
    from hyperqueue_tpu.utils.slo import SloEngine

    watcher.slo = SloEngine()
    metrics_server = None
    if metrics_port is not None:
        # the standby is the process that SURVIVES shard deaths, so its
        # endpoint is where hq_federation_shard_up / failovers_total stay
        # scrapeable through a failover (ISSUE 15)
        from hyperqueue_tpu.utils.metrics import start_metrics_server

        def _probe_healthz():
            return True, {"role": "standby"}

        def _probe_readyz():
            # ready = the lease-scan loop is actually turning over: the
            # last COMPLETED scan is recent. A standby whose watcher task
            # died or wedged keeps serving /metrics (the endpoint is a
            # separate task) but must fail readiness — it can no longer
            # promote into a dead shard.
            stale_after = max(3.0 * watcher.poll, 1.0)
            if watcher.last_scan <= 0.0:
                return False, {"role": "standby",
                               "checks": {"scan": "never ran"}}
            age = clock.monotonic() - watcher.last_scan
            ok = age < stale_after
            detail = "ok" if ok else f"stale ({age:.1f}s)"
            return ok, {"role": "standby", "checks": {"scan": detail},
                        "promoted_shards": sorted(watcher.promoted)}

        metrics_server, bound = await start_metrics_server(
            REGISTRY, metrics_port, host=metrics_host,
            probes={"/healthz": _probe_healthz, "/readyz": _probe_readyz},
        )
        print(
            f"| standby metrics on http://{metrics_host}:{bound}/metrics"
            " (+ /healthz /readyz)",
            flush=True,
        )
    logger.warning(
        "standby ready: watching %d shard(s) at %s (lease timeout %.1fs)",
        fed["shard_count"], root, lease_timeout,
    )
    try:
        await watcher.run()
    finally:
        if coordinator is not None:
            coordinator.stop()
        if metrics_server is not None:
            metrics_server.close()
        await watcher.shutdown()
