"""Jobs layer: user-facing grouping of tasks.

Reference: crates/hyperqueue/src/server/{state.rs,job.rs} — a Job owns a set
of tasks (array or graph), per-task states with counters, a `max_fails` abort
policy, and open jobs that accept more tasks after submission. Job ids are the
upper half of each packed task id (ids.py), mirroring how the reference leaks
job ids into tako task ids (reference internal/common/ids.rs:5-60).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from hyperqueue_tpu.ids import IdCounter, make_task_id, task_id_task
from hyperqueue_tpu.server.task import TaskState
from hyperqueue_tpu.utils import clock

# client-visible task status strings
_STATUS = {
    TaskState.WAITING: "waiting",
    TaskState.READY: "waiting",
    TaskState.ASSIGNED: "waiting",
    TaskState.RUNNING: "running",
    TaskState.FINISHED: "finished",
    TaskState.FAILED: "failed",
    TaskState.CANCELED: "canceled",
}


@dataclass
class JobTaskInfo:
    job_task_id: int
    status: str = "waiting"
    error: str = ""
    worker_ids: list[int] = field(default_factory=list)
    # lifecycle timeline endpoints (submitted_at defaults to creation time;
    # restore overwrites it with the journal's job-submitted time so a
    # restored timeline keeps the original clock)
    submitted_at: float = field(default_factory=clock.now)
    started_at: float = 0.0
    finished_at: float = 0.0


@dataclass
class Job:
    job_id: int
    name: str
    submit_dir: str
    max_fails: int | None = None
    is_open: bool = False
    cancel_reason: str = ""  # why tasks were canceled (user / max_fails)
    submitted_at: float = field(default_factory=clock.now)
    # one record per submit: {"n_tasks": N, "request": wire request dict}
    # echoed in job detail (reference JobDetail.submit_descs)
    submits: list = field(default_factory=list)
    tasks: dict[int, JobTaskInfo] = field(default_factory=dict)  # job_task_id ->
    # unmaterialized lazy array tasks owned by this job (server/lazy.py
    # maintains the count; the task records themselves live in the core's
    # LazyStore until the scheduler materializes them)
    n_lazy: int = 0
    # chunked-submit streams (ingest plane): uid -> {"applied": set of
    # chunk indexes already ingested (exactly-once ack replay), "sealed"}.
    # While any stream is unsealed the job cannot terminate — a fast
    # worker finishing chunk k must not fire job-completed while chunk
    # k+1 is still on the wire.
    streams: dict = field(default_factory=dict)
    open_streams: int = 0
    counters: dict[str, int] = field(
        default_factory=lambda: {
            "running": 0,
            "finished": 0,
            "failed": 0,
            "canceled": 0,
        }
    )

    def n_tasks(self) -> int:
        return len(self.tasks) + self.n_lazy

    def n_waiting(self) -> int:
        return self.n_tasks() - sum(self.counters.values()) + self.counters["running"]

    def all_tasks_done(self) -> bool:
        """Every task submitted so far is terminal (used by `job wait`; an
        open job can be waited on without being closed)."""
        done = (
            self.counters["finished"]
            + self.counters["failed"]
            + self.counters["canceled"]
        )
        return done == self.n_tasks()

    def seal_streams(self) -> list:
        """Force-seal every chunk stream (job close / explicit cancel):
        a client that died mid-stream must not leave the job unable to
        terminate forever. Returns the uids that were still open, so the
        caller can journal the forced seal (restore must not resurrect
        the stream as open)."""
        sealed = [
            uid for uid, s in self.streams.items() if not s["sealed"]
        ]
        for stream in self.streams.values():
            stream["sealed"] = True
        self.open_streams = 0
        return sealed

    def is_terminated(self) -> bool:
        if self.is_open or self.open_streams > 0:
            return False
        done = (
            self.counters["finished"]
            + self.counters["failed"]
            + self.counters["canceled"]
        )
        return done == self.n_tasks()

    def status(self) -> str:
        # reference client/status.rs:18 job_status precedence: running >
        # waiting > failed > canceled > opened/finished (failures dominate
        # once nothing is left to run: a max-fails abort cancels the
        # remainder but the job's outcome is "failed")
        c = self.counters
        waiting = (self.n_tasks() - c["finished"] - c["failed"]
                   - c["canceled"] - c["running"])
        if c["running"]:
            return "running"
        if waiting > 0:
            return "waiting"
        if c["failed"]:
            return "failed"
        if c["canceled"]:
            return "canceled"
        return "opened" if self.is_open else "finished"

    def to_info(self) -> dict:
        return {
            "id": self.job_id,
            "name": self.name,
            "status": self.status(),
            "n_tasks": self.n_tasks(),
            "counters": dict(self.counters),
            "is_open": self.is_open,
            "submit_dir": self.submit_dir,
            "submitted_at": self.submitted_at,
            "cancel_reason": self.cancel_reason,
        }

    def to_detail(self) -> dict:
        info = self.to_info()
        info["submits"] = self.submits
        info["tasks"] = [
            {
                "id": t.job_task_id,
                "status": t.status,
                "error": t.error,
                "workers": t.worker_ids,
                "started_at": t.started_at,
                "finished_at": t.finished_at,
            }
            for t in sorted(self.tasks.values(), key=lambda t: t.job_task_id)
        ]
        return info


class JobManager:
    """Owns all jobs; receives task events from the tako-equivalent core via
    the EventSink bridge (server/bootstrap.py wires it)."""

    def __init__(self):
        self.jobs: dict[int, Job] = {}
        self.job_id_counter = IdCounter()

    def create_job(
        self,
        name: str,
        submit_dir: str,
        max_fails: int | None = None,
        is_open: bool = False,
        job_id: int | None = None,
    ) -> Job:
        if job_id is None:
            job_id = self.job_id_counter.next()
        else:
            self.job_id_counter.ensure_above(job_id)
        job = Job(
            job_id=job_id,
            name=name,
            submit_dir=submit_dir,
            max_fails=max_fails,
            is_open=is_open,
        )
        self.jobs[job_id] = job
        return job

    def attach_task(self, job: Job, job_task_id: int) -> int:
        job.tasks[job_task_id] = JobTaskInfo(job_task_id=job_task_id)
        return make_task_id(job.job_id, job_task_id)

    # --- event handlers (called from the EventSink bridge) ---------------
    def _task(self, job_id: int, task_id: int) -> tuple[Job, JobTaskInfo] | None:
        job = self.jobs.get(job_id)
        if job is None:
            return None
        info = job.tasks.get(task_id_task(task_id))
        if info is None:
            return None
        return job, info

    def on_task_started(self, job_id: int, task_id: int,
                        worker_ids: list[int],
                        started_at: float | None = None):
        found = self._task(job_id, task_id)
        if not found:
            return
        job, info = found
        if info.status != "running":
            job.counters["running"] += 1
        info.status = "running"
        info.worker_ids = worker_ids
        # started_at comes from the core task's t_started when available: a
        # reattach after a server restart re-announces a task that never
        # stopped running, and the timeline must keep the ORIGINAL start
        # instead of restarting the clock (no duplicate spawn phase)
        info.started_at = started_at or clock.now()

    def on_task_restarted(self, job_id: int, task_id: int):
        found = self._task(job_id, task_id)
        if not found:
            return
        job, info = found
        if info.status == "running":
            job.counters["running"] -= 1
        info.status = "waiting"
        info.worker_ids = []

    def _finish(self, job_id: int, task_id: int, status: str, error: str = ""):
        found = self._task(job_id, task_id)
        if not found:
            return None
        job, info = found
        if info.status == "running":
            job.counters["running"] -= 1
        if info.status in ("finished", "failed", "canceled"):
            return None  # already terminal
        info.status = status
        info.error = error
        info.finished_at = clock.now()
        job.counters[status] += 1
        return job

    def on_task_finished(self, job_id: int, task_id: int):
        return self._finish(job_id, task_id, "finished")

    def on_task_failed(self, job_id: int, task_id: int, message: str):
        """Returns task ids to cancel if max_fails is exceeded."""
        job = self._finish(job_id, task_id, "failed", message)
        if job is None:
            return []
        if job.max_fails is not None and job.counters["failed"] > job.max_fails:
            job.cancel_reason = (
                f"max_fails={job.max_fails} exceeded "
                f"({job.counters['failed']} tasks failed)"
            )
            return [
                make_task_id(job.job_id, t.job_task_id)
                for t in job.tasks.values()
                if t.status in ("waiting", "running")
            ]
        return []

    def on_task_canceled(self, job_id: int, task_id: int):
        self._finish(job_id, task_id, "canceled")
