"""Reactor: every mutation of the server core happens here.

Reference: crates/tako/src/internal/server/reactor.rs — on_new_worker,
on_remove_worker (requeue + crash counters), on_new_tasks (dep counting),
on_task_update, on_cancel_tasks. The scheduler is invoked between reactor
batches via an "ask_for_scheduling" flag + wakeup, never reentrantly
(reference server/comm.rs:61-101).
"""

from __future__ import annotations

import logging
import time as _time
from typing import Protocol

from hyperqueue_tpu.ids import task_id_job, task_id_task
from hyperqueue_tpu.scheduler import decision as decision_mod
from hyperqueue_tpu.scheduler.queues import (
    BLEVEL_STRIDE,
    Priority as Priority_t,
    decode_sched_blevel,
    decode_sched_job,
    encode_sched_priority,
)
from hyperqueue_tpu.scheduler.tick import Batch, create_batches, run_tick
from hyperqueue_tpu.server.core import Core
from hyperqueue_tpu.server.task import Task, TaskState
from hyperqueue_tpu.server.worker import Worker
from hyperqueue_tpu.transport.framing import attach_trace_wire
from hyperqueue_tpu.utils.metrics import REGISTRY
from hyperqueue_tpu.utils.trace import TRACER
from hyperqueue_tpu.utils import clock

logger = logging.getLogger(__name__)

# tick telemetry in the process-wide metrics plane (utils/metrics.py):
# per-phase latency histograms plus assignment counters. Observed once per
# tick (not per task) so the cost is a handful of dict ops per schedule().
_TICK_PHASE_SECONDS = REGISTRY.histogram(
    "hq_tick_phase_seconds",
    "scheduler tick latency per phase (snapshot/batches/gangs/assemble/"
    "solve/mapping/prefill/decide/total)",
    labels=("phase",),
)
_TICKS_TOTAL = REGISTRY.counter(
    "hq_scheduler_ticks_total", "scheduling ticks run"
)
_ASSIGNED_TOTAL = REGISTRY.counter(
    "hq_scheduler_assigned_tasks_total",
    "tasks assigned to workers by the dense solve + gang phases",
)
_PREFILLED_TOTAL = REGISTRY.counter(
    "hq_scheduler_prefilled_tasks_total",
    "tasks proactively prefilled onto busy workers",
)
_RETRACTED_TOTAL = REGISTRY.counter(
    "hq_scheduler_retracts_total",
    "prefilled tasks asked back from workers",
    labels=("reason",),
)
_SOLVE_GANG_GROUPS = REGISTRY.counter(
    "hq_solve_gang_groups",
    "multi-node gangs co-scheduled atomically by the fused dense solve "
    "(all-or-nothing column groups, --scheduler greedy-fused)",
)
_SOLVE_LOOKAHEAD_DEPTH = REGISTRY.gauge(
    "hq_solve_lookahead_depth",
    "critical-path depth (b-level) of the deepest task in the last "
    "dependency-carrying submit batch",
)
_POLICY_JAIN = REGISTRY.gauge(
    "hq_policy_fairness_jain",
    "Jain fairness index of per-job running resource usage at the last "
    "tick that had work running (1.0 = perfectly even; --policy-file "
    "fairness fold, scheduler/policy.py)",
)
_POLICY_HIT_RATE = REGISTRY.gauge(
    "hq_policy_predictor_hit_rate",
    "fraction of runtime-predictor lookups that had a learned EWMA "
    "(scheduler/predict.py; 0 until the table warms or is journal-seeded)",
)
_POLICY_BOOST_MAX = REGISTRY.gauge(
    "hq_policy_boost_max",
    "largest per-job priority boost (fairness + prediction) applied to "
    "the last scheduling tick's batch sort",
)

# at most this many gang rows ride one fused solve: gangs are rare and a
# deep mn backlog must not grow the padded batch axis (each row holds its
# selected workers for the whole scan, so later rows see a drained pool
# anyway — exactly like the host phase's one-reservation-at-a-time drain)
MAX_FUSED_GANG_ROWS = 16

# max tasks queued on a worker beyond its current capacity. The reference
# uses 40 (scheduler/state.rs:4-21) with its own tick cadence; ours is sized
# so that the refill round-trip (scheduler min-delay + two plane RTTs +
# batch processing, ~35 ms measured) amortized over a full prefill batch
# stays well under the <0.1 ms/task overhead target even when every task
# completes instantly.
PREFILL_MAX = 512


class Comm(Protocol):
    def send_compute(self, worker_id: int, tasks: list[dict]) -> None: ...
    def send_cancel(self, worker_id: int, task_ids: list[int]) -> None: ...
    def send_retract(
        self, worker_id: int, task_refs: list[tuple[int, int]]
    ) -> None: ...  # (task_id, instance_id) pairs
    def ask_for_scheduling(self) -> None: ...


class EventSink(Protocol):
    """Upward channel to the product (jobs) layer.

    Reference: the EventProcessor trait (tako events.rs:7-33) — the only way
    task-graph news reaches jobs/journal/clients.
    """

    def on_task_started(self, task_id: int, instance_id: int,
                        worker_ids: list[int], variant: int = 0,
                        wtrace: dict | None = None) -> None: ...
    def on_task_restarted(self, task_id: int) -> None: ...
    def on_task_finished(self, task_id: int,
                         wtrace: dict | None = None) -> None: ...
    def on_task_failed(self, task_id: int, message: str,
                       wtrace: dict | None = None) -> None: ...
    def on_task_canceled(self, task_id: int) -> None: ...
    def on_worker_new(self, worker: Worker) -> None: ...
    def on_worker_lost(self, worker_id: int, reason: str) -> None: ...


def on_new_tasks(core: Core, comm: Comm, tasks: list[Task]) -> None:
    """Insert tasks, count dependencies, enqueue the ready ones.

    Reference reactor.rs:188 (on_new_tasks).
    """
    for task in tasks:
        core.tasks[task.task_id] = task
    _apply_blevel_lookahead(core, tasks)
    for task in tasks:
        unfinished = 0
        for dep_id in task.deps:
            dep = core.tasks.get(dep_id)
            if dep is None or dep.state is TaskState.FINISHED:
                continue
            dep.consumers.add(task.task_id)
            unfinished += 1
        task.unfinished_deps = unfinished
        if unfinished == 0:
            _make_ready(core, task)
    comm.ask_for_scheduling()


def _apply_blevel_lookahead(core: Core, tasks: list[Task]) -> None:
    """Critical-path (b-level) lookahead over one submitted batch.

    Re-encodes the scheduler-priority component (scheduler/queues.py
    encoding) so that within a job, a task with more dependent work below
    it outranks its siblings: blevel = 1 + max over in-batch consumers,
    0 for sinks. Tasks carrying raw test-literal priorities are left
    untouched, so explicit priority assertions stay bit-exact; production
    submits always carry the encoding.
    """
    if not any(t.deps for t in tasks):
        return
    batch = {t.task_id: t for t in tasks}
    n_children: dict[int, int] = {}
    for t in tasks:
        for dep_id in t.deps:
            if dep_id in batch:
                n_children[dep_id] = n_children.get(dep_id, 0) + 1
    blevel = dict.fromkeys(batch, 0)
    stack = [t for t in tasks if n_children.get(t.task_id, 0) == 0]
    while stack:
        t = stack.pop()
        lvl = blevel[t.task_id] + 1
        for dep_id in t.deps:
            if dep_id not in batch:
                continue
            if lvl > blevel[dep_id]:
                blevel[dep_id] = lvl
            n_children[dep_id] -= 1
            if n_children[dep_id] == 0:
                stack.append(batch[dep_id])
    depth = 0
    for tid, lvl in blevel.items():
        if lvl <= 0:
            continue
        t = batch[tid]
        user, sched = t.priority
        if sched > -BLEVEL_STRIDE:
            continue  # raw literal scheduler priority: no blevel channel
        t.priority = (
            user, encode_sched_priority(decode_sched_job(sched), lvl)
        )
        if lvl > depth:
            depth = lvl
    if depth:
        _SOLVE_LOOKAHEAD_DEPTH.set(depth)


def _make_ready(core: Core, task: Task) -> None:
    task.state = TaskState.READY
    task.t_ready = clock.now()
    if core.paused_jobs:
        job_id = task_id_job(task.task_id)
        if job_id in core.paused_jobs:
            # the job is paused: the task is READY but held out of the
            # queues until `hq job resume` re-enqueues it
            core.paused_held.setdefault(job_id, set()).add(task.task_id)
            return
    rqv = core.rq_map.get_variants(task.rq_id)
    if rqv.is_multi_node:
        core.mn_queue.append(task.task_id)
        core.mn_queue.sort(key=lambda t: core.tasks[t].priority, reverse=True)
    else:
        core.queues.add(task.rq_id, task.priority, task.task_id)


def pause_jobs(core: Core, comm: Comm, job_ids: list[int]) -> tuple[int, int]:
    """Hold the READY tasks of these jobs out of the scheduler queues.

    Tasks already RUNNING (or assigned with resources accounted) are not
    recalled — pause gates placement, it does not preempt.  PREFILLED
    backlog (queued on a worker, not started) IS asked back via the
    retract path: a successful retract requeues through _make_ready,
    which holds the task because the job is paused.  WAITING tasks whose
    dependencies finish while paused are held the same way.  Returns
    (newly held, retracts sent)."""
    wanted = set(job_ids)
    core.paused_jobs |= wanted
    held = 0
    for job_id in wanted:
        # lazy array segments leave the scheduler levels as whole chunks
        # (no materialization — a paused 1M-task array stays O(chunks));
        # resume_jobs re-enqueues them the same way
        held += core.lazy.detach_job(core, job_id)
    for _rq_id, queue in core.queues.items():
        for task_id in queue.all_tasks():
            if task_id_job(task_id) in wanted:
                queue.remove(task_id)
                core.paused_held.setdefault(
                    task_id_job(task_id), set()
                ).add(task_id)
                held += 1
    for task_id in list(core.mn_queue):
        if task_id_job(task_id) in wanted:
            core.mn_queue.remove(task_id)
            _clear_mn_reservations(core, task_id)
            core.paused_held.setdefault(
                task_id_job(task_id), set()
            ).add(task_id)
            held += 1
    retracts: dict[int, list[tuple[int, int]]] = {}
    for worker in core.workers.values():
        for task_id in worker.prefilled_tasks:
            if task_id_job(task_id) not in wanted:
                continue
            task = core.tasks[task_id]
            if task.retract_pending:
                continue  # an earlier retract already covers it
            task.retract_pending = True
            retracts.setdefault(worker.worker_id, []).append(
                (task_id, task.instance_id)
            )
    n_retracted = 0
    for worker_id, refs in retracts.items():
        _RETRACTED_TOTAL.labels("pause").inc(len(refs))
        n_retracted += len(refs)
        comm.send_retract(worker_id, refs)
    return held, n_retracted


def resume_jobs(core: Core, comm: Comm, job_ids: list[int]) -> int:
    """Re-enqueue the held READY tasks of paused jobs."""
    released = 0
    mn_added = False
    for job_id in job_ids:
        core.paused_jobs.discard(job_id)
        released += core.lazy.requeue_job(core, job_id)
        held = core.paused_held.pop(job_id, None)
        if not held:
            continue
        for task_id in sorted(held):
            task = core.tasks.get(task_id)
            if (
                task is None
                or task.is_done
                or task.state is not TaskState.READY
            ):
                continue
            if core.rq_map.get_variants(task.rq_id).is_multi_node:
                core.mn_queue.append(task_id)
                mn_added = True
            else:
                core.queues.add(task.rq_id, task.priority, task_id)
            released += 1
    if mn_added:
        core.mn_queue.sort(key=lambda t: core.tasks[t].priority, reverse=True)
    if released:
        comm.ask_for_scheduling()
    return released


def recall_tasks(core: Core, comm: Comm, task_ids: list[int]) -> int:
    """Recall ASSIGNED/RUNNING tasks from their workers (migration
    export, ISSUE 17): release resources, cancel the incarnation on the
    worker, bump the instance — a late uplink from the recalled
    incarnation then carries a stale instance id and is discarded — and
    requeue through _make_ready (the caller pauses the job first, so the
    task lands in the pause ledger, not a queue).  Never charges the
    crash counter: the recall is deliberate, not a worker failure."""
    per_worker: dict[int, list[int]] = {}
    recalled = 0
    for tid in task_ids:
        task = core.tasks.get(tid)
        if task is None or task.is_done:
            continue
        if task.state not in (TaskState.ASSIGNED, TaskState.RUNNING):
            continue
        notify = list(task.mn_workers) or [task.assigned_worker]
        _release_task_resources(core, task)
        for wid in notify:
            if wid:
                per_worker.setdefault(wid, []).append(tid)
        task.increment_instance()
        task.state = TaskState.WAITING
        _make_ready(core, task)
        recalled += 1
    for wid, tids in per_worker.items():
        comm.send_cancel(wid, tids)
    return recalled


def on_new_worker(core: Core, comm: Comm, events: EventSink, worker: Worker) -> None:
    core.workers[worker.worker_id] = worker
    core.bump_membership()
    events.on_worker_new(worker)
    comm.ask_for_scheduling()


def on_remove_worker(
    core: Core, comm: Comm, events: EventSink, worker_id: int, reason: str
) -> None:
    """Worker lost: requeue its tasks with crash accounting.

    Reference reactor.rs:64 — sn tasks go back to the queues with
    crash_counter+1 and die at the crash limit (deliberate stops are
    exempt). mn tasks: a RUNNING gang losing a NON-root member keeps
    running on the root with the member dropped (reference
    RunningMultiNode retain; CHANGELOG v0.25.1); root loss — or any
    member loss before the gang reports running — tears the gang down
    and reschedules it.
    """
    worker = core.workers.pop(worker_id, None)
    if worker is None:
        return
    core.bump_membership()
    events.on_worker_lost(worker_id, reason)
    for task_id in list(worker.prefilled_tasks):
        task = core.tasks.get(task_id)
        if task is None or task.is_done:
            continue
        task.prefilled = False
        task.retract_pending = False
        task.assigned_worker = 0
        task.increment_instance()
        task.state = TaskState.WAITING
        _make_ready(core, task)
    for task_id in list(worker.assigned_tasks):
        task = core.tasks.get(task_id)
        if task is None or task.is_done:
            continue
        was_running = task.state is TaskState.RUNNING
        task.assigned_worker = 0
        task.increment_instance()
        # never-restart tasks fail on ANY worker loss while running, even a
        # deliberate stop (reference reactor.rs:166, outside the
        # reason.is_failure() gate)
        if was_running and task.never_restart:
            task.state = TaskState.FAILED
            _propagate_failure(
                core, events, task,
                "task was running on a lost worker while never-restart was set",
            )
            continue
        # a deliberate stop (hq worker stop, idle/time limit) restarts the
        # task without charging its crash counter (reference CrashLimit)
        if was_running and not worker.clean_stop and task.crashed():
            task.state = TaskState.FAILED
            _propagate_failure(core, events, task, "worker lost too many times")
            continue
        if was_running:
            events.on_task_restarted(task_id)
        task.state = TaskState.WAITING
        _make_ready(core, task)
    if worker.mn_task:
        task = core.tasks.get(worker.mn_task)
        if task is not None and not task.is_done:
            if (
                task.state is TaskState.RUNNING
                and task.mn_workers
                and worker_id != task.mn_workers[0]
            ):
                # non-root member lost while RUNNING: the task keeps running
                # on the root — the user's launcher inside the task decides
                # what a dead node means (reference reactor.rs
                # RunningMultiNode ws.retain; CHANGELOG v0.25.1)
                task.mn_workers = tuple(
                    w for w in task.mn_workers if w != worker_id
                )
            else:
                _teardown_gang(core, comm, events, task,
                               lost_worker=worker_id,
                               clean=worker.clean_stop)
    comm.ask_for_scheduling()


def _teardown_gang(
    core: Core, comm: Comm, events: EventSink, task: Task, lost_worker: int,
    clean: bool = False
) -> None:
    root = task.mn_workers[0] if task.mn_workers else 0
    core.bump_membership()
    for wid in task.mn_workers:
        w = core.workers.get(wid)
        if w is not None:
            w.mn_task = 0
            # cancel on surviving workers for ASSIGNED too: the compute
            # message may already be in flight to the root even though
            # task_running has not come back yet; worker-side cancel of an
            # unknown task id is a no-op, so this is always safe
            if wid != lost_worker and task.state in (
                TaskState.ASSIGNED,
                TaskState.RUNNING,
            ):
                comm.send_cancel(wid, [task.task_id])
    task.mn_workers = ()
    task.increment_instance()
    if lost_worker == root and task.state is TaskState.RUNNING:
        if task.never_restart:
            task.state = TaskState.FAILED
            _propagate_failure(
                core, events, task,
                "task was running on a lost worker while never-restart was "
                "set",
            )
            return
        if not clean and task.crashed():
            task.state = TaskState.FAILED
            _propagate_failure(
                core, events, task, "gang root lost too many times"
            )
            return
    if task.state is TaskState.RUNNING:
        events.on_task_restarted(task.task_id)
    task.state = TaskState.WAITING
    _make_ready(core, task)


def on_task_reattached(
    core: Core, events: EventSink, task: Task, worker: Worker
) -> None:
    """A reconnecting worker claimed a restored maybe-running task.

    The task was held out of the queues by restore (server.reattach_pending)
    with its pre-crash instance id and chosen variant preserved; the worker
    proved it still runs that exact incarnation, so it is attached to the
    new worker record as RUNNING — no requeue, no crash-counter charge, no
    instance bump (the worker's in-flight completion message must still
    match)."""
    task.state = TaskState.RUNNING
    task.assigned_worker = worker.worker_id
    if not task.t_started:
        # restore pre-seeds t_started from the journal's task-started time;
        # a reattach must NOT restart the clock — the task kept running
        # through the outage and its timeline is one unbroken span
        task.t_started = clock.now()
    worker.assign(
        task.task_id,
        core.variant_amounts(task.rq_id, task.assigned_variant, worker),
    )
    events.on_task_started(
        task.task_id, task.instance_id, [worker.worker_id],
        task.assigned_variant,
    )


def requeue_reattach_expired(core: Core, comm: Comm, task: Task) -> None:
    """No worker reclaimed this restored maybe-running task within the
    reattach window: fence out the (presumed dead) pre-crash incarnation,
    then queue it like any other ready task. The fence jumps to this
    boot's generation base — the crashed boot may have requeued/restarted
    the task past the journaled instance inside its lost tail, so a plain
    +1 could collide with an incarnation that still runs somewhere. No
    crash-counter charge — a server restart is not the task's fault."""
    task.fence_instance(core.instance_fence_floor)
    task.state = TaskState.WAITING
    _make_ready(core, task)
    comm.ask_for_scheduling()


def on_task_running(
    core: Core, events: EventSink, task_id: int, instance_id: int,
    wtrace: dict | None = None
) -> None:
    task = core.tasks.get(task_id)
    if task is None or task.instance_id != instance_id or task.is_done:
        return  # stale message from a previous incarnation
    if task.state is TaskState.ASSIGNED:
        if task.prefilled:
            # the prefilled task actually started: account its resources now
            worker = core.workers.get(task.assigned_worker)
            if worker is not None:
                worker.prefilled_tasks.discard(task_id)
                worker.assign(
                    task_id,
                    core.variant_amounts(
                        task.rq_id, task.assigned_variant, worker
                    ),
                )
            task.prefilled = False
            task.retract_pending = False
        task.state = TaskState.RUNNING
        task.t_started = clock.now()
        workers = list(task.mn_workers) or [task.assigned_worker]
        events.on_task_started(
            task_id, instance_id, workers, task.assigned_variant,
            wtrace=wtrace,
        )


def on_task_finished(
    core: Core, comm: Comm, events: EventSink, task_id: int, instance_id: int,
    wtrace: dict | None = None
) -> None:
    task = core.tasks.get(task_id)
    if task is None or task.instance_id != instance_id or task.is_done:
        return
    _release_task_resources(core, task)
    task.state = TaskState.FINISHED
    events.on_task_finished(task_id, wtrace=wtrace)
    for consumer_id in sorted(task.consumers):
        consumer = core.tasks.get(consumer_id)
        if consumer is None or consumer.state is not TaskState.WAITING:
            continue
        consumer.unfinished_deps -= 1
        if consumer.unfinished_deps == 0:
            _make_ready(core, consumer)
    task.consumers.clear()
    comm.ask_for_scheduling()


def on_task_failed(
    core: Core,
    comm: Comm,
    events: EventSink,
    task_id: int,
    instance_id: int,
    message: str,
    wtrace: dict | None = None,
) -> None:
    task = core.tasks.get(task_id)
    if task is None or task.instance_id != instance_id or task.is_done:
        return
    _release_task_resources(core, task)
    task.state = TaskState.FAILED
    _propagate_failure(core, events, task, message, wtrace=wtrace)
    comm.ask_for_scheduling()


def _propagate_failure(
    core: Core, events: EventSink, task: Task, message: str,
    wtrace: dict | None = None
) -> None:
    """Fail the task and transitively cancel waiting consumers."""
    events.on_task_failed(task.task_id, message, wtrace=wtrace)
    stack = sorted(task.consumers)
    task.consumers.clear()
    while stack:
        tid = stack.pop()
        consumer = core.tasks.get(tid)
        if consumer is None or consumer.is_done:
            continue
        consumer.state = TaskState.CANCELED
        events.on_task_canceled(tid)
        stack.extend(sorted(consumer.consumers))
        consumer.consumers.clear()


def on_cancel_tasks(
    core: Core, comm: Comm, events: EventSink, task_ids: list[int]
) -> list[int]:
    """Cancel tasks (and transitively their consumers). Returns ids actually
    canceled. Reference reactor.rs:706."""
    canceled: list[int] = []
    stack = list(task_ids)
    per_worker: dict[int, list[int]] = {}
    while stack:
        tid = stack.pop()
        task = core.tasks.get(tid)
        if task is None or task.is_done:
            continue
        stack.extend(sorted(task.consumers))
        task.consumers.clear()
        if task.state is TaskState.READY:
            held = core.paused_held.get(task_id_job(tid))
            if held is not None and tid in held:
                held.discard(tid)  # paused: held out of the queues
            else:
                rqv = core.rq_map.get_variants(task.rq_id)
                if rqv.is_multi_node:
                    if tid in core.mn_queue:
                        core.mn_queue.remove(tid)
                    _clear_mn_reservations(core, tid)
                else:
                    core.queues.remove(task.rq_id, tid)
        elif task.state in (TaskState.ASSIGNED, TaskState.RUNNING):
            notify = list(task.mn_workers) or [task.assigned_worker]
            _release_task_resources(core, task)
            for wid in notify:
                if wid:
                    per_worker.setdefault(wid, []).append(tid)
        task.state = TaskState.CANCELED
        events.on_task_canceled(tid)
        canceled.append(tid)
    for wid, tids in per_worker.items():
        comm.send_cancel(wid, tids)
    if canceled:
        comm.ask_for_scheduling()
    return canceled


def _release_task_resources(core: Core, task: Task) -> None:
    if task.mn_workers:
        core.bump_membership()
        for wid in task.mn_workers:
            w = core.workers.get(wid)
            if w is not None:
                w.mn_task = 0
        task.mn_workers = ()
        return
    worker = core.workers.get(task.assigned_worker)
    if worker is not None:
        if task.prefilled:
            worker.prefilled_tasks.discard(task.task_id)
            task.prefilled = False
            task.retract_pending = False
        elif task.task_id in worker.assigned_tasks:
            amounts = core.variant_amounts(
                task.rq_id, task.assigned_variant, worker
            )
            worker.unassign(task.task_id, amounts)
    task.assigned_worker = 0


def _mn_member_eligible(worker: Worker, req) -> bool:
    """Can this worker serve as a gang member for `req`?

    Reference worker.rs:273-344 (is_capable_to_run): remaining lifetime must
    cover the request's min_time; resource entries (absent on reference mn
    requests, permitted here) must fit the empty worker.
    """
    if worker.lifetime_secs() < req.min_time_secs:
        return False
    for entry in req.entries:
        if worker.resources.amount(entry.resource_id) < entry.amount:
            return False
    return True


def _rqv_fit_count(resources, rqv) -> int:
    """How many tasks of this request class the worker could run AT ONCE
    on empty resources — the best variant's min over entries of
    pool // amount. ALL-policy entries (amount 0) take a whole pool:
    count 1. Used to bound displacement retraction to what a worker
    could plausibly absorb from the displacing batch."""
    best = 0
    for req in rqv.variants:
        fit: int | None = None
        for entry in req.entries:
            if entry.amount <= 0:
                fit = 1
                break
            count = resources.amount(entry.resource_id) // entry.amount
            fit = count if fit is None else min(fit, count)
        if fit is None:
            # no resource entries: bounded only by the task-count slots
            fit = resources.task_max_count()
        best = max(best, fit)
    return max(best, 1)


def _top_sn_priority(core: Core) -> Priority_t | None:
    """Highest priority among ready single-node tasks that at least one
    worker is capable of running (an unschedulable high-priority task must
    not suppress gang reservations forever)."""
    best: Priority_t | None = None
    for rq_id, queue in core.queues.items():
        sizes = queue.priority_sizes()
        if not sizes or (best is not None and sizes[0][0] <= best):
            continue
        rqv = core.rq_map.get_variants(rq_id)
        if any(
            w.resources.is_capable_of_rqv(rqv) for w in core.workers.values()
        ):
            best = sizes[0][0]
    return best


def _sn_runnable_on(core: Core, above_user_priority: int, workers) -> bool:
    """Is some ready single-node class with user priority strictly above
    `above_user_priority` runnable on one of these (idle) workers right
    now? (User-priority comparison only — the tuple's second component is
    -job_id and an older job must not permanently outrank a gang.)"""
    for rq_id, queue in core.queues.items():
        sizes = queue.priority_sizes()
        if not any(p[0] > above_user_priority for p, n in sizes if n > 0):
            continue
        rqv = core.rq_map.get_variants(rq_id)
        if any(w.resources.is_capable_of_rqv(rqv) for w in workers):
            return True
    return False


def _clear_mn_reservations(core: Core, task_id: int) -> None:
    for w in core.workers.values():
        if w.mn_reserved == task_id:
            w.mn_reserved = 0
            core.bump_membership()


def _apply_fused_gangs(
    core: Core, mapped, per_worker_msgs: dict, now: float
) -> tuple[list, int]:
    """Apply the gang sentinel assignments (variant == -1) a fused solve
    emitted, validating against CURRENT state — a pipelined solve maps one
    tick late, so a member may have been claimed, drained or disconnected
    while the solve was in flight; the whole gang is then dropped and
    retried next tick (it is still in core.mn_queue).

    Returns (the non-gang assignments, gangs applied)."""
    gang_cells: dict[int, list[int]] = {}
    sn = []
    for a in mapped:
        if a[3] == -1:
            gang_cells.setdefault(a[0], []).append(a[1])
        else:
            sn.append(a)
    n_gangs = 0
    for task_id, member_ids in gang_cells.items():
        task = core.tasks.get(task_id)
        if task is None or task.is_done or task_id not in core.mn_queue:
            continue
        rqv = core.rq_map.get_variants(task.rq_id)
        n_nodes = rqv.variants[0].n_nodes
        members = [core.workers.get(wid) for wid in member_ids]
        if len(members) != n_nodes or any(
            w is None or w.mn_task or w.draining or not w.is_idle()
            for w in members
        ):
            continue  # stale solve: the gang retries next tick
        core.mn_queue.remove(task_id)
        core.bump_membership()
        for w in members:
            w.mn_task = task_id
        task.mn_workers = tuple(w.worker_id for w in members)
        task.state = TaskState.ASSIGNED
        task.t_assigned = now
        root = members[0]
        msg = _compute_message(core, task, variant=0)
        msg["node_ids"] = list(task.mn_workers)
        msg["node_hostnames"] = [
            core.workers[wid].configuration.hostname
            for wid in task.mn_workers
        ]
        per_worker_msgs.setdefault(root.worker_id, []).append(msg)
        n_gangs += 1
    if n_gangs:
        _SOLVE_GANG_GROUPS.inc(n_gangs)
    return sn, n_gangs


def schedule(
    core: Core, comm: Comm, events: EventSink, model, prefill: bool = True
) -> int:
    """Run one scheduling tick: gangs first (host-side), then the dense solve.

    Returns the number of tasks assigned (prefilled tasks not counted).
    Reference scheduler/main.rs:48 (run_scheduling = batches -> solver ->
    mapping -> send). `prefill=False` disables proactive filling (used by
    deterministic scheduler tests).
    """
    assigned = 0
    prefilled = 0
    gang_assigned = 0
    per_worker_msgs: dict[int, list[dict]] = {}
    # per-phase latency breakdown of THIS tick (ms), recorded into
    # core.tick_stats at the end and surfaced via `hq server stats`
    phases: dict = {}
    _t_tick = _time.perf_counter()
    # one wall-clock stamp per tick: every task assigned this tick shares it
    # (the timeline's resolution is the tick itself)
    now = clock.now()
    # DecisionRecord collection (scheduler/decision.py + utils/flight.py):
    # gang_unplaced gathers per-gang reasons during the gang phase,
    # decision_info receives the solver verdict from run_tick, and the
    # leftover classification runs once at the end of the tick
    record_decision = core.flight.enabled
    gang_unplaced: list[dict] = []
    decision_info: dict = {}

    # --- multi-node gangs: all-or-nothing N eligible workers from one
    # group.  Per-member eligibility matches the reference's
    # is_capable_to_run_rqv (worker.rs:273-344): enough remaining lifetime
    # for the request's min_time (mn entries are ignored by design, like the
    # reference; if present they are checked too).  A gang that cannot be
    # placed yet RESERVES workers so they drain (see Worker.mn_reserved) —
    # unless strictly-higher-priority sn work is still pending, which keeps
    # the reference's priority interleaving (the MILP schedules higher
    # classes first and only blocks lower ones, solver.rs:479-518). ---
    _t_phase = _time.perf_counter()
    # fused mode (--scheduler greedy-fused): gangs become all-or-nothing
    # column groups INSIDE the dense solve instead of this host phase —
    # but only when the dense snapshot can serve the tick (tick_cache
    # refuses min-utilization workers; the scratch/mu path keeps the host
    # gang semantics)
    fused_tick = core.fused_solve and not any(
        w.configuration.min_utilization > 0.001
        for w in core.workers.values()
        if not (w.mn_task or w.mn_reserved or w.draining)
    )
    if core.mn_queue and not fused_tick:
        top_sn = _top_sn_priority(core)
        remaining_mn = []
        for task_id in core.mn_queue:
            task = core.tasks.get(task_id)
            if task is None or task.is_done:
                _clear_mn_reservations(core, task_id)
                continue
            rqv = core.rq_map.get_variants(task.rq_id)
            req = rqv.variants[0]
            n_nodes = req.n_nodes
            groups: dict[str, list[Worker]] = {}
            for w in core.workers.values():
                if w.mn_task or w.mn_reserved not in (0, task_id):
                    continue
                if w.draining or not _mn_member_eligible(w, req):
                    continue
                groups.setdefault(w.group, []).append(w)
            chosen: list[Worker] | None = None
            for members in groups.values():
                idle = [w for w in members if w.is_idle()]
                if len(idle) >= n_nodes:
                    # prefer the workers already drained for this gang so
                    # other reservations lift as soon as possible
                    idle.sort(
                        key=lambda w: (w.mn_reserved != task_id, w.worker_id)
                    )
                    chosen = idle[:n_nodes]
                    break
            deferred_for_sn = False
            if (
                chosen is not None
                and top_sn is not None
                and top_sn[0] > task.priority[0]
                and _sn_runnable_on(core, task.priority[0], chosen)
            ):
                # strictly-higher-priority single-node work can use these
                # workers: it goes first this tick (the reference MILP
                # blocks the gang the same way, solver.rs:479-518); the
                # gang retries on what the sn solve leaves idle
                chosen = None
                deferred_for_sn = True
            if chosen is None:
                remaining_mn.append(task_id)
                if record_decision:
                    if deferred_for_sn:
                        # the gang WAS placeable: the solver deferred it
                        # behind higher-priority single-node work, which is
                        # not a group shortfall
                        reason = decision_mod.REASON_SOLVER_DEFERRED
                        detail = (
                            f"{n_nodes} idle same-group workers are "
                            "available, but strictly-higher-priority "
                            "single-node work goes first this tick"
                        )
                    else:
                        best = max(groups.values(), key=len, default=None)
                        n_idle = (
                            sum(1 for w in best if w.is_idle())
                            if best else 0
                        )
                        reason = decision_mod.REASON_GANG_INCOMPLETE
                        detail = (
                            f"needs {n_nodes} idle same-group workers; "
                            f"largest eligible group has "
                            f"{len(best) if best else 0} "
                            f"({n_idle} idle)"
                        )
                    gang_unplaced.append({
                        "rq_id": task.rq_id,
                        "job": task_id_job(task_id),
                        "task": task_id_task(task_id),
                        "priority": task.priority[0],
                        "count": 1,
                        "reason": reason,
                        "detail": detail,
                    })
                # user-priority comparison only: the scheduler component of
                # the tuple is -job_id, and an older sn job must not
                # strictly outrank a same-user-priority gang forever
                if top_sn is not None and top_sn[0] > task.priority[0]:
                    # higher-priority sn work outranks this gang; do not
                    # hold workers hostage for it yet
                    _clear_mn_reservations(core, task_id)
                    continue
                # reserve (and start draining) n_nodes eligible workers in
                # the group closest to satisfying the gang
                best = max(groups.values(), key=len, default=None)
                if best is None or len(best) < n_nodes:
                    # no group can currently host the gang at all; release
                    # any stale reservations rather than wedging workers
                    _clear_mn_reservations(core, task_id)
                    continue
                best.sort(
                    key=lambda w: (
                        not w.is_idle(),
                        len(w.assigned_tasks) + len(w.prefilled_tasks),
                        w.worker_id,
                    )
                )
                target = {w.worker_id for w in best[:n_nodes]}
                for w in core.workers.values():
                    if w.mn_reserved == task_id and w.worker_id not in target:
                        w.mn_reserved = 0
                        core.bump_membership()
                for w in best[:n_nodes]:
                    newly_reserved = w.mn_reserved != task_id
                    if newly_reserved:
                        core.bump_membership()
                    w.mn_reserved = task_id
                    if newly_reserved and w.prefilled_tasks:
                        # steal the queued backlog back so the drain is
                        # bounded by the currently-running tasks only (sent
                        # once per reservation, not per tick); mark pending
                        # or on_retract_response drops the answers
                        refs = []
                        for tid in sorted(w.prefilled_tasks):
                            victim = core.tasks[tid]
                            if victim.retract_pending:
                                continue  # an earlier retract already covers it
                            victim.retract_pending = True
                            refs.append((tid, victim.instance_id))
                        if refs:
                            _RETRACTED_TOTAL.labels("gang-drain").inc(
                                len(refs)
                            )
                            comm.send_retract(w.worker_id, refs)
                continue
            _clear_mn_reservations(core, task_id)
            core.bump_membership()
            for w in chosen:
                w.mn_task = task_id
            task.mn_workers = tuple(w.worker_id for w in chosen)
            task.state = TaskState.ASSIGNED
            task.t_assigned = now
            root = chosen[0]
            msg = _compute_message(core, task, variant=0)
            msg["node_ids"] = list(task.mn_workers)
            msg["node_hostnames"] = [
                core.workers[wid].configuration.hostname
                for wid in task.mn_workers
            ]
            per_worker_msgs.setdefault(root.worker_id, []).append(msg)
            assigned += 1
            gang_assigned += 1
        core.mn_queue = remaining_mn
        phases["gangs"] = (_time.perf_counter() - _t_phase) * 1e3
        TRACER.record("scheduler/gangs", _time.perf_counter() - _t_phase)

    # --- fused gangs: the head of the mn queue rides the dense solve as
    # all-or-nothing gang rows (scheduler/tick.py Batch.gang_nodes; kernel
    # semantics in ops/assign.py scan_batches).  Tasks STAY in mn_queue
    # until their sentinel assignments come back and validate — a stale
    # pipelined solve simply drops its gang and the next tick retries. ---
    fused_gang_batches: list[Batch] = []
    if fused_tick and core.mn_queue:
        remaining_mn = []
        for task_id in core.mn_queue:
            task = core.tasks.get(task_id)
            if task is None or task.is_done:
                _clear_mn_reservations(core, task_id)
                continue
            remaining_mn.append(task_id)
            if len(fused_gang_batches) < MAX_FUSED_GANG_ROWS:
                # fused mode never reserves: lift any reservation left
                # over from a host-phase tick so the workers rejoin the
                # dense row set
                _clear_mn_reservations(core, task_id)
                rqv = core.rq_map.get_variants(task.rq_id)
                fused_gang_batches.append(Batch(
                    rq_id=task.rq_id, priority=task.priority, size=1,
                    gang_task=task_id,
                    gang_nodes=rqv.variants[0].n_nodes,
                ))
        core.mn_queue = remaining_mn
        phases["gangs"] = (_time.perf_counter() - _t_phase) * 1e3

    # Soft drain for fused gangs: the kernel holds members WITHIN one
    # solve, but between ticks the prefill phase would keep piling backlog
    # onto the busy members a waiting gang needs (the host phase used the
    # mn_reserved drain for this).  Mark each pending gang's best-group
    # candidate set prefill-exempt instead — no membership change, so the
    # rows stay in the dense solve for the gang row to take.  Mirrors the
    # host interleave: a gang outranked by strictly-higher-priority ready
    # single-node work holds nothing yet.
    fused_gang_hold: set[int] = set()
    if fused_gang_batches:
        top_sn = _top_sn_priority(core)
        for gb in fused_gang_batches:
            if top_sn is not None and top_sn[0] > gb.priority[0]:
                continue
            req = core.rq_map.get_variants(gb.rq_id).variants[0]
            groups: dict[str, list[Worker]] = {}
            for w in core.workers.values():
                if (
                    w.mn_task
                    or w.draining
                    or w.worker_id in fused_gang_hold
                    or not _mn_member_eligible(w, req)
                ):
                    continue
                groups.setdefault(w.group, []).append(w)
            best = max(groups.values(), key=len, default=None)
            if best is None or len(best) < gb.gang_nodes:
                continue
            best.sort(key=lambda w: (
                not w.is_idle(),
                len(w.assigned_tasks) + len(w.prefilled_tasks),
                w.worker_id,
            ))
            fused_gang_hold.update(w.worker_id for w in best[:gb.gang_nodes])

    # --- single-node: dense solve ---
    # Batches are built ONCE per schedule(): run_tick consumes this list,
    # and the prefill phase below reuses it with per-batch taken counts
    # subtracted (the queues see no other mutation in between), instead of
    # re-walking every queue's priority levels two more times (measurable
    # host work at 1k queues x 32 cuts).
    #
    # The dense snapshot is INCREMENTAL: tick_cache.sync applies
    # dirty-tracking deltas to persistent (W, R) arrays instead of
    # rebuilding WorkerRows (sync must run AFTER the gang phase — gang
    # reservations above change row membership).  The cache refuses ticks
    # with min-utilization workers; those fall back to the from-scratch
    # WorkerRow path, whose mu carve-out needs per-worker floors.
    core.tick_counter += 1
    # --- pipelined tick (scheduler/pipeline.py): map the solve dispatched
    # LAST tick first — its device execution overlapped all the host work
    # since then, so the readback is usually free.  This must happen before
    # tick_cache.sync: applying the mapped assignments dirties the worker
    # rows, and the snapshot this tick dispatches from has to include them
    # (the device already does, via the donated free_after).  `--paranoid-
    # tick` ticks force the synchronous path: the pending solve is drained
    # here and the fresh solve below runs sync + bit-checked. ---
    pipeline = core.tick_pipeline
    paranoid_now = (
        core.paranoid_tick > 0
        and core.tick_counter % core.paranoid_tick == 0
    )
    if pipeline is not None and pipeline.pending is not None:
        decision_target = decision_info if record_decision else None
        mapped = (
            pipeline.drain(model=model, phases=phases,
                           decision=decision_target)
            if paranoid_now
            else pipeline.take_result(model=model, phases=phases,
                                      decision=decision_target)
        )
        mapped, n_gangs = _apply_fused_gangs(core, mapped, per_worker_msgs, now)
        assigned += n_gangs
        gang_assigned += n_gangs
        for task_id, worker_id, rq_id, variant in mapped:
            task = core.tasks.get(task_id)
            if task is None:
                continue  # vanished while the solve was in flight
            worker = core.workers.get(worker_id)
            if worker is None:
                # its worker disconnected while the solve was in flight:
                # back to the queue, a later tick re-places it
                core.queues.add(rq_id, task.priority, task_id)
                continue
            task.state = TaskState.ASSIGNED
            task.t_assigned = now
            task.assigned_worker = worker_id
            task.assigned_variant = variant
            worker.assign(
                task_id, core.variant_amounts(rq_id, variant, worker)
            )
            per_worker_msgs.setdefault(worker_id, []).append(
                _compute_message(core, task, variant)
            )
            assigned += 1
    snapshot = core.tick_cache.sync(core)
    rows = core.worker_rows() if snapshot is None else None
    leftover_batches = None
    _t_phase = _time.perf_counter()
    have_workers = (
        bool(snapshot.worker_ids) if snapshot is not None else bool(rows)
    )
    run_gangs_fused = bool(fused_gang_batches) and snapshot is not None
    placed_blevel: dict[int, int] | None = None
    policy_ctx = None
    fairness_placed: tuple | None = None
    if have_workers and (core.queues.total_ready() or run_gangs_fused):
        _t_batches = _time.perf_counter()
        batches = create_batches(core.queues)
        gang_ok = group_ids = None
        if run_gangs_fused:
            batches = batches + fused_gang_batches
            # worker-side gang inputs, aligned to the snapshot rows: host
            # idleness (prefilled backlog does not show in `free`, so the
            # kernel cannot derive it) and the worker-group index map
            gmap: dict[str, int] = {}
            gang_ok = []
            group_ids = []
            for wid in snapshot.worker_ids:
                w = core.workers[wid]
                gang_ok.append(1 if w.is_idle() else 0)
                group_ids.append(gmap.setdefault(w.group, len(gmap)))
        phases["batches"] = (_time.perf_counter() - _t_batches) * 1e3
        if core.policy is not None:
            # weighted objective (--policy-file): resolve this tick's
            # affinity rows + priority boosts against the tick's worker
            # order — the dense snapshot's worker_ids when the cache
            # served, else the row list order (run_tick only reorders
            # workers on the mu path, which strips the rows itself and
            # keeps the alignment-free boosts).
            wids = (
                snapshot.worker_ids if snapshot is not None
                else [r.worker_id for r in rows]
            )
            policy_ctx = core.policy.tick_context(
                core.workers, core.rq_map, core.resource_map,
                wids, batches,
            )
        if snapshot is not None and paranoid_now:
            from hyperqueue_tpu.scheduler.tick_cache import paranoid_check

            paranoid_check(
                core, snapshot, batches, core.rq_map, core.resource_map,
                gang_ok=gang_ok, group_ids=group_ids, policy=policy_ctx,
            )
        pipeline_this_tick = (
            pipeline
            if pipeline is not None and not paranoid_now
            and snapshot is not None
            else None
        )
        if (
            pipeline_this_tick is not None
            and pipeline_this_tick.idle_sig is not None
            and pipeline_this_tick.idle_sig == (
                core.membership_epoch, core.queues.version,
                core.queues.total_ready(),
            )
            and core.tick_cache.rows_rewritten_last == 0
        ):
            # the last pipelined solve mapped NOTHING and no queue
            # mutation, membership change or worker-row drift happened
            # since it was dispatched: a re-solve would see bit-identical
            # inputs and assign nothing again.  Skip the dispatch — with
            # no pending solve the end-of-tick self-request stays off, so
            # an unplaceable backlog costs one extra tick instead of
            # spinning at the min-delay cadence until the next event.
            assignments = []
        else:
            assignments = run_tick(
                core.queues, rows, core.rq_map, core.resource_map, model,
                batches=batches, dense=snapshot, phases=phases,
                key_cache=core.tick_cache,
                decision=decision_info if record_decision else None,
                pipeline=pipeline_this_tick,
                gang_ok=gang_ok, group_ids=group_ids, policy=policy_ctx,
            )
            if (
                pipeline_this_tick is not None
                and pipeline_this_tick.pending is not None
            ):
                # stamp the solve-input state so an EMPTY mapping next tick
                # can prove a re-solve redundant (PendingSolve.state_sig)
                pipeline_this_tick.pending.state_sig = (
                    core.membership_epoch, core.queues.version,
                    core.queues.total_ready(),
                )
        if run_gangs_fused:
            assignments, n_gangs = _apply_fused_gangs(
                core, assignments, per_worker_msgs, now
            )
            assigned += n_gangs
            gang_assigned += n_gangs
        taken_by_batch: dict[tuple[int, Priority_t], int] = {}
        for task_id, worker_id, rq_id, variant in assignments:
            task = core.tasks[task_id]
            worker = core.workers[worker_id]
            task.state = TaskState.ASSIGNED
            task.t_assigned = now
            task.assigned_worker = worker_id
            task.assigned_variant = variant
            worker.assign(
                task_id, core.variant_amounts(rq_id, variant, worker)
            )
            per_worker_msgs.setdefault(worker_id, []).append(
                _compute_message(core, task, variant)
            )
            assigned += 1
            key = (rq_id, task.priority)
            taken_by_batch[key] = taken_by_batch.get(key, 0) + 1
        leftover_batches = []
        for batch in batches:
            if batch.gang_nodes:
                continue  # gang rows never feed prefill/displacement
            batch.size -= taken_by_batch.get(
                (batch.rq_id, batch.priority), 0
            )
            if batch.size > 0:
                leftover_batches.append(batch)
        if record_decision:
            # per-job max b-level among the batches that PLACED work this
            # tick: a same-job leftover with a shallower critical path was
            # deliberately held behind deeper work (lookahead-held)
            placed_blevel = {}
            for (_rq, prio), _n in taken_by_batch.items():
                if prio[1] <= -BLEVEL_STRIDE:
                    j = decode_sched_job(prio[1])
                    bl = decode_sched_blevel(prio[1])
                    if bl > placed_blevel.get(j, -1):
                        placed_blevel[j] = bl
            if policy_ctx is not None and policy_ctx.boosts:
                # lowest original priority among placed batches of
                # fairness/prediction-boosted jobs: a leftover class whose
                # own priority sits ABOVE it was overtaken by the boost
                # (decision.build_unplaced_entries fairness-deferred)
                for (_rq, prio), _n in taken_by_batch.items():
                    if policy_ctx.boost_for_sched(prio[1]) > 0:
                        t = tuple(prio)
                        if fairness_placed is None or t < fairness_placed:
                            fairness_placed = t
            if run_gangs_fused:
                still_waiting = set(core.mn_queue)
                for gb in fused_gang_batches:
                    if gb.gang_task not in still_waiting:
                        continue
                    per_group: dict[str, int] = {}
                    for w in core.workers.values():
                        if w.mn_task or w.draining:
                            continue
                        per_group[w.group] = per_group.get(w.group, 0) + 1
                    feasible = (
                        max(per_group.values(), default=0) >= gb.gang_nodes
                    )
                    reason = (
                        decision_mod.REASON_GANG_GROUP_DEFERRED
                        if feasible
                        else decision_mod.REASON_GANG_INCOMPLETE
                    )
                    gang_unplaced.append({
                        "rq_id": gb.rq_id,
                        "job": task_id_job(gb.gang_task),
                        "task": task_id_task(gb.gang_task),
                        "priority": gb.priority[0],
                        "count": 1,
                        "reason": reason,
                        "detail": (
                            f"fused solve held {gb.gang_nodes} group "
                            "members this tick (busy or taken by the "
                            "scan)" if feasible else
                            f"no group musters {gb.gang_nodes} eligible "
                            "members"
                        ),
                    })
        TRACER.record("scheduler/solve", _time.perf_counter() - _t_phase)

    # --- proactive prefilling: push extra top-priority tasks to busy
    # workers so short tasks pipeline without a server round-trip per task
    # (reference mapping.rs:159 process_proactive_filling, max 40/worker) ---
    _t_phase = _time.perf_counter()
    if prefill and core.queues.total_ready():
        budgets = {
            w.worker_id: PREFILL_MAX - len(w.prefilled_tasks)
            for w in core.workers.values()
            if not w.mn_task
            and not w.mn_reserved
            and not w.draining
            and w.worker_id not in fused_gang_hold
            and (w.assigned_tasks or w.prefilled_tasks)
            and len(w.prefilled_tasks) < PREFILL_MAX
        }
        # starvation guard (reference reservation vars, solver.rs:479-518):
        # each request class with leftover ready tasks reserves ONE capable
        # worker where strictly-lower-priority tasks may not prefill, so a
        # big task eventually sees a fully drained worker instead of losing
        # every race against streams of small tasks.
        if leftover_batches is None:
            leftover_batches = create_batches(core.queues)
        if policy_ctx is not None and policy_ctx.boosts:
            # the solve's boost-weighted order lives in run_tick's COPY of
            # the batch list; prefill consumes the caller's list, so fold
            # the same boost arithmetic here — under deep prefill budgets
            # this order, not the solve's ~capacity-sized mapping, decides
            # which job's backlog reaches the workers first
            leftover_batches.sort(key=lambda b: (
                b.priority[0],
                b.priority[1]
                + policy_ctx.boost_for_sched(b.priority[1]) * BLEVEL_STRIDE,
            ), reverse=True)
        reservations: dict[int, Priority_t] = {}
        for batch in leftover_batches:
            rqv = core.rq_map.get_variants(batch.rq_id)
            for w in sorted(core.workers.values(), key=lambda w: w.worker_id):
                if (
                    w.mn_task or w.mn_reserved or w.draining
                    or w.worker_id in reservations
                ):
                    continue
                if w.resources.is_capable_of_rqv(rqv):
                    reservations[w.worker_id] = batch.priority
                    break
        # prefill in GLOBAL priority order (batches are priority-sorted), so
        # high-priority classes claim worker budgets first; workers are fed
        # least-backlog-first so a deep budget cannot pile onto one worker
        # while its peers run dry between refills
        workers_by_backlog = sorted(
            core.workers.values(),
            key=lambda w: (
                len(w.prefilled_tasks) + len(w.assigned_tasks),
                w.worker_id,
            ),
        )
        for batch in leftover_batches:
            queue = core.queues.queue(batch.rq_id)
            rqv = core.rq_map.get_variants(batch.rq_id)
            eligible: list[tuple[Worker, int]] = []
            for worker in workers_by_backlog:
                if budgets.get(worker.worker_id, 0) <= 0:
                    continue
                blocking = reservations.get(worker.worker_id)
                if blocking is not None and batch.priority < blocking:
                    continue
                variant = next(
                    (
                        i
                        for i, v in enumerate(rqv.variants)
                        if worker.resources.is_capable_of(v)
                    ),
                    None,
                )
                if variant is None:
                    continue
                eligible.append((worker, variant))
            if not eligible:
                continue
            # fair-share split across eligible workers (multiple passes so
            # budget-capped workers' leftovers flow to the others); without
            # this a deep budget lets the first worker swallow the batch
            fair = max(-(-batch.size // len(eligible)), 1)
            progress = True
            while progress:
                progress = False
                for worker, variant in eligible:
                    budget = budgets.get(worker.worker_id, 0)
                    if budget <= 0:
                        continue
                    taken = queue.take(batch.priority, min(budget, fair))
                    if not taken:
                        break
                    progress = True
                    batch.size -= len(taken)  # keeps leftover sizes true
                    for task_id in taken:
                        task = core.tasks[task_id]
                        task.state = TaskState.ASSIGNED
                        task.t_assigned = now
                        task.assigned_worker = worker.worker_id
                        task.assigned_variant = variant
                        task.prefilled = True
                        prefilled += 1
                        worker.prefilled_tasks.add(task_id)
                        budgets[worker.worker_id] -= 1
                        per_worker_msgs.setdefault(
                            worker.worker_id, []
                        ).append(_compute_message(core, task, variant))

    # --- displacement: strictly-higher-user-priority READY work must not
    # sit in the queues while lower-priority prefilled backlog holds the
    # workers that could run it.  Retract the lowest-priority settled
    # victims; once they answer, the next tick prefills in global priority
    # order (reference redirects the prefilled task on submit,
    # test_reactor.rs test_prefill_submit_high_priority) ---
    if prefill and core.queues.total_ready():
        # per-worker victim lists are built ONCE (ascending priority, with
        # this tick's sends and in-flight retracts excluded), then consumed
        # across the batch loop — not rebuilt per (batch x worker).  The
        # common saturated case (all leftover and backlog at one user
        # priority) exits on the first victim comparison per worker.
        victim_lists: dict[int, list] = {}
        for worker in core.workers.values():
            if worker.mn_task or worker.mn_reserved:
                continue
            if not worker.prefilled_tasks:
                continue
            just_sent = {
                m["id"] for m in per_worker_msgs.get(worker.worker_id, ())
            }
            victims = sorted(
                (
                    core.tasks[tid]
                    for tid in worker.prefilled_tasks
                    if tid not in just_sent
                    and not core.tasks[tid].retract_pending
                ),
                key=lambda t: t.priority,
            )
            if victims:
                victims.reverse()  # pop() consumes lowest-priority first
                victim_lists[worker.worker_id] = victims
        if victim_lists:
            # leftover_batches already carries the post-solve post-prefill
            # sizes (both phases decrement batch.size) — no third
            # create_batches walk
            if leftover_batches is None:
                leftover_batches = create_batches(core.queues)
            retract_by_worker: dict[int, list[tuple[int, int]]] = {}
            # per-worker retract cap: one large leftover batch must not
            # strip every lower-priority prefilled task from every capable
            # worker in a single tick (far more than those workers could
            # run) — that just churns retract/re-prefill under deep
            # backlogs. Per displacing batch, a worker gives up at most
            # 2× the batch tasks it could simultaneously RUN (the extra
            # factor leaves backlog headroom), within a PREFILL_MAX
            # overall budget.
            retract_budget = {wid: PREFILL_MAX for wid in victim_lists}
            for batch in leftover_batches:
                if batch.size <= 0:
                    continue
                rqv = core.rq_map.get_variants(batch.rq_id)
                need = batch.size
                for worker_id, victims in victim_lists.items():
                    if need <= 0:
                        break
                    if not victims or retract_budget[worker_id] <= 0:
                        continue
                    worker = core.workers[worker_id]
                    if not worker.resources.is_capable_of_rqv(rqv):
                        continue
                    allowance = min(
                        retract_budget[worker_id],
                        2 * _rqv_fit_count(worker.resources, rqv),
                    )
                    while victims and need > 0 and allowance > 0:
                        if victims[-1].priority[0] >= batch.priority[0]:
                            break  # ascending: nothing lower remains
                        victim = victims.pop()
                        victim.retract_pending = True
                        retract_by_worker.setdefault(
                            worker_id, []
                        ).append((victim.task_id, victim.instance_id))
                        need -= 1
                        allowance -= 1
                        retract_budget[worker_id] -= 1
            for wid, refs in retract_by_worker.items():
                _RETRACTED_TOTAL.labels("displacement").inc(len(refs))
                comm.send_retract(wid, refs)

    # --- retract: steal prefilled backlog back from loaded workers
    # whenever idle capacity appears that the backlog could use — not only
    # when the queues are drained; under sustained arrivals the remaining
    # ready work may simply not fit the idle workers (reference runs this
    # check periodically on the worker, worker/rpc.rs:322; RetractTasks /
    # on_retract_response, reactor.rs:462) ---
    if prefill:
        idle = [
            w for w in core.workers.values()
            if w.is_idle()
            and not w.mn_reserved
            and not w.draining
            and w.worker_id not in per_worker_msgs
        ]
        if idle:
            donors = sorted(
                (w for w in core.workers.values() if w.prefilled_tasks),
                key=lambda w: -len(w.prefilled_tasks),
            )
            # per-class slot budget over CAPABLE idle workers only:
            # retracting a class toward slots that cannot host it would
            # churn the tasks straight back to the donor next tick
            class_slots: dict[int, int] = {}

            def slots_for(rq_id: int) -> int:
                slots = class_slots.get(rq_id)
                if slots is None:
                    rqv = core.rq_map.get_variants(rq_id)
                    slots = sum(
                        w.nt_free
                        for w in idle
                        if w.resources.is_capable_of_rqv(rqv)
                    )
                    class_slots[rq_id] = slots
                return slots

            for donor in donors:
                # tasks prefilled THIS tick have their compute message still
                # queued behind us; a retract would outrun it and no-op
                # (FIFO), so only settled, not-already-asked tasks qualify —
                # oldest first, they are at the worker's queue tail risk
                just_sent = {
                    m["id"] for m in per_worker_msgs.get(donor.worker_id, ())
                }
                victims = []
                budget = len(donor.prefilled_tasks) // 2
                for tid in sorted(donor.prefilled_tasks):
                    if len(victims) >= budget:
                        break
                    task = core.tasks[tid]
                    if tid in just_sent or task.retract_pending:
                        continue
                    if slots_for(task.rq_id) <= 0:
                        continue
                    class_slots[task.rq_id] -= 1
                    task.retract_pending = True
                    victims.append((tid, task.instance_id))
                if victims:
                    _RETRACTED_TOTAL.labels("rebalance").inc(len(victims))
                    comm.send_retract(donor.worker_id, victims)
        phases["prefill"] = (_time.perf_counter() - _t_phase) * 1e3
        TRACER.record("scheduler/prefill", _time.perf_counter() - _t_phase)

    for worker_id, msgs in per_worker_msgs.items():
        comm.send_compute(worker_id, msgs)

    # --- decision record: attribute everything this tick left unplaced
    # to a reason code (scheduler/decision.py) and push the record into
    # the flight recorder ring. Cost is O(leftover classes), never
    # O(tasks) — `phases["decide"]` makes any regression visible in the
    # same place the <=5% budget is enforced. ---
    record = None
    if record_decision:
        _t_phase = _time.perf_counter()
        try:
            # tick-local: only a solve that actually ran THIS tick can mark
            # it degraded (a stale flag from a previous tick must not leak)
            solver = decision_info.get("solver") or {"status": "idle"}
            degraded = solver["status"] in ("fallback", "skipped")
            unplaced = list(gang_unplaced)
            ready_left = core.queues.total_ready()
            if ready_left:
                if leftover_batches is None:
                    leftover_batches = create_batches(core.queues)
                unplaced.extend(decision_mod.build_unplaced_entries(
                    core, leftover_batches, {}, degraded=degraded,
                    placed_blevel=placed_blevel,
                    fairness_placed=fairness_placed,
                ))
            n_paused = 0
            for job_id, held in core.paused_held.items():
                if held:
                    n_paused += len(held)
                    unplaced.append({
                        "rq_id": None, "job": job_id, "priority": None,
                        "count": len(held),
                        "reason": decision_mod.REASON_QUEUE_PAUSED,
                    })
            record = {
                "tick": core.tick_counter,
                "time": now,
                "solver": solver,
                "counts": {
                    "workers": len(core.workers),
                    "assigned": assigned - gang_assigned,
                    "gang_assigned": gang_assigned,
                    "prefilled": prefilled,
                    "unplaced": sum(
                        e["count"] for e in unplaced
                        if e["reason"] != decision_mod.REASON_QUEUE_PAUSED
                    ),
                    "paused": n_paused,
                    "ready_left": ready_left,
                    "mn_waiting": len(core.mn_queue),
                },
                "unplaced": unplaced,
            }
        except Exception:  # noqa: BLE001 - explainability must never
            # take the scheduling loop down with it
            logger.exception("decision-record assembly failed; tick %d "
                             "goes unrecorded", core.tick_counter)
            record = None
        phases["decide"] = (_time.perf_counter() - _t_phase) * 1e3

    phases["total"] = (_time.perf_counter() - _t_tick) * 1e3
    core.tick_stats.record(phases)
    if core.policy is not None:
        # fairness/prediction telemetry: one ledger fold + two dict reads
        # per tick, surfaced as gauges and through `hq server stats`
        jain = core.policy.observe_jain()
        if jain is not None:
            _POLICY_JAIN.set(jain)
        if core.policy.predictor is not None:
            _POLICY_HIT_RATE.set(core.policy.predictor.hit_rate())
        _POLICY_BOOST_MAX.set(core.policy.last_boost_range[1])
    _TICKS_TOTAL.inc()
    if assigned:
        _ASSIGNED_TOTAL.inc(assigned)
    if prefilled:
        _PREFILLED_TOTAL.inc(prefilled)
    for name, ms in phases.items():
        _TICK_PHASE_SECONDS.labels(name).observe(ms / 1e3)
    if record is not None:
        record["duration_ms"] = round(phases["total"], 4)
        record["phases"] = {k: round(v, 4) for k, v in phases.items()}
        core.flight.record_tick(record)
    if pipeline is not None and pipeline.pending is not None:
        # a solve is in flight: without another event (submit, completion,
        # worker change) no further tick would run and the pending solve
        # would never be mapped — ask for one more pass.  The server's
        # schedule_min_delay throttle paces the follow-up, which doubles as
        # the window the device has to finish before the readback.
        comm.ask_for_scheduling()
    return assigned


def on_retract_response(
    core: Core, comm: Comm, task_id: int, ok: bool, instance_id: int
) -> None:
    """Worker answered a retract: ok=True means the task had not started and
    is back in our hands; requeue it for the next tick.

    instance_id is the echo of the instance named in the retract request —
    the same staleness token every other task message carries. A STALE
    response (the task was since requeued and re-prefilled, possibly even
    onto the same worker) carries an old instance and must not steal the
    task off its new placement while that placement's compute message is in
    flight (duplicate execution)."""
    task = core.tasks.get(task_id)
    if task is None or task.is_done or not task.prefilled:
        return
    if task.instance_id != instance_id:
        return  # answer about a previous incarnation
    if not task.retract_pending:
        return  # nothing asked
    task.retract_pending = False
    if not ok:
        return  # it started racing; task_running accounting takes over
    worker = core.workers.get(task.assigned_worker)
    if worker is not None:
        worker.prefilled_tasks.discard(task_id)
    task.prefilled = False
    task.assigned_worker = 0
    task.increment_instance()
    task.state = TaskState.WAITING
    _make_ready(core, task)
    comm.ask_for_scheduling()


def _compute_message(core: Core, task: Task, variant: int) -> dict:
    # entries/n_nodes depend only on (rq_id, variant) within a Core (rq
    # interning is append-only): cache on the Core instance — at 100k-task
    # arrays this is per-task hot path
    key = (task.rq_id, variant)
    cached = core.entries_cache.get(key)
    if cached is None:
        rqv = core.rq_map.get_variants(task.rq_id)
        request = rqv.variants[variant]
        entries = [
            {
                "name": core.resource_map.name_of(e.resource_id),
                "amount": e.amount,
                "policy": e.policy.value,
            }
            for e in request.entries
            # mask subcolumns (gpus#k) are server-side placement
            # constraints; workers only know physical resource names
            if not core.resource_map.is_masked(e.resource_id)
        ]
        cached = (entries, request.n_nodes)
        core.entries_cache[key] = cached
    entries, n_nodes = cached
    msg = {
        "id": task.task_id,
        "instance": task.instance_id,
        "body": task.body,
        "entries": entries,
        "n_nodes": n_nodes,
        "variant": variant,
        "priority": list(task.priority),
    }
    if task.entry is not None:
        msg["entry"] = task.entry
    # trace-context header: the worker stamps accept/launch/spawn clocks
    # against this id and echoes the parent span in its uplinks, so the
    # server-side trace assembly can link the hops causally (the cost on
    # the per-task dispatch path is one small dict)
    traces = core.traces
    if traces.enabled:
        ctx = traces.wire_ctx(task.task_id)
        if ctx is not None:
            attach_trace_wire(msg, ctx[0], ctx[1])
    return msg
