"""Lazy array-task materialization (JASDA-style job atomization).

An array submit arriving through the chunked ingest plane is stored as ONE
`ArrayChunk` record — shared body + a compact id range — instead of a Task
object and a JobTaskInfo per element, so a 1M-task submit costs O(chunks)
allocations at ingest (arxiv 2510.14599 motivates exactly this seam:
split huge arrays into scheduler-sized chunks at ingest, materialize at
dispatch). Per-task records are created only when the scheduler actually
pops ids out of the ready queues (assignment/prefill) or when a per-task
operation (cancel, explain, pause) forces them into existence.

Invariants:

- A lazy task is logically READY from the moment its chunk is registered:
  `t_ready` of the materialized Task is the chunk's registration clock,
  and `JobTaskInfo.submitted_at` is the chunk's OWN submit stamp (not the
  materialization time), so `hq job timeline` phase sums stay exact for
  open jobs that append chunks over time.
- Job-level counters (`Job.n_tasks`) always include unmaterialized ids via
  `Job.n_lazy`, maintained here; terminal-state accounting is untouched
  because a task must materialize before it can start, finish, or cancel.
- Ordering at equal priority is approximate FIFO: materialized tasks
  (requeues, retract returns) drain before lazy segments of the same
  priority level.

Only single-node array chunks without dependencies are registered lazily;
graph submits and multi-node requests keep the eager path.
"""

from __future__ import annotations

from bisect import bisect_left
from collections import deque
from dataclasses import dataclass, field

from hyperqueue_tpu.ids import make_task_id
from hyperqueue_tpu.server.task import Task, TaskState


@dataclass(slots=True)
class ArrayChunk:
    """One ingested array chunk: shared body + compact ids."""

    job_id: int
    rq_id: int
    priority: tuple[int, int]
    body: dict
    crash_limit: int
    # exactly one of id_range (contiguous [start, stop)) or ids (sorted)
    id_range: tuple[int, int] | None = None
    ids: list[int] | None = None
    entries: list | None = None
    submitted_at: float = 0.0  # per-chunk submit stamp (timeline)
    ready_at: float = 0.0      # when the chunk entered the queues
    # submit trace stamps shared by every task of the chunk:
    # {"id", "sent_at", "recv_at", "commit_at"} — replayed into the trace
    # store at materialization so chunked submits still open each task's
    # trace with the client/submit + server/submit spans
    trace: dict | None = None

    @property
    def n(self) -> int:
        if self.id_range is not None:
            return self.id_range[1] - self.id_range[0]
        return len(self.ids)

    def id_at(self, index: int) -> int:
        if self.id_range is not None:
            return self.id_range[0] + index
        return self.ids[index]

    def index_of(self, job_task_id: int) -> int | None:
        if self.id_range is not None:
            lo, hi = self.id_range
            if lo <= job_task_id < hi:
                return job_task_id - lo
            return None
        i = bisect_left(self.ids, job_task_id)
        if i < len(self.ids) and self.ids[i] == job_task_id:
            return i
        return None

    def entry_at(self, index: int):
        if self.entries is None:
            return None
        return self.entries[index]

    def min_id(self) -> int:
        return self.id_range[0] if self.id_range is not None else self.ids[0]

    def max_id(self) -> int:
        if self.id_range is not None:
            return self.id_range[1] - 1
        return self.ids[-1]


class LazySegment:
    """Queue-side view of one chunk: a take cursor plus tombstones for ids
    extracted individually (cancel/explain/single-task materialization)."""

    __slots__ = ("chunk", "pos", "dead", "dead_ahead", "in_queue")

    def __init__(self, chunk: ArrayChunk):
        self.chunk = chunk
        self.pos = 0
        self.dead: set[int] = set()   # tombstoned indexes
        self.dead_ahead = 0           # tombstones at/after pos
        self.in_queue = False

    @property
    def remaining(self) -> int:
        return self.chunk.n - self.pos - self.dead_ahead

    def take_indexes(self, count: int) -> list[int]:
        """Advance the cursor past up to `count` live indexes."""
        out = []
        n = self.chunk.n
        while self.pos < n and len(out) < count:
            i = self.pos
            self.pos += 1
            if i in self.dead:
                self.dead.discard(i)
                self.dead_ahead -= 1
                continue
            out.append(i)
        return out

    def tombstone(self, index: int) -> bool:
        if index < self.pos or index in self.dead:
            return False
        self.dead.add(index)
        self.dead_ahead += 1
        return True

    def remaining_ids(self):
        """Iterate the not-yet-materialized ids (detail/timeline synth)."""
        chunk = self.chunk
        for i in range(self.pos, chunk.n):
            if i not in self.dead:
                yield chunk.id_at(i)


class LazyStore:
    """All unmaterialized array tasks, indexed for both the scheduler
    queues ((rq_id, priority) FIFO levels) and job-level operations."""

    def __init__(self):
        # (rq_id, priority) -> FIFO of in-queue segments
        self.levels: dict[tuple[int, tuple], deque[LazySegment]] = {}
        # rq_id -> live in-queue task count (cheap hybrid-view predicate)
        self.rq_ready: dict[int, int] = {}
        # rq_id -> {priority: in-queue task count}: batch sizing must be
        # O(levels), never O(segments) — at thousands of streamed chunks
        # a per-tick segment walk was measurable in the tick p95
        self.level_ready: dict[int, dict[tuple, int]] = {}
        self.per_job: dict[int, list[LazySegment]] = {}
        self.ready = 0           # unmaterialized ids currently in queues
        self.held = 0            # unmaterialized ids held by job pause
        self.materialized_total = 0
        self.chunks_total = 0
        # bound by Server/bootstrap: () -> JobManager (job-side accounting)
        self.jobs_getter = None

    # --- registration ---------------------------------------------------
    def register(self, core, chunk: ArrayChunk, held: bool = False) -> None:
        seg = LazySegment(chunk)
        self.per_job.setdefault(chunk.job_id, []).append(seg)
        self.chunks_total += 1
        job = self._job(chunk.job_id)
        if job is not None:
            job.n_lazy += chunk.n
        if held:
            self.held += chunk.n
        else:
            self._enqueue(core, seg)

    def _adjust(self, rq_id: int, priority: tuple, delta: int) -> None:
        """Single point of truth for the three in-queue count indexes."""
        self.ready += delta
        self.rq_ready[rq_id] = self.rq_ready.get(rq_id, 0) + delta
        by_p = self.level_ready.setdefault(rq_id, {})
        n = by_p.get(priority, 0) + delta
        if n > 0:
            by_p[priority] = n
        else:
            by_p.pop(priority, None)

    def _enqueue(self, core, seg: LazySegment) -> None:
        key = (seg.chunk.rq_id, seg.chunk.priority)
        self.levels.setdefault(key, deque()).append(seg)
        seg.in_queue = True
        self._adjust(seg.chunk.rq_id, seg.chunk.priority, seg.remaining)
        core.queues.version += 1

    def _job(self, job_id: int):
        if self.jobs_getter is None:
            return None
        return self.jobs_getter().jobs.get(job_id)

    def _retire(self, seg: LazySegment) -> None:
        """Drop a fully-drained segment from every index. Without this,
        per_job would retain every chunk's body + entries list for the
        server's lifetime (and _check_array_ids would keep rejecting
        appends overlapping long-finished chunks)."""
        job_list = self.per_job.get(seg.chunk.job_id)
        if job_list is not None:
            try:
                job_list.remove(seg)
            except ValueError:
                pass
            if not job_list:
                del self.per_job[seg.chunk.job_id]
        if seg.in_queue:
            key = (seg.chunk.rq_id, seg.chunk.priority)
            segs = self.levels.get(key)
            if segs is not None:
                try:
                    segs.remove(seg)
                except ValueError:
                    pass
                if not segs:
                    self.levels.pop(key, None)
            seg.in_queue = False

    def forget_job(self, job_id: int) -> None:
        """Drop every segment of a forgotten job. Terminated jobs have
        none live, but a MIGRATED-OUT job leaves its sealed segments here
        in chunk form — release their in-queue/held counts too, or the
        unmaterialized gauge stays inflated for the server's lifetime."""
        for seg in self.per_job.get(job_id, ()):
            if not seg.remaining:
                continue
            if seg.in_queue:
                self._adjust(seg.chunk.rq_id, seg.chunk.priority,
                             -seg.remaining)
                key = (seg.chunk.rq_id, seg.chunk.priority)
                segs = self.levels.get(key)
                if segs is not None:
                    try:
                        segs.remove(seg)
                    except ValueError:
                        pass
                    if not segs:
                        self.levels.pop(key, None)
                seg.in_queue = False
            else:
                self.held -= seg.remaining
        self.per_job.pop(job_id, None)

    # --- queue-side interface (consumed by scheduler/queues.py) ---------
    def ready_count_rq(self, rq_id: int) -> int:
        return self.rq_ready.get(rq_id, 0)

    def ready_rqs(self):
        return [rq for rq, n in self.rq_ready.items() if n > 0]

    def level_sizes(self, rq_id: int) -> dict[tuple, int]:
        return dict(self.level_ready.get(rq_id) or ())

    def take(self, core, rq_id: int, priority: tuple, count: int) -> list[int]:
        """Pop up to `count` ids at this level, MATERIALIZING each into a
        core Task + JobTaskInfo. This is the scheduler's dispatch-time
        entry point — the one place lazy tasks become real in bulk."""
        segs = self.levels.get((rq_id, priority))
        if not segs:
            return []
        jobs_mgr = self.jobs_getter() if self.jobs_getter else None
        out: list[int] = []
        while segs and len(out) < count:
            seg = segs[0]
            taken = seg.take_indexes(count - len(out))
            for index in taken:
                out.append(
                    self._materialize(core, jobs_mgr, seg.chunk, index)
                )
            if seg.remaining == 0:
                segs.popleft()
                seg.in_queue = False
                self._retire(seg)
            if not taken and segs and segs[0] is seg:
                break  # defensive: no progress
        if not segs:
            self.levels.pop((rq_id, priority), None)
        if out:
            self._adjust(rq_id, priority, -len(out))
        return out

    # --- materialization -------------------------------------------------
    def _materialize(self, core, jobs_mgr, chunk: ArrayChunk,
                     index: int) -> int:
        from hyperqueue_tpu.server.jobs import JobTaskInfo

        job_task_id = chunk.id_at(index)
        task_id = make_task_id(chunk.job_id, job_task_id)
        task = Task(
            task_id=task_id,
            rq_id=chunk.rq_id,
            priority=chunk.priority,
            body=chunk.body,
            entry=chunk.entry_at(index),
            crash_limit=chunk.crash_limit,
        )
        task.state = TaskState.READY
        task.t_ready = chunk.ready_at
        core.tasks[task_id] = task
        if jobs_mgr is not None:
            job = jobs_mgr.jobs.get(chunk.job_id)
            if job is not None:
                job.tasks[job_task_id] = JobTaskInfo(
                    job_task_id=job_task_id,
                    submitted_at=chunk.submitted_at,
                )
                job.n_lazy -= 1
        traces = core.traces
        if traces.enabled and chunk.trace and chunk.trace.get("id"):
            tr = chunk.trace
            traces.begin(task_id, tr["id"])
            parent = None
            sent = float(tr.get("sent_at") or 0.0)
            recv = float(tr.get("recv_at") or 0.0)
            commit = float(tr.get("commit_at") or 0.0) or recv
            if sent and recv:
                parent = traces.span(
                    task_id, "client/submit", sent, recv, "client",
                )
            if recv:
                traces.span(
                    task_id, "server/submit", recv, commit, "server",
                    parent=parent,
                )
        self.materialized_total += 1
        return task_id

    # --- job-level operations --------------------------------------------
    def segments_of(self, job_id: int):
        return [
            s for s in self.per_job.get(job_id, ()) if s.remaining > 0
        ]

    def job_unmaterialized(self, job_id: int) -> int:
        return sum(s.remaining for s in self.per_job.get(job_id, ()))

    def owns(self, job_id: int, job_task_id: int) -> bool:
        for seg in self.per_job.get(job_id, ()):
            index = seg.chunk.index_of(job_task_id)
            if index is None:
                continue
            if index >= seg.pos and index not in seg.dead:
                return True
        return False

    def drop_id(self, core, job_id: int, job_task_id: int) -> bool:
        """Tombstone one lazy id WITHOUT materializing it (restore uses
        this to carve journal-tail-touched ids out of a snapshot chunk
        before handing them to the per-task restore path)."""
        for seg in self.per_job.get(job_id, ()):
            index = seg.chunk.index_of(job_task_id)
            if index is None:
                continue
            if not seg.tombstone(index):
                continue
            if seg.in_queue:
                self._adjust(seg.chunk.rq_id, seg.chunk.priority, -1)
                core.queues.version += 1
            else:
                self.held -= 1
            job = self._job(job_id)
            if job is not None:
                job.n_lazy -= 1
            if seg.remaining == 0:
                self._retire(seg)
            return True
        return False

    def extract(self, core, job_id: int, job_task_id: int):
        """Materialize ONE lazy task out of its segment (per-task ops:
        cancel of a single id, `hq task explain`). Returns the Task (state
        READY, NOT enqueued — the caller decides queue membership) or None
        when the id is not lazily held."""
        for seg in self.per_job.get(job_id, ()):
            index = seg.chunk.index_of(job_task_id)
            if index is None:
                continue
            if not seg.tombstone(index):
                continue
            if seg.in_queue:
                self._adjust(seg.chunk.rq_id, seg.chunk.priority, -1)
                core.queues.version += 1
            else:
                self.held -= 1
            jobs_mgr = self.jobs_getter() if self.jobs_getter else None
            task_id = self._materialize(core, jobs_mgr, seg.chunk, index)
            if seg.remaining == 0:
                self._retire(seg)
            return core.tasks[task_id]
        return None

    def materialize_job(self, core, job_id: int) -> list:
        """Force every remaining lazy task of a job into existence (rare
        whole-job ops: cancel, forced drain). In-queue segments turn into
        READY tasks in the base queues — exactly what an eager submit
        would have produced; held segments (job paused) land in the pause
        ledger (core.paused_held) like any other held READY task."""
        segs = self.per_job.pop(job_id, [])
        jobs_mgr = self.jobs_getter() if self.jobs_getter else None
        out: list = []
        for seg in segs:
            was_queued = seg.in_queue
            n = seg.remaining
            if n == 0:
                continue
            if was_queued:
                self._dequeue(core, seg)
            else:
                self.held -= n
            for index in seg.take_indexes(n):
                task_id = self._materialize(
                    core, jobs_mgr, seg.chunk, index
                )
                task = core.tasks[task_id]
                if was_queued:
                    core.queues.add(task.rq_id, task.priority, task_id)
                else:
                    core.paused_held.setdefault(job_id, set()).add(task_id)
                out.append(task)
        return out

    def _dequeue(self, core, seg: LazySegment) -> None:
        key = (seg.chunk.rq_id, seg.chunk.priority)
        segs = self.levels.get(key)
        if segs is not None:
            try:
                segs.remove(seg)
            except ValueError:
                pass
            if not segs:
                self.levels.pop(key, None)
        seg.in_queue = False
        self._adjust(seg.chunk.rq_id, seg.chunk.priority, -seg.remaining)
        core.queues.version += 1

    def detach_job(self, core, job_id: int) -> int:
        """Pull a job's in-queue segments out of the scheduler levels
        (job pause); they stay owned by per_job, flagged held."""
        moved = 0
        for seg in self.per_job.get(job_id, ()):
            if seg.in_queue and seg.remaining:
                n = seg.remaining
                self._dequeue(core, seg)
                self.held += n
                moved += n
        return moved

    def requeue_job(self, core, job_id: int) -> int:
        """Re-enqueue a job's held segments (job resume)."""
        moved = 0
        for seg in self.per_job.get(job_id, ()):
            if not seg.in_queue and seg.remaining:
                n = seg.remaining
                self.held -= n
                self._enqueue(core, seg)
                moved += n
        return moved

    def stats(self) -> dict:
        return {
            "unmaterialized": self.ready + self.held,
            "ready": self.ready,
            "held": self.held,
            "chunks": self.chunks_total,
            "materialized_total": self.materialized_total,
        }
