"""Fan-out plane: downlink framing + AEAD seal on a sender thread pool.

The reactor's other single-core tax (after the journal, see
server/journal_plane.py) was outbound framing: every worker downlink
batch and every client response/stream frame was msgpack-encoded and
ChaCha20-Poly1305-sealed INLINE on the loop that owns the socket — the
`fanout` lag plane of the PR 8 stall detector. With encryption on, the
seal dominates (per wire byte), and with 1k workers a tick's compute
fan-out serialized the whole cluster's crypto onto one core.

This pool moves the CPU half of a send — `Connection.encode` (msgpack +
seal) — onto dedicated sender threads; the cheap half (two buffered
writes + drain) stays on the loop that owns the transport. Ordering is
preserved per connection because each connection has exactly ONE sender
coroutine, which awaits the offloaded encode before writing: counter
nonces are consumed in send order, frames hit the socket in seal order.
Different connections' encodes run concurrently across the pool — with
N senders and native/numpy AEAD, downlink crypto scales to N cores
instead of pinning one.

The existing bounded-queue/drop semantics are untouched: per-worker
queues, per-client outqueues and subscriber buffers backpressure (or
drop) exactly as before — this plane only changes WHERE the encode runs.

`--fanout-senders 0` keeps encodes inline on the owning loop (escape
hatch, mirroring `--client-plane reactor` / `--journal-plane reactor`).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

from hyperqueue_tpu.utils.metrics import REGISTRY

FANOUT_FRAMES = REGISTRY.counter(
    "hq_fanout_plane_frames_total",
    "downlink frames encoded+sealed by the sender pool",
)
FANOUT_BYTES = REGISTRY.counter(
    "hq_fanout_plane_bytes_total",
    "wire bytes produced by the sender pool",
)
FANOUT_BATCH = REGISTRY.histogram(
    "hq_fanout_plane_batch_msgs",
    "messages coalesced per downlink frame by the worker sender",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
)
FANOUT_STALLS = REGISTRY.counter(
    "hq_fanout_plane_send_stalls_total",
    "sends whose encode+write exceeded the 50 ms stall threshold "
    "(slow consumer socket or an oversubscribed sender pool)",
)

SEND_STALL_SECONDS = 0.05

# note_send runs on BOTH the reactor loop (worker senders) and the
# ingest-plane loop (client senders); the registry's `value +=` is a
# non-atomic read-modify-write, so these shared counters take a lock —
# unlike every other metric in the tree, which has a single writer
_NOTE_LOCK = threading.Lock()


class SendPool:
    """Shared encode executor for every outbound plane of one server."""

    def __init__(self, senders: int):
        self.senders = max(int(senders), 0)
        self.executor = (
            ThreadPoolExecutor(
                max_workers=self.senders, thread_name_prefix="hq-fanout"
            )
            if self.senders
            else None
        )

    @property
    def enabled(self) -> bool:
        return self.executor is not None

    async def encode(self, loop, conn, payload) -> bytes:
        """Encode+seal `payload` for `conn`, on the pool when enabled.
        Must be awaited from the connection's single sender task (seal
        order = send order)."""
        if self.executor is None:
            return conn.encode(payload)
        return await loop.run_in_executor(
            self.executor, conn.encode, payload
        )

    @staticmethod
    def note_send(n_msgs: int, n_bytes: int, dt: float) -> None:
        with _NOTE_LOCK:
            FANOUT_FRAMES.inc()
            FANOUT_BYTES.inc(n_bytes)
            FANOUT_BATCH.observe(n_msgs)
            if dt >= SEND_STALL_SECONDS:
                FANOUT_STALLS.inc()

    def stop(self) -> None:
        if self.executor is not None:
            self.executor.shutdown(wait=False, cancel_futures=True)
