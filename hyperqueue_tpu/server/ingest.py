"""Decoupled client-connection plane.

Client socket accept, framing, authentication (including the per-frame
ChaCha20-Poly1305 seal/open, which on the pure-python fallback costs ~6 us
per wire byte) and msgpack decode run on a DEDICATED thread with its own
asyncio loop — the first concrete slice of the ROADMAP "pipelined reactor
planes" item. Decoded messages cross into the scheduler reactor through a
batched handoff deque; responses and stream frames flow back through
per-connection outbound queues drained by a sender coroutine on this
thread. The reactor never touches a client socket, and a storm of
submitting clients costs it only the batched drain work (measured as the
`ingest` plane in the PR 8 lag tracker).

Backpressure is two-level and applies to the READ side, so a flooding
client is parked on its own TCP connection instead of growing server
memory:

- per-client window: at most `window` handed-off, not-yet-answered
  requests per connection;
- global handoff bound: when the reactor falls behind and the handoff
  deque reaches `handoff_max` items, every reader pauses until the next
  drain.

Both stall events are counted in `hq_ingest_backpressure_stalls_total`.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import threading
import time

from hyperqueue_tpu.transport.auth import (
    ROLE_CLIENT,
    ROLE_SERVER,
    AuthError,
    do_authentication,
)
from hyperqueue_tpu.utils.metrics import REGISTRY

logger = logging.getLogger("hq.ingest")

# ingest-plane telemetry (single-writer per metric: chunk/task counters are
# bumped by the reactor at apply time, the stall counter by the ingest
# thread, depth/client gauges by the metrics collect hook)
INGEST_CHUNKS = REGISTRY.counter(
    "hq_ingest_chunks_total", "submit chunks ingested (streaming submit)"
)
INGEST_TASKS = REGISTRY.counter(
    "hq_ingest_tasks_total", "tasks ingested through the submit plane"
)
INGEST_REQUESTS = REGISTRY.counter(
    "hq_ingest_requests_total",
    "client requests handed from the connection plane to the reactor",
)
INGEST_STALLS = REGISTRY.counter(
    "hq_ingest_backpressure_stalls_total",
    "reads paused by the per-client window or the global handoff bound",
)

_CLOSE = object()  # outbound-queue sentinel: sender exits


class ClientChannel:
    """One authenticated client connection, as seen by the reactor.

    Socket-side state (outq, resume event, inflight counter) lives on the
    ingest loop; `reply`/`stream_send`/`kick` are the thread-safe surface
    the reactor uses. `gone` is an Event on the REACTOR loop, set by the
    drain loop when the disconnect notification crosses the handoff — it
    is what terminates streaming RPC handlers.
    """

    _next_id = 0

    def __init__(self, plane: "IngestPlane", conn):
        ClientChannel._next_id += 1
        self.id = ClientChannel._next_id
        self.plane = plane
        self.conn = conn
        # outbound frames; bounded so a dead-slow streaming consumer
        # backpressures the reactor-side streaming task (stream_send
        # awaits space) instead of buffering the whole journal
        self.outq: asyncio.Queue = asyncio.Queue(maxsize=1024)
        self.resume = asyncio.Event()
        self.resume.set()
        self.inflight = 0
        self.closed = False
        self.is_gone = False       # set by the reactor drain loop
        self.gone: asyncio.Event | None = None  # reactor-loop event
        # streaming task (subscribe/stream_events) bound to this channel,
        # cancelled when the disconnect notification arrives
        self.stream_task = None

    # --- reactor-side API ------------------------------------------------
    def reply(self, frame: dict) -> None:
        """Queue a request/response frame (thread-safe, non-blocking).
        Bounded by the inflight window: there can never be more pending
        replies than handed-off requests."""
        try:
            self.plane.loop.call_soon_threadsafe(self._deliver, frame)
        except RuntimeError:
            pass  # ingest loop already shut down

    async def stream_send(self, frame: dict) -> None:
        """Send one streaming frame, awaiting outbound-queue space (used
        by subscribe/stream_events handlers on the reactor loop)."""
        if self.is_gone or self.closed:
            raise ConnectionError("client disconnected")
        try:
            fut = asyncio.run_coroutine_threadsafe(
                self.outq.put(frame), self.plane.loop
            )
        except RuntimeError as e:
            raise ConnectionError("connection plane stopped") from e
        await asyncio.wrap_future(fut)

    def close(self) -> None:
        """Close the connection (thread-safe; used by the reactor after a
        streaming handler finishes — request/response channels are closed
        by the client side)."""
        def _do() -> None:
            self.closed = True
            self.conn.close()

        try:
            self.plane.loop.call_soon_threadsafe(_do)
        except RuntimeError:
            pass

    def reactor_gone_event(self) -> asyncio.Event:
        """The disconnect event, created lazily ON the reactor loop."""
        if self.gone is None:
            self.gone = asyncio.Event()
            if self.is_gone:
                self.gone.set()
        return self.gone

    # --- ingest-loop internals -------------------------------------------
    def _deliver(self, frame: dict) -> None:
        self.inflight -= 1
        self.resume.set()
        if self.closed:
            return
        try:
            self.outq.put_nowait(frame)
        except asyncio.QueueFull:
            # only possible if the peer stopped reading while hammering
            # requests; drop the connection rather than buffer unboundedly
            logger.warning("client %d outbound queue overflow; closing",
                           self.id)
            self.closed = True
            self.conn.close()


class IngestPlane:
    """The client-plane thread: accept/auth/decode + batched handoff."""

    def __init__(self, key_getter, window: int = 64,
                 handoff_max: int = 8192, sendpool=None):
        self.key_getter = key_getter
        # shared fan-out sender pool (server/fanout.py): client response/
        # stream frames (subscriber fan-out included) encode+seal on the
        # pool's threads instead of this plane's loop; None/disabled =
        # inline encode on this thread (still off the reactor)
        self.sendpool = sendpool
        self.window = max(int(window), 1)
        self.handoff_max = max(int(handoff_max), self.window)
        self.handoff: collections.deque = collections.deque()
        self.loop: asyncio.AbstractEventLoop | None = None
        self.port: int | None = None
        self.clients: set[ClientChannel] = set()
        self._thread: threading.Thread | None = None
        self._server = None
        self._drained: asyncio.Event | None = None   # ingest-loop event
        self._reactor_loop: asyncio.AbstractEventLoop | None = None
        self._wake_cb = None
        self._stopping = False

    # --- lifecycle -------------------------------------------------------
    def start(self, host: str, port: int, reactor_loop, wake_cb) -> int:
        """Bind the client listener on the plane thread; returns the bound
        port. `wake_cb` is called (threadsafe, on the reactor loop) after
        every handoff append."""
        self._reactor_loop = reactor_loop
        self._wake_cb = wake_cb
        started = threading.Event()
        boot: dict = {}

        def run() -> None:
            from hyperqueue_tpu.utils import profiler

            # sampling-profiler plane label (ISSUE 19): connection-plane
            # CPU (framing, decode, backpressure) attributes to `ingest`
            profiler.register_plane("ingest")
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self.loop = loop
            self._drained = asyncio.Event()

            async def bind():
                try:
                    self._server = await asyncio.start_server(
                        self._serve_client, host, port
                    )
                    boot["port"] = (
                        self._server.sockets[0].getsockname()[1]
                    )
                except Exception as e:  # noqa: BLE001 - surfaced to start()
                    boot["error"] = e
                finally:
                    started.set()

            loop.run_until_complete(bind())
            if "error" in boot:
                loop.close()
                profiler.unregister_plane()
                return
            try:
                loop.run_forever()
            finally:
                # cancel leftovers so close doesn't warn
                for task in asyncio.all_tasks(loop):
                    task.cancel()
                try:
                    loop.run_until_complete(
                        loop.shutdown_asyncgens()
                    )
                except Exception:  # noqa: BLE001
                    pass
                loop.close()
                profiler.unregister_plane()

        self._thread = threading.Thread(
            target=run, name="hq-ingest", daemon=True
        )
        self._thread.start()
        started.wait()
        if "error" in boot:
            raise boot["error"]
        self.port = boot["port"]
        return self.port

    def stop(self) -> None:
        self._stopping = True
        loop = self.loop
        if loop is None:
            return

        def shutdown() -> None:
            if self._server is not None:
                self._server.close()
            for channel in list(self.clients):
                channel.closed = True
                channel.conn.close()
            loop.stop()

        try:
            loop.call_soon_threadsafe(shutdown)
        except RuntimeError:
            return
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # --- reactor-side API ------------------------------------------------
    def pop_batch(self, limit: int) -> list:
        out = []
        while self.handoff and len(out) < limit:
            out.append(self.handoff.popleft())
        return out

    def notify_drained(self) -> None:
        """Reactor drained a batch: lift the global backpressure gate."""
        loop = self.loop
        if loop is None:
            return
        try:
            loop.call_soon_threadsafe(self._drained.set)
        except RuntimeError:
            pass

    # --- ingest-loop internals -------------------------------------------
    def _wake_reactor(self) -> None:
        try:
            self._reactor_loop.call_soon_threadsafe(self._wake_cb)
        except RuntimeError:
            pass

    async def _serve_client(self, reader, writer) -> None:
        channel = None
        try:
            conn = await do_authentication(
                reader, writer, ROLE_SERVER, ROLE_CLIENT, self.key_getter()
            )
            channel = ClientChannel(self, conn)
            self.clients.add(channel)
            sender = asyncio.ensure_future(self._sender(channel))
            try:
                while True:
                    msg = await conn.recv()
                    # backpressure BEFORE the handoff: park this reader
                    # while its window is exhausted or the reactor is
                    # behind on the global queue
                    while channel.inflight >= self.window:
                        INGEST_STALLS.inc()
                        channel.resume.clear()
                        if channel.inflight >= self.window:
                            await channel.resume.wait()
                    while len(self.handoff) >= self.handoff_max:
                        INGEST_STALLS.inc()
                        self._drained.clear()
                        if len(self.handoff) >= self.handoff_max:
                            await self._drained.wait()
                    channel.inflight += 1
                    INGEST_REQUESTS.inc()
                    self.handoff.append((channel, msg))
                    self._wake_reactor()
            finally:
                sender.cancel()
                try:
                    await sender
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
        except (
            AuthError,
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
        ) as e:
            logger.debug("client connection ended: %s", e)
        except Exception:  # noqa: BLE001 - one bad client never kills the plane
            logger.exception("client connection crashed")
        finally:
            if channel is not None:
                channel.closed = True
                self.clients.discard(channel)
                if not self._stopping:
                    # tell the reactor so it tears down subscriptions and
                    # sets channel.gone for streaming handlers
                    self.handoff.append((channel, None))
                    self._wake_reactor()
            writer.close()

    async def _sender(self, channel: ClientChannel) -> None:
        conn = channel.conn
        pool = self.sendpool
        while True:
            frame = await channel.outq.get()
            if frame is _CLOSE:
                return
            try:
                if pool is not None and pool.enabled:
                    t0 = time.perf_counter()
                    data = await pool.encode(self.loop, conn, frame)
                    await conn.send_bytes(data)
                    pool.note_send(1, len(data), time.perf_counter() - t0)
                else:
                    await conn.send(frame)
            except (ConnectionError, OSError):
                channel.closed = True
                conn.close()
                return
