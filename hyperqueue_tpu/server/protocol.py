"""Wire codecs for resource requests and task descriptions.

Reference: crates/hyperqueue/src/transfer/messages.rs (client<->server DTOs)
and crates/tako/src/internal/messages/worker.rs (server<->worker). Resource
requests travel as name-keyed dicts (workers and clients don't know the
server's interned ids); the server converts to interned form on arrival.
"""

from __future__ import annotations

from hyperqueue_tpu.resources.map import ResourceIdMap
from hyperqueue_tpu.resources.request import (
    AllocationPolicy,
    ResourceRequest,
    ResourceRequestEntry,
    ResourceRequestVariants,
)


def submit_record(job_desc: dict, n_tasks: int) -> dict:
    """Summary of one submit echoed in job detail (reference
    JobDetail.submit_descs): the wire resource request plus task count.
    A graph submit with heterogeneous per-task requests echoes the deduped
    list under "requests" instead of misreporting tasks[0]'s as THE
    request."""
    array = job_desc.get("array")
    if array:
        return {"n_tasks": n_tasks, "request": array.get("request") or {}}
    distinct: list[dict] = []
    for t in job_desc.get("tasks") or []:
        request = t.get("request") or {}
        if request not in distinct:
            distinct.append(request)
    if len(distinct) <= 1:
        return {"n_tasks": n_tasks,
                "request": distinct[0] if distinct else {}}
    return {"n_tasks": n_tasks, "requests": distinct}


def array_desc_ids(array: dict) -> list[int]:
    """The task ids of a wire array description: explicit "ids" list or
    the chunked-submit "id_range" [start, stop) compact form."""
    id_range = array.get("id_range")
    if id_range is not None:
        return list(range(int(id_range[0]), int(id_range[1])))
    return list(array["ids"])


def expand_desc_tasks(job_desc: dict) -> list[dict]:
    """Expand a submit description into per-task dicts (array or graph form).

    Used where per-task iteration is needed anyway (journal restore, detail
    queries); the live submit path keeps the compressed array form.
    """
    array = job_desc.get("array")
    if not array:
        return list(job_desc.get("tasks", []))
    out = []
    entries = array.get("entries")
    shared_body = array.get("body", {})
    for i, task_id in enumerate(array_desc_ids(array)):
        task = {
            "id": task_id,
            # ONE body object for the whole array; the entry travels as its
            # own field so the compute-message body dedup survives restore
            "body": shared_body,
            "request": array.get("request") or {},
            "priority": array.get("priority", 0),
            "crash_limit": array.get("crash_limit", 5),
        }
        if entries is not None:
            task["entry"] = entries[i]
        out.append(task)
    return out


def rqv_to_wire(rqv: ResourceRequestVariants, resource_map: ResourceIdMap) -> dict:
    return {
        "variants": [
            {
                "n_nodes": v.n_nodes,
                "min_time": v.min_time_secs,
                "weight": v.weight,
                "entries": [
                    {
                        "name": resource_map.name_of(e.resource_id),
                        "amount": e.amount,
                        "policy": e.policy.value,
                    }
                    for e in v.entries
                ],
            }
            for v in rqv.variants
        ]
    }


def rqv_from_wire(data: dict, resource_map: ResourceIdMap) -> ResourceRequestVariants:
    variants = []
    for v in data.get("variants") or [{}]:
        entries_list = []
        for e in v.get("entries", []):
            entries_list.append(
                ResourceRequestEntry(
                    resource_id=resource_map.get_or_create(e["name"]),
                    amount=int(e["amount"]),
                    policy=AllocationPolicy.parse(e.get("policy", "compact")),
                )
            )
            if e.get("group") is not None:
                # non-fungible indexed constraint ("group k of gpus"):
                # one extra dense mask entry against the per-group
                # subcolumn, NOT a materialized per-group variant — the
                # batched solve sees it as one more needs row
                entries_list.append(
                    ResourceRequestEntry(
                        resource_id=resource_map.get_or_create_masked(
                            e["name"], int(e["group"])
                        ),
                        amount=int(e["amount"]),
                    )
                )
        entries = tuple(entries_list)
        if not entries and not v.get("n_nodes"):
            # default: 1 cpu
            entries = (
                ResourceRequestEntry(
                    resource_id=resource_map.get_or_create("cpus"),
                    amount=10_000,
                ),
            )
        variants.append(
            ResourceRequest(
                entries=entries,
                n_nodes=int(v.get("n_nodes", 0)),
                min_time_secs=float(v.get("min_time", 0.0)),
                weight=float(v.get("weight", 1.0)),
            )
        )
    rqv = ResourceRequestVariants(variants=tuple(variants))
    rqv.validate()
    return rqv
