"""Server core state.

Reference: crates/tako/src/internal/server/core.rs:42-62 — the single source
of truth mutated only by the reactor on the single-threaded server loop:
task map, worker map, interning maps, ready queues, id counters. Purity of
the scheduler (a function of a snapshot of this state) is what makes the TPU
offload possible; nothing here holds locks.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from hyperqueue_tpu.ids import IdCounter
from hyperqueue_tpu.resources.map import ResourceIdMap, ResourceRqMap
from hyperqueue_tpu.resources.request import ResourceRequestVariants
from hyperqueue_tpu.scheduler.queues import TaskQueues
from hyperqueue_tpu.scheduler.tick import WorkerRow
from hyperqueue_tpu.scheduler.tick_cache import TickPhaseStats, TickStateCache
from hyperqueue_tpu.server.lazy import LazyStore
from hyperqueue_tpu.server.task import Task, TaskState
from hyperqueue_tpu.server.worker import Worker
from hyperqueue_tpu.utils.flight import FlightRecorder
from hyperqueue_tpu.utils.trace import TaskTraceStore


@dataclass
class Core:
    tasks: dict[int, Task] = field(default_factory=dict)
    workers: dict[int, Worker] = field(default_factory=dict)
    resource_map: ResourceIdMap = field(default_factory=ResourceIdMap)
    rq_map: ResourceRqMap = field(default_factory=ResourceRqMap)
    queues: TaskQueues = field(default_factory=TaskQueues)
    worker_id_counter: IdCounter = field(default_factory=IdCounter)
    # multi-node gang tasks waiting for enough workers, in priority order
    mn_queue: list[int] = field(default_factory=list)
    scheduling_needed: bool = False
    # restart fencing base for THIS boot: n_prior_boots * the generation
    # stride (task.py INSTANCE_GENERATION_STRIDE), set by journal restore.
    # Every restored task re-issued (not reattached) is fenced to at least
    # this, so no instance a crashed boot issued in its lost journal tail
    # can collide with a re-issue (0 on a fresh server: nothing to fence)
    instance_fence_floor: int = 0
    # (rq_id, variant) -> (wire entries, n_nodes); rq interning is
    # append-only so entries never change within a Core
    entries_cache: dict = field(default_factory=dict)
    # (rq_id, variant) -> (has_all, [(resource_id, amount)]) memo for
    # variant_amounts (per-assignment hot path)
    amounts_cache: dict = field(default_factory=dict)
    # persistent dense tick snapshot, updated by dirty-tracking deltas
    # instead of rebuilt per tick (scheduler/tick_cache.py)
    tick_cache: TickStateCache = field(default_factory=TickStateCache)
    # per-phase tick latency breakdown, recorded by reactor.schedule and
    # surfaced through `hq server stats`
    tick_stats: TickPhaseStats = field(default_factory=TickPhaseStats)
    # debug: every N ticks, assert the incremental assembly is
    # bit-identical to a from-scratch one (0 = off; --paranoid-tick N)
    paranoid_tick: int = 0
    # fused-solve mode (--scheduler greedy-fused): multi-node gangs become
    # all-or-nothing column groups inside the dense solve (scheduler/tick.py
    # gang rows) instead of the host-side reservation drain
    fused_solve: bool = False
    # two-stage async tick pipeline (scheduler/pipeline.TickPipeline) when
    # the server started with --tick-pipeline; None = synchronous ticks
    tick_pipeline: object = None
    # weighted scheduling objective (scheduler/policy.PolicyState) when the
    # server started with --policy-file; None = flat placement-count
    # objective. Only consulted on the fused dense path.
    policy: object = None
    tick_counter: int = 0
    # bumped on every change of the schedulable-worker SET (connect,
    # disconnect, gang reservation/claim/release): lets the tick cache
    # skip the O(W) membership walk on the common unchanged tick.
    # Row CONTENT changes (free/nt_free) ride on Worker.epoch instead.
    membership_epoch: int = 0
    # flight recorder: ring of per-tick DecisionRecords + control-plane
    # events (utils/flight.py); reactor.schedule records into it and the
    # explain/flight-recorder/trace RPCs read it
    flight: FlightRecorder = field(default_factory=FlightRecorder)
    # per-task distributed traces (utils/trace.py TaskTraceStore): spans
    # from client submit through worker spawn to completion commit are
    # assembled here and queried by the task_trace RPC / `hq task trace`
    traces: TaskTraceStore = field(default_factory=TaskTraceStore)
    # rq_id -> (membership_epoch, amount_capable, lifetime_ok) memo for
    # decision.classify_class (pure in the worker set per class)
    capable_memo: dict = field(default_factory=dict)
    # jobs paused via `hq job pause`: their READY tasks are held out of the
    # scheduler queues (paused_held[job_id] = task ids) until resume
    paused_jobs: set[int] = field(default_factory=set)
    paused_held: dict[int, set[int]] = field(default_factory=dict)
    # unmaterialized lazy array tasks (server/lazy.py): chunked array
    # submits register O(chunks) records here; the queues materialize
    # per-task state only at dispatch/prefill time
    lazy: LazyStore = field(default_factory=LazyStore)

    def __post_init__(self) -> None:
        # the queues consult the lazy store for batch sizing and
        # materializing takes; takes need the core for task creation
        self.queues.bind_lazy(self.lazy, self)

    def bump_membership(self) -> None:
        self.membership_epoch += 1

    def intern_rqv(self, rqv: ResourceRequestVariants) -> int:
        return self.rq_map.get_or_create(rqv)

    def worker_rows(self) -> list[WorkerRow]:
        """Snapshot rows for the tick; excludes workers reserved for gangs
        and workers draining toward a graceful stop."""
        return [
            WorkerRow(
                worker_id=w.worker_id,
                free=w.free,
                nt_free=w.nt_free,
                lifetime_secs=w.lifetime_secs(),
                total=w.resources.amounts,
                cpu_floor=w.cpu_floor(),
            )
            for w in self.workers.values()
            if w.mn_task == 0 and w.mn_reserved == 0 and not w.draining
        ]

    def variant_amounts(
        self, rq_id: int, variant: int, worker=None
    ) -> list[tuple[int, int]]:
        """[(resource_id, amount)] of the chosen variant for accounting.

        ALL-policy entries take the WORKER's whole pool (reference
        solver.rs:120-124 amount_or_none_if_all), so `worker` must be passed
        whenever the request could contain one — assign and release then
        stay symmetric because the pool size is static per worker.

        Classes without ALL entries (the overwhelming majority) get their
        amount list memoized per (rq_id, variant): this is called once per
        assignment on the apply path, and rebuilding the list dominated
        the tick's apply phase at 1M x 1k (callers treat it read-only).
        """
        key = (rq_id, variant)
        cached = self.amounts_cache.get(key)
        if cached is None:
            from hyperqueue_tpu.resources.request import AllocationPolicy

            entries = self.rq_map.get_variants(rq_id).variants[variant].entries
            if any(e.policy is AllocationPolicy.ALL for e in entries):
                cached = (True, None)
            else:
                cached = (
                    False, [(e.resource_id, e.amount) for e in entries]
                )
            self.amounts_cache[key] = cached
        has_all, static = cached
        if not has_all:
            return static
        from hyperqueue_tpu.resources.request import AllocationPolicy

        rqv = self.rq_map.get_variants(rq_id)
        return [
            (
                e.resource_id,
                worker.resources.amount(e.resource_id)
                if worker is not None
                and e.policy is AllocationPolicy.ALL
                else e.amount,
            )
            for e in rqv.variants[variant].entries
        ]

    def sanity_check(self) -> None:
        """Debug invariant walk (reference core.rs:274-430)."""
        self.queues.sanity_check()
        for task in self.tasks.values():
            if task.state is TaskState.WAITING:
                assert task.unfinished_deps > 0, task
            if task.state in (TaskState.ASSIGNED, TaskState.RUNNING):
                assert task.assigned_worker in self.workers or task.mn_workers
        for worker in self.workers.values():
            for rid, amount in enumerate(worker.free):
                assert 0 <= amount <= worker.resources.amount(rid), (
                    worker.worker_id,
                    rid,
                    amount,
                )
            for task_id in worker.assigned_tasks:
                task = self.tasks.get(task_id)
                assert task is not None and task.assigned_worker == worker.worker_id
            for task_id in worker.prefilled_tasks:
                task = self.tasks.get(task_id)
                assert (
                    task is not None
                    and task.prefilled
                    and task.assigned_worker == worker.worker_id
                ), task_id
