"""Journal plane: group commit + fsync on a dedicated thread.

Before this plane existed, `Server.emit_event` performed the journal
append (msgpack encode + CRC framing + write + flush, + fsync under
`--journal-fsync always`) inline on the reactor loop — the `journal` lag
plane of the PR 8 stall detector. This module turns `emit_event` into an
enqueue:

- the **reactor** appends records to a pending deque (one lock-guarded
  list op) and registers *visibility callbacks* — client acks, event
  deliveries to listeners/subscribers — against the current enqueue
  ticket;
- the **commit thread** drains whole batches, performs ONE buffered
  write (+ flush/fsync per the configured policy) per batch, then posts
  the new durability watermark back to the reactor loop, which releases
  every callback at or below it.

Durability-before-visibility is therefore preserved *by construction*:
nothing externally observable (an ack frame, a completion surfaced to a
subscriber, a job_wait response) runs before the records that justify it
are as durable as the fsync policy promises — exactly the contract the
old synchronous group-commit block enforced, now without holding the
event loop for the disk.

Group commit gets BETTER under load, not worse: the deeper the backlog
the more records amortize one write+fsync, which is the arxiv 2002.07062
batch-architecture argument applied to the durability plane.

`--journal-plane reactor` keeps the old inline behavior (escape hatch,
mirroring `--client-plane reactor`).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque

from hyperqueue_tpu.utils.metrics import REGISTRY
from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.journal")

_COMMITS_TOTAL = REGISTRY.counter(
    "hq_journal_plane_commits_total",
    "group commits performed by the journal commit thread",
)
_BATCH_RECORDS = REGISTRY.histogram(
    "hq_journal_plane_batch_records",
    "records folded into one journal-plane group commit",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384),
)
_COMMIT_SECONDS = REGISTRY.histogram(
    "hq_journal_plane_commit_seconds",
    "journal-plane group commit latency (write + flush + fsync)",
)
_STALLS_TOTAL = REGISTRY.counter(
    "hq_journal_plane_stalls_total",
    "reactor enqueues that blocked on the journal plane's pending bound "
    "(the disk cannot keep up with the event rate)",
)


class JournalPlane:
    """The commit thread + watermark bookkeeping around one Journal.

    Thread ownership: between start() and stop()/suspend(), the commit
    thread is the ONLY writer of the underlying Journal. The reactor
    interacts through append/when_durable (non-blocking) and
    barrier/suspend (deliberately blocking, for chaos injection points,
    compaction swaps and shutdown).
    """

    def __init__(
        self,
        journal,
        *,
        fsync_always: bool,
        flush_each: bool,
        loop,
        lag=None,
        on_fatal=None,
        max_pending: int = 65536,
    ):
        self.journal = journal
        self.fsync_always = fsync_always
        # flush-to-OS per commit (the default per-event policy, batched);
        # False = a periodic loop calls request_flush instead
        self.flush_each = flush_each
        self.loop = loop
        self.lag = lag
        self.on_fatal = on_fatal
        self.max_pending = max(int(max_pending), 1)
        self._cv = threading.Condition()
        self._pending: deque = deque()  # (enqueue_monotonic, record)
        self._enqueued = 0   # tickets handed out
        self._durable = 0    # tickets committed per the fsync policy
        self._synced = 0     # tickets covered by an actual fsync
        self._sync_target = 0
        self._flush_req = False
        self._flush_req_sync = False
        self._suspended = False
        self._parked = threading.Event()
        self._stop = False
        self._dead = False
        self._callbacks: deque = deque()  # (ticket, cb), ticket-ordered
        self._thread: threading.Thread | None = None
        self.commits = 0
        self.records = 0
        self.max_batch = 0
        # test hook (tests/test_server_planes.py): stretch the
        # enqueue->commit window so the durability-before-visibility
        # property is observable — an ack must NOT beat the commit
        self._test_delay = float(
            os.environ.get("HQ_JOURNAL_PLANE_TEST_DELAY", "0") or 0
        )

    # --- reactor side ---------------------------------------------------
    def append(self, record: dict) -> int:
        """Enqueue one journal record; returns its ticket."""
        with self._cv:
            if len(self._pending) >= self.max_pending and not self._dead:
                # the disk is behind the event rate: park the reactor on
                # the commit (bounded memory beats an unbounded deque; the
                # stall is visible in the counter and the lag plane)
                _STALLS_TOTAL.inc()
                target = self._enqueued
                self._cv.notify_all()
                self._cv.wait_for(
                    lambda: self._durable >= target or self._dead
                )
            self._enqueued += 1
            self._pending.append((clock.monotonic(), record))
            self._cv.notify_all()
            return self._enqueued

    def when_durable(self, cb) -> None:
        """Run `cb` (on the reactor loop) once everything enqueued so far
        is committed. Runs inline when the plane is already caught up —
        callbacks always fire in enqueue order."""
        with self._cv:
            ticket = self._enqueued
            if self._durable >= ticket and not self._callbacks:
                run_now = True
            else:
                self._callbacks.append((ticket, cb))
                run_now = False
        if run_now:
            cb()

    def barrier(self, sync: bool = False) -> None:
        """Block the calling thread until everything enqueued so far is
        committed (and fsynced, with sync=True). Used by the chaos
        injection point, compaction's capture barrier, explicit flush
        RPCs and shutdown — the deliberate stop-the-world moments.

        sync=False only guarantees the records reached the appender
        (commit_batch); under --journal-flush-period the file-object
        buffer may still hold them. A caller about to RE-READ the file
        (history replay, journal info) must pass sync=True."""
        with self._cv:
            target = self._enqueued
            if sync:
                self._sync_target = max(self._sync_target, target)
            self._cv.notify_all()
            self._cv.wait_for(
                lambda: self._dead
                or (
                    self._durable >= target
                    and (not sync or self._synced >= target)
                )
            )
            if self._dead:
                raise RuntimeError("journal plane failed; see server log")

    def request_flush(self, sync: bool = False) -> None:
        """Non-blocking flush request (the periodic flush loop's lever)."""
        with self._cv:
            self._flush_req = True
            self._flush_req_sync = self._flush_req_sync or sync
            self._cv.notify_all()

    def suspend(self) -> None:
        """Drain + park the commit thread so the caller may close/replace
        the journal appender (compaction swap, prune). The caller MUST
        not await between suspend() and resume() — appends would pile up
        against a parked thread. Raises if the plane died (a dead thread
        can never park; blocking the reactor on it would wedge the
        server past even its own stop())."""
        with self._cv:
            if self._dead:
                raise RuntimeError("journal plane failed; see server log")
            self._suspended = True
            self._parked.clear()
            self._cv.notify_all()
        self._parked.wait()
        if self._dead:
            raise RuntimeError("journal plane failed; see server log")

    def resume(self) -> None:
        with self._cv:
            self._suspended = False
            self._cv.notify_all()

    def depth(self) -> int:
        return len(self._pending)

    def stats(self) -> dict:
        return {
            "mode": "thread",
            "depth": len(self._pending),
            "enqueued": self._enqueued,
            "durable": self._durable,
            "commits": self.commits,
            "max_batch": self.max_batch,
            "mean_batch": round(self.records / self.commits, 2)
            if self.commits else 0.0,
        }

    # --- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="hq-journal", daemon=True
        )
        self._thread.start()

    def stop(self) -> bool:
        """Drain everything, then join the thread. The journal stays
        open — the owner closes it. Returns False when the thread did
        not finish within the deadline: the owner must then NOT close
        the journal (closing the appender under a still-writing thread
        would turn a clean stop into silent crash-consistency)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is None:
            return True
        self._thread.join(timeout=30.0)
        if self._thread.is_alive():
            logger.critical(
                "journal plane did not drain within 30s at shutdown "
                "(%d records pending); leaving the appender open",
                len(self._pending),
            )
            return False
        return True

    # --- commit thread --------------------------------------------------
    def _run(self) -> None:
        from hyperqueue_tpu.utils import profiler

        # plane label for the sampling profiler (ISSUE 19): commit-thread
        # CPU shows up as the `journal` plane next to its lag histogram.
        # Unregistered on EVERY exit path (clean drain and crash alike) so
        # a recycled thread ident can never wear a stale label.
        profiler.register_plane("journal")
        try:
            self._run_inner()
        finally:
            profiler.unregister_plane()

    def _run_inner(self) -> None:
        try:
            while True:
                with self._cv:
                    self._cv.wait_for(
                        lambda: self._pending
                        or self._stop
                        or self._suspended
                        or self._flush_req
                        or self._sync_target > self._synced
                    )
                    if self._suspended:
                        # only park fully drained: the swap must see
                        # every acknowledged-enqueued record on disk.
                        # _parked is re-set on EVERY wakeup while
                        # suspended: a second suspend() arriving before
                        # this thread observed the first resume() clears
                        # _parked and must still see it set again, or
                        # the reactor would wait forever.
                        if not self._pending:
                            while self._suspended:
                                self._parked.set()
                                self._cv.wait()
                            continue
                    if self._stop and not self._pending:
                        return
                    batch = list(self._pending)
                    self._pending.clear()
                    sync_goal = self._sync_target
                    flush_req = self._flush_req
                    flush_sync = self._flush_req_sync
                    self._flush_req = False
                    self._flush_req_sync = False
                t0 = time.perf_counter()
                if batch and self._test_delay:
                    time.sleep(self._test_delay)
                if batch:
                    self.journal.begin_batch()
                    for _ts, record in batch:
                        self.journal.write(record)
                    self.journal.commit_batch()
                new_durable = self._durable + len(batch)
                want_sync = (
                    (self.fsync_always and batch)
                    or sync_goal > self._synced
                    or flush_sync
                )
                if want_sync or (batch and self.flush_each) or flush_req:
                    self.journal.flush(sync=want_sync)
                now = clock.monotonic()
                with self._cv:
                    self._durable = new_durable
                    if want_sync:
                        self._synced = new_durable
                    self._cv.notify_all()
                if batch:
                    self.commits += 1
                    self.records += len(batch)
                    self.max_batch = max(self.max_batch, len(batch))
                    _COMMITS_TOTAL.inc()
                    _BATCH_RECORDS.observe(len(batch))
                    _COMMIT_SECONDS.observe(time.perf_counter() - t0)
                    try:
                        # the lag observation rides the release callback
                        # so every LagTracker write stays loop-affine
                        # (a stats snapshot or /metrics render iterating
                        # the dicts must never race an insert)
                        self.loop.call_soon_threadsafe(
                            self._release, new_durable,
                            now - batch[0][0],
                        )
                    except RuntimeError:
                        return  # loop gone (shutdown)
        except Exception:  # noqa: BLE001 - a dead journal is fatal
            logger.critical("journal plane crashed", exc_info=True)
            with self._cv:
                self._dead = True
                self._cv.notify_all()
            self._parked.set()  # a waiting suspend() must not hang forever
            if self.on_fatal is not None:
                try:
                    self.loop.call_soon_threadsafe(self.on_fatal)
                except RuntimeError:
                    pass

    # --- reactor loop side ----------------------------------------------
    def _release(self, durable: int, lag_s: float | None = None) -> None:
        if lag_s is not None and self.lag is not None:
            # the re-pointed `journal` lag plane: handoff latency
            # (enqueue -> durable) of the oldest record in the batch,
            # not loop hold time
            self.lag.observe("journal", lag_s)
        cbs = self._callbacks
        while cbs and cbs[0][0] <= durable:
            _ticket, cb = cbs.popleft()
            try:
                cb()
            except Exception:  # noqa: BLE001 - one bad callback must not
                # wedge every later ack behind it
                logger.exception("durability callback failed")
