"""Python user API.

Reference: crates/pyhq/python/hyperqueue — Client, Job (program + Python
function tasks with dependencies), LocalCluster.
"""

from hyperqueue_tpu.api.client import (
    Client,
    FailedJobsException,
    Job,
    LocalCluster,
    Task,
)

__all__ = ["Client", "FailedJobsException", "Job", "LocalCluster", "Task"]
