"""DAG visualization of a Job.

Reference: crates/pyhq/python/hyperqueue/visualization.py — renders the task
graph; here as Graphviz DOT text (render with `dot -Tsvg`) plus a terse
ASCII topological listing for terminals.
"""

from __future__ import annotations

from hyperqueue_tpu.api.client import Job


def job_to_dot(job: Job) -> str:
    lines = [f'digraph "{job.name}" {{', "  rankdir=LR;"]
    for task in job._tasks:
        cmd = " ".join(task.spec["body"]["cmd"][:3])
        label = f"{task.task_id}: {cmd[:40]}"
        lines.append(f'  t{task.task_id} [label="{label}", shape=box];')
    for task in job._tasks:
        for dep in task.spec.get("deps", []):
            lines.append(f"  t{dep} -> t{task.task_id};")
    lines.append("}")
    return "\n".join(lines)


def job_to_text(job: Job) -> str:
    out = [f"job {job.name!r}: {len(job._tasks)} task(s)"]
    for task in job._tasks:
        deps = task.spec.get("deps", [])
        arrow = f" <- {deps}" if deps else ""
        cmd = " ".join(task.spec["body"]["cmd"][:4])
        out.append(f"  [{task.task_id}] {cmd}{arrow}")
    return "\n".join(out)
