"""Python user API: Client, Job, tasks (programs and Python functions).

Reference: crates/pyhq/python/hyperqueue/ — Client.submit/wait_for_jobs/
get_failed_tasks/forget (client.py:24-125), Job.program/function with deps
(job.py:14-161), cloudpickle-wrapped Python functions executed by a spawned
interpreter (task/function/), and LocalCluster (cluster/__init__.py:20-73).
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path
from hyperqueue_tpu.utils import clock


class FailedJobsException(Exception):
    def __init__(self, failed: dict):
        self.failed = failed
        super().__init__(f"jobs failed: {failed}")


class Task:
    def __init__(self, task_id: int, spec: dict):
        self.task_id = task_id
        self.spec = spec


class Job:
    """A job under construction: add tasks, then Client.submit(job)."""

    def __init__(self, name: str = "python-job", max_fails: int | None = None):
        self.name = name
        self.max_fails = max_fails
        self._tasks: list[Task] = []

    def _next_id(self) -> int:
        return len(self._tasks)

    def program(
        self,
        args: list[str],
        *,
        env: dict | None = None,
        cwd: str | None = None,
        stdout: str | None = None,
        stderr: str | None = None,
        stdin: bytes | None = None,
        deps: list[Task] | None = None,
        priority: int = 0,
        resources: dict | None = None,
        nodes: int = 0,
        time_request: float = 0.0,
        weight: float = 1.0,
    ) -> Task:
        """Add a program task. resources: {"cpus": "2", "gpus": "0.5"}."""
        from hyperqueue_tpu.resources.amount import amount_from_str

        entries = []
        for name, amount in (resources or {}).items():
            if amount == "all":
                entries.append({"name": name, "amount": 0, "policy": "all"})
            else:
                entries.append(
                    {"name": name, "amount": amount_from_str(str(amount)),
                     "policy": "compact"}
                )
        body = {
            "cmd": [str(a) for a in args],
            "env": {str(k): str(v) for k, v in (env or {}).items()},
            "cwd": cwd,
            "stdout": stdout,
            "stderr": stderr,
            "submit_dir": os.getcwd(),
        }
        if stdin is not None:
            body["stdin"] = stdin
        spec = {
            "id": self._next_id(),
            "body": body,
            "request": {
                "variants": [
                    {"n_nodes": nodes, "min_time": time_request,
                     "weight": weight, "entries": entries}
                ]
            },
            "deps": [t.task_id for t in (deps or [])],
            "priority": priority,
        }
        task = Task(spec["id"], spec)
        self._tasks.append(task)
        return task

    def function(
        self,
        fn,
        *,
        args: tuple = (),
        kwargs: dict | None = None,
        deps: list[Task] | None = None,
        priority: int = 0,
        resources: dict | None = None,
        stdout: str | None = None,
        stderr: str | None = None,
    ) -> Task:
        """Add a Python function task (cloudpickle-shipped, reference
        task/function/wrapper.py CloudWrapper)."""
        import cloudpickle

        payload = cloudpickle.dumps((fn, args, kwargs or {}))
        return self.program(
            [sys.executable, "-m", "hyperqueue_tpu.api.function_runner"],
            stdin=payload,
            deps=deps,
            priority=priority,
            resources=resources,
            stdout=stdout,
            stderr=stderr,
        )

    def to_wire(self) -> dict:
        return {
            "name": self.name,
            "submit_dir": os.getcwd(),
            "max_fails": self.max_fails,
            "tasks": [t.spec for t in self._tasks],
        }


class Client:
    """Synchronous client to a running server."""

    def __init__(self, server_dir: str | Path | None = None):
        from hyperqueue_tpu.client.connection import open_session
        from hyperqueue_tpu.utils.serverdir import default_server_dir

        # open_session resolves a federation root to a routing
        # FederatedSession (ISSUE 11); classic dirs get a ClientSession
        self._session = open_session(
            Path(server_dir) if server_dir else default_server_dir()
        )

    def submit(self, job: Job) -> int:
        response = self._session.request(
            {"op": "submit", "job": job.to_wire()}
        )
        return response["job_id"]

    def submit_stream(self, name: str, body: dict, entries,
                      chunk_size: int = 16384, request: dict | None = None,
                      max_fails: int | None = None,
                      window: int | None = None) -> tuple[int, int]:
        """Streaming chunked array submit (ISSUE 10): one task per entry
        (HQ_ENTRY), pipelined to the server in `chunk_size` chunks over
        the chunked ingest plane. `entries` may be any iterable — a
        generator is never buffered beyond one chunk plus the in-flight
        window, so arbitrarily long streams submit in bounded memory.
        Returns (job_id, n_tasks)."""
        from hyperqueue_tpu.client.connection import SubmitStream

        stream = SubmitStream(
            self._session,
            {"name": name, "submit_dir": os.getcwd(),
             "max_fails": max_fails},
            window=window,
        )
        base = {"body": body, "request": request or {}}
        next_id = 0
        buf: list = []
        for entry in entries:
            buf.append(entry if isinstance(entry, str) else str(entry))
            if len(buf) >= max(chunk_size, 1):
                stream.send_chunk(array={
                    **base, "id_range": [next_id, next_id + len(buf)],
                    "entries": buf,
                })
                next_id += len(buf)
                buf = []
        if buf:
            stream.send_chunk(array={
                **base, "id_range": [next_id, next_id + len(buf)],
                "entries": buf,
            })
        return stream.finish()

    def wait_for_jobs(self, job_ids: list[int], raise_on_fail: bool = True,
                      progress=None):
        """progress: optional callback(done, total) polled while waiting
        (reference pyhq wait_for_jobs progress callback)."""
        if progress is None:
            response = self._session.request(
                {"op": "job_wait", "job_ids": list(job_ids)}
            )
            jobs = response["jobs"]
        else:
            while True:
                jobs = self._session.request(
                    {"op": "job_info", "job_ids": list(job_ids)}
                )["jobs"]
                total = sum(j["n_tasks"] for j in jobs)
                done = sum(
                    j["counters"]["finished"]
                    + j["counters"]["failed"]
                    + j["counters"]["canceled"]
                    for j in jobs
                )
                progress(done, total)
                if done >= total and all(
                    not j["counters"]["running"] for j in jobs
                ):
                    break
                time.sleep(0.25)
        failed = self.get_failed_tasks(job_ids)
        if failed and raise_on_fail:
            raise FailedJobsException(failed)
        return jobs

    def get_failed_tasks(self, job_ids: list[int]) -> dict:
        response = self._session.request(
            {"op": "job_info", "job_ids": list(job_ids)}
        )
        failed: dict[int, dict[int, str]] = {}
        for job in response["jobs"]:
            for task in job["tasks"]:
                if task["status"] == "failed":
                    failed.setdefault(job["id"], {})[task["id"]] = task["error"]
        return failed

    def forget(self, job_ids: list[int]) -> int:
        response = self._session.request(
            {"op": "job_forget", "job_ids": list(job_ids)}
        )
        return response["forgotten"]

    def job_info(self, job_ids: list[int]) -> list[dict]:
        return self._session.request(
            {"op": "job_info", "job_ids": list(job_ids)}
        )["jobs"]

    def close(self) -> None:
        self._session.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class LocalCluster:
    """In-process-managed local server + N workers for scripts and tests.

    Reference: pyhq cluster/__init__.py:20-73 (embedded server); here the
    server/workers are child processes sharing a private server dir.
    """

    def __init__(self, n_workers: int = 1, cpus_per_worker: int = 4,
                 server_dir: str | None = None):
        import subprocess
        import tempfile

        self._dir = Path(server_dir or tempfile.mkdtemp(prefix="hq-local-"))
        self._dir.mkdir(parents=True, exist_ok=True)
        env = {**os.environ, "JAX_PLATFORMS": os.environ.get(
            "HQ_LOCAL_CLUSTER_JAX_PLATFORM", "cpu")}
        self._procs = [
            subprocess.Popen(
                [sys.executable, "-m", "hyperqueue_tpu", "server", "start",
                 "--server-dir", str(self._dir)],
                env=env,
                stdout=open(self._dir / "server.log", "wb"),
                stderr=subprocess.STDOUT,
            )
        ]
        deadline = clock.now() + 30
        while clock.now() < deadline:
            if (self._dir / "hq-current" / "access.json").exists():
                break
            if self._procs[0].poll() is not None:
                raise RuntimeError(
                    "local server died: "
                    + (self._dir / "server.log").read_text()[-2000:]
                )
            time.sleep(0.05)
        else:
            raise TimeoutError("local server did not start")
        for i in range(n_workers):
            self.add_worker(cpus=cpus_per_worker)

    def add_worker(self, cpus: int = 4) -> None:
        import subprocess

        self._procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "hyperqueue_tpu", "worker", "start",
                 "--server-dir", str(self._dir), "--cpus", str(cpus)],
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
                stdout=open(self._dir / f"worker{len(self._procs)}.log", "wb"),
                stderr=subprocess.STDOUT,
            )
        )

    def client(self) -> Client:
        return Client(self._dir)

    def stop(self) -> None:
        for p in reversed(self._procs):
            if p.poll() is None:
                p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=5)
            except Exception:
                p.kill()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
