"""Executes a cloudpickled (fn, args, kwargs) payload from stdin.

Reference: crates/pyhq/python/hyperqueue/task/function/__init__.py:39-149 —
the pickled function runs in a freshly spawned interpreter; a non-zero exit
code (with the traceback on stderr) marks the task failed.
"""

from __future__ import annotations

import sys


def main() -> int:
    import cloudpickle

    payload = sys.stdin.buffer.read()
    fn, args, kwargs = cloudpickle.loads(payload)
    result = fn(*args, **kwargs)
    if result is not None:
        print(repr(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
