"""Multi-chip scheduling model: the sharded cut-scan as a production backend.

Selected with `--scheduler=multichip`. Same `solve` interface and identical
semantics as GreedyCutScanModel (the sharded kernel reproduces the single-chip
visit order exactly — see parallel/solve.py); the worker axis is sharded over
a jax.sharding.Mesh so that tick cost scales with W / n_devices.

In the reference the solver IS the production scheduler
(crates/tako/src/internal/scheduler/main.rs:40-46, solver.rs:16-461); this
model is the multi-device form of that seat, reached through the same
reactor.schedule -> run_tick -> model.solve path as every other backend.

Device handling: the mesh is built lazily on first solve from however many
devices the process sees (all of them by default, or `n_devices`). With a
single device the model degrades to the plain single-chip kernel — a
single-chip deployment selecting `--scheduler=multichip` is valid and loses
nothing.
"""

from __future__ import annotations

import logging

import numpy as np

from hyperqueue_tpu.models.greedy import GreedyCutScanModel, _bucket

logger = logging.getLogger(__name__)


class MultichipModel(GreedyCutScanModel):
    def __init__(self, n_devices: int | None = None, **kwargs):
        # backend only matters for the single-device fallback, where the
        # parent's "auto" (numpy on CPU hosts) is the right default; with a
        # real mesh the sharded jax kernel is used unconditionally
        super().__init__(**kwargs)
        self._requested_devices = n_devices
        self._mesh = None  # built lazily: jax.devices() only at first solve

    def _get_mesh(self):
        if self._mesh is None:
            import jax

            from hyperqueue_tpu.parallel.solve import make_worker_mesh

            try:
                available = len(jax.devices())
            except RuntimeError:
                # accelerator backend failed to initialize (e.g. unhealthy
                # TPU relay): degrade to the single-chip host fallback
                # instead of killing the scheduler loop
                available = 1
                logger.warning(
                    "multichip scheduler: jax backend unavailable, "
                    "falling back to the single-chip host solve",
                    exc_info=True,
                )
            n = (
                min(self._requested_devices, available)
                if self._requested_devices
                else available
            )
            if n <= 1:
                self._mesh = False  # sentinel: single-chip fallback
                logger.info(
                    "multichip scheduler: 1 device visible, using the "
                    "single-chip kernel"
                )
            else:
                self._mesh = make_worker_mesh(n)
                logger.info(
                    "multichip scheduler: worker axis sharded over %d devices",
                    n,
                )
        return self._mesh

    def _worker_bucket(self, n_w: int) -> int:
        pw = _bucket(n_w, self.worker_floor)
        mesh = self._get_mesh()
        if mesh:
            d = mesh.devices.size
            pw = ((pw + d - 1) // d) * d  # shard_map needs W % D == 0
        return pw

    def _solve_padded(
        self, free_p, nt_p, life_p, needs_p, sizes_p, mt_p, class_m,
        order_ids, total_p=None, amask_p=None,
    ):
        mesh = self._get_mesh()
        if not mesh:
            return super()._solve_padded(
                free_p, nt_p, life_p, needs_p, sizes_p, mt_p, class_m,
                order_ids, total_p=total_p, amask_p=amask_p,
            )
        from hyperqueue_tpu.parallel.solve import (
            place_tick_inputs,
            sharded_cut_scan,
        )

        placed = place_tick_inputs(
            mesh, free_p, nt_p, life_p, needs_p, sizes_p, mt_p, class_m,
            order_ids, total=total_p, all_mask=amask_p,
        )
        counts, _free_after, _nt_after = sharded_cut_scan(mesh, *placed)
        return np.asarray(counts)
