"""Multi-chip scheduling model: the sharded cut-scan as a production backend.

Selected with `--scheduler=multichip`. Same `solve` interface and identical
semantics as GreedyCutScanModel (the sharded kernel reproduces the single-chip
visit order exactly — see parallel/solve.py); the worker axis is sharded over
a jax.sharding.Mesh so that tick cost scales with W / n_devices.

In the reference the solver IS the production scheduler
(crates/tako/src/internal/scheduler/main.rs:40-46, solver.rs:16-461); this
model is the multi-device form of that seat, reached through the same
reactor.schedule -> run_tick -> model.solve path as every other backend.

Device handling: the mesh is built lazily on first solve from however many
devices the process sees (all of them by default, or `n_devices`). With a
single device the model degrades to the plain single-chip kernel — a
single-chip deployment selecting `--scheduler=multichip` is valid and loses
nothing.

Residency: the sharded solve inherits the device-resident tick state from
the parent model — the (W, R) shards stay on their devices across ticks,
per-tick uploads are the dirty-row delta (scattered under GSPMD, so each
device receives only its own rows), and `sharded_cut_scan_donate` reuses
the resident buffers for `free_after`/`nt_after`.  `--scheduler=multichip`
is an explicit operator choice, so the adaptive host-vs-device cost model
is bypassed: with a real mesh the sharded kernel runs unconditionally
(the watchdog still guards failures), matching the documented contract
that selecting multichip means "shard my solve".
"""

from __future__ import annotations

import logging

from hyperqueue_tpu.models.greedy import GreedyCutScanModel, _bucket

logger = logging.getLogger(__name__)


class MultichipModel(GreedyCutScanModel):
    _device_backend_name = "device-sharded"

    def __init__(self, n_devices: int | None = None, **kwargs):
        # backend only matters for the single-device fallback, where the
        # parent's "auto" (numpy on CPU hosts) is the right default; with a
        # real mesh the sharded jax kernel is used unconditionally
        super().__init__(**kwargs)
        self._requested_devices = n_devices
        self._mesh = None  # built lazily: jax.devices() only at first solve

    def _get_mesh(self):
        if self._mesh is None:
            import jax

            from hyperqueue_tpu.parallel.solve import make_worker_mesh

            try:
                available = len(jax.devices())
            except RuntimeError:
                # accelerator backend failed to initialize (e.g. unhealthy
                # TPU relay): degrade to the single-chip host fallback
                # instead of killing the scheduler loop
                available = 1
                logger.warning(
                    "multichip scheduler: jax backend unavailable, "
                    "falling back to the single-chip host solve",
                    exc_info=True,
                )
            n = (
                min(self._requested_devices, available)
                if self._requested_devices
                else available
            )
            if n <= 1:
                self._mesh = False  # sentinel: single-chip fallback
                logger.info(
                    "multichip scheduler: 1 device visible, using the "
                    "single-chip kernel"
                )
            else:
                self._mesh = make_worker_mesh(n)
                logger.info(
                    "multichip scheduler: worker axis sharded over %d devices",
                    n,
                )
        return self._mesh

    def _worker_bucket(self, n_w: int) -> int:
        pw = _bucket(n_w, self.worker_floor)
        mesh = self._get_mesh()
        if mesh:
            d = mesh.devices.size
            pw = ((pw + d - 1) // d) * d  # shard_map needs W % D == 0
        return pw

    def _backend_decision(self, shape_key):
        # an operator who selected --scheduler=multichip asked for the
        # sharded device solve: run it whenever a mesh exists (the solver
        # watchdog still catches failures); without one, behave exactly
        # like the single-chip model (adaptive on accelerators, host on
        # CPU-only deployments)
        if self._get_mesh():
            return "device", "multichip-mesh"
        return super()._backend_decision(shape_key)

    def _residency(self):
        if self._res is None:
            from hyperqueue_tpu.parallel.resident import DeviceResidency
            from hyperqueue_tpu.parallel.solve import _mesh_shardings

            mesh = self._get_mesh()
            if mesh:
                self._res = DeviceResidency(shardings=_mesh_shardings(mesh))
            else:
                self._res = super()._residency()
        return self._res

    def _kernel_dispatch(self, res, free_d, nt_d, life_d, total_d, prep):
        mesh = self._get_mesh()
        if not mesh:
            return super()._kernel_dispatch(
                res, free_d, nt_d, life_d, total_d, prep
            )
        from hyperqueue_tpu.parallel.solve import sharded_cut_scan_donate

        return sharded_cut_scan_donate(
            mesh, free_d, nt_d, life_d,
            res.place_cached("needs", prep["needs_p"]),
            res.place_cached("sizes", prep["sizes_p"]),
            res.place_cached("min_time", prep["mt_p"]),
            res.place_cached("class_m", prep["class_m"], kind=3),
            res.place_cached("order_ids", prep["order_ids"]),
            total=total_d,
            all_mask=res.place_cached("all_mask", prep["amask_p"]),
            gang_nodes=res.place_cached("gang_nodes", prep["gang_p"]),
            gang_ok=res.place_cached("gang_ok", prep["gok_p"], kind=1),
            group_onehot=res.place_cached(
                "group_onehot", prep["goh_p"], kind=0
            ),
            policy_mask=res.place_cached(
                "policy_mask", prep["pmask_p"], kind=3
            ),
        )

    def _fresh_device_counts(self, prep):
        mesh = self._get_mesh()
        if not mesh:
            return super()._fresh_device_counts(prep)
        from hyperqueue_tpu.parallel.solve import (
            place_tick_inputs,
            sharded_cut_scan,
        )

        placed = place_tick_inputs(
            mesh, prep["free_p"], prep["nt_p"], prep["life_p"],
            prep["needs_p"], prep["sizes_p"], prep["mt_p"],
            prep["class_m"], prep["order_ids"], total=prep["total_p"],
            all_mask=prep["amask_p"], gang_nodes=prep["gang_p"],
            gang_ok=prep["gok_p"], group_onehot=prep["goh_p"],
            policy_mask=prep["pmask_p"],
        )
        counts, _f, _n = sharded_cut_scan(mesh, *placed)
        return counts
