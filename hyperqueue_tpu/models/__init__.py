"""Scheduler policy models.

A "model" here is a scheduling policy: it consumes a dense tick snapshot and
produces per-(batch, variant, worker) task counts. `greedy` is the production
cut-scan model (jitted, bucketed shapes); `milp` is the exact host MILP
(scipy HiGHS) used as the accuracy oracle and selectable with
`--scheduler=milp`; `multichip` shards the cut-scan's worker axis over a
device mesh (`--scheduler=multichip`) with semantics identical to `greedy`.
"""

from hyperqueue_tpu.models.greedy import GreedyCutScanModel
from hyperqueue_tpu.models.milp import MilpModel
from hyperqueue_tpu.models.multichip import MultichipModel

__all__ = ["GreedyCutScanModel", "MilpModel", "MultichipModel"]
