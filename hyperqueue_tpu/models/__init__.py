"""Scheduler policy models.

A "model" here is a scheduling policy: it consumes a dense tick snapshot and
produces per-(batch, variant, worker) task counts. `greedy` is the production
cut-scan model (jitted, bucketed shapes). Future models (auction refinement,
LP-polish) plug in behind the same interface so `--scheduler=` can select them.
"""

from hyperqueue_tpu.models.greedy import GreedyCutScanModel

__all__ = ["GreedyCutScanModel"]
