"""Greedy cut-scan scheduling model: bucketing + compile-cache around the kernel.

The kernel (ops/assign.py) needs static shapes; real ticks have varying worker
counts, batch counts, resource counts and variant counts. This wrapper pads
every dimension up to a bucket (powers of two with a small floor) so that in
steady state every tick hits one already-compiled program — the same trick the
reference uses to keep its MILP warm is unnecessary there but essential under
XLA (see SURVEY.md §7 "Fixed shapes on TPU").

Padding is semantically inert: padded workers have zero free resources and
zero task slots; padded batches have size 0; padded variants are all-zero
need rows which `_variant_capacity` masks off.

Device path (new in the device-resident tick): the padded state stays
RESIDENT on the accelerator (parallel/resident.py) — per-tick uploads are
only the dirty-row delta, the solve donates its buffers so free_after/nt_after
of solve N feed solve N+1 on-device, and the padded counts are sliced to the
live (B, V, W) extents ON the device before readback.  Backend choice is a
per-solve cost model over measured host and device times with a periodically
re-probed sync latency — a transiently slow relay no longer disables the
device path for the life of the process.
"""

from __future__ import annotations

import functools
import threading
import time

import numpy as np

from hyperqueue_tpu.ops.assign import (
    greedy_cut_scan,
    greedy_cut_scan_numpy,
    host_visit_classes,
    scarcity_weights,
)
from hyperqueue_tpu.utils.constants import INF_TIME
from hyperqueue_tpu.utils import clock


def _bucket(n: int, floor: int) -> int:
    size = floor
    while size < n:
        size *= 2
    return size


# Device sync-latency probe, shared by all models in the process.
# None = not yet resolved; float = measured round-trip ms (inf = probe
# failed). Probed in a BACKGROUND daemon thread: in-process (an exclusively
# attached TPU cannot be re-initialized from a subprocess), and without
# ever blocking the caller (this environment's relay is known to WEDGE —
# a hung probe simply never resolves and the host solve stays selected).
# Unlike the original one-shot probe, a resolved measurement AGES OUT
# (REPROBE_INTERVAL_S): callers that pass max_age_s re-launch the probe in
# the background when the value is stale, so a relay that was slow at
# startup gets re-evaluated instead of benching the device forever.
_DEVICE_SYNC_MS: float | None = None
_PROBE_RUNNING = False
_PROBE_DONE = None  # threading.Event of the probe currently in flight
_PROBE_TS = 0.0     # monotonic stamp of the last RESOLVED probe
_PROBE_LOCK = threading.Lock()

# A tick must complete in single-digit milliseconds; a device whose
# dispatch+readback round trip alone exceeds this is not worth using for
# the solve (e.g. a TPU reached through a network relay with ~70 ms RTT —
# the kernel is sub-millisecond ON the device, but the scheduler runs on
# a host that cannot see the result sooner than the relay allows).
DISPATCH_LATENCY_BUDGET_MS = 5.0

# re-probe the sync latency when the last measurement is older than this
# and the host path is currently winning (the device path self-measures)
REPROBE_INTERVAL_S = 30.0

# while the cost model picks the host, retry the device path after this
# many solves even if the last device measurement lost — measurements go
# stale as shapes and relay health drift
DEVICE_RETRY_SOLVES = 512

# cost-model EWMA smoothing for per-shape host/device solve times
_EWMA_ALPHA = 0.25


def _start_probe_locked() -> None:
    global _PROBE_RUNNING, _PROBE_DONE
    _PROBE_RUNNING = True
    _PROBE_DONE = threading.Event()
    done = _PROBE_DONE

    def _probe():
        global _DEVICE_SYNC_MS, _PROBE_RUNNING, _PROBE_TS
        try:
            import jax
            import jax.numpy as jnp

            f = jax.jit(lambda v: (v * 2).sum())
            x = jax.device_put(jnp.arange(256, dtype=jnp.int32))
            np.asarray(f(x))  # compile + first transfer
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(f(x))
                ts.append((time.perf_counter() - t0) * 1000)
            measured = min(ts)
        except Exception:
            measured = float("inf")
        with _PROBE_LOCK:
            _DEVICE_SYNC_MS = measured
            _PROBE_TS = clock.monotonic()
            _PROBE_RUNNING = False
        done.set()

    threading.Thread(
        target=_probe, name="hq-device-probe", daemon=True
    ).start()


def device_sync_ms(wait_s: float = 0.0,
                   max_age_s: float | None = None) -> float | None:
    """Current known device sync round trip in ms.

    Starts the background probe on first call; returns None while the
    FIRST probe is unresolved (callers treat that as "use the host solve
    for now").  `max_age_s` triggers a background RE-probe when the last
    resolved measurement is older — the stale value keeps being returned
    until the new one lands, so callers never block on freshness.
    `wait_s` > 0 blocks up to that long for a result — benchmarks use it
    for a stable backend choice; the server never passes it."""
    with _PROBE_LOCK:
        if _DEVICE_SYNC_MS is None and not _PROBE_RUNNING:
            _start_probe_locked()
        elif (
            max_age_s is not None
            and not _PROBE_RUNNING
            and _DEVICE_SYNC_MS is not None
            and clock.monotonic() - _PROBE_TS > max_age_s
        ):
            _start_probe_locked()
        done = _PROBE_DONE
    if wait_s > 0 and done is not None:
        done.wait(wait_s)
    return _DEVICE_SYNC_MS


def _reset_probe_for_tests() -> None:
    global _DEVICE_SYNC_MS, _PROBE_RUNNING, _PROBE_DONE, _PROBE_TS
    with _PROBE_LOCK:
        _DEVICE_SYNC_MS = None
        _PROBE_RUNNING = False
        _PROBE_DONE = None
        _PROBE_TS = 0.0


class ResidentParanoidError(AssertionError):
    """The device-resident solve diverged from a fresh full-upload solve.

    Deliberately loud: the solver watchdog re-raises it instead of
    degrading (like tick_cache.paranoid_check, the paranoid contract is a
    debug tool — masking the divergence behind the fallback would both
    hide the bug and destroy the evidence via resident invalidation)."""


@functools.lru_cache(maxsize=64)
def _device_slicer(n_b: int, n_v: int, n_w: int):
    """Jitted padded->live slicer: the device path trims the padded
    (PB, PV, PW) counts to the live extents ON the device, so the host
    readback never copies (or receives) the padded volume and the
    resulting numpy array is C-contiguous (scheduler/tick.py relies on
    that to use the native nonzero on both backends).  Compiled once per
    distinct extent triple — live extents repeat in steady state."""
    import jax

    return jax.jit(lambda c: c[:n_b, :n_v, :n_w])


class _ReadyCounts:
    """Solve handle whose result is already materialized (host paths)."""

    __slots__ = ("_counts",)

    def __init__(self, counts: np.ndarray):
        self._counts = counts

    def result(self) -> np.ndarray:
        return self._counts


class _DeviceCounts:
    """In-flight device solve: `result()` materializes the (device-sliced)
    counts, re-synchronizes the residency mirror from the donated outputs,
    and feeds the cost model.  The dispatch is asynchronous — between
    construction and `result()` the device executes while the host does
    other tick work (the pipelined tick exploits exactly this window)."""

    __slots__ = ("_model", "_res", "_counts_dev", "_after", "_prep")

    def __init__(self, model, res, counts_dev, after, prep):
        self._model = model
        self._res = res
        self._counts_dev = counts_dev
        self._after = after  # (free_after, nt_after) device arrays
        self._prep = prep

    def result(self) -> np.ndarray:
        model = self._model
        prep = self._prep
        t0 = time.perf_counter()
        out = np.asarray(self._counts_dev)
        if self._after is not None:
            free_after, nt_after = self._after
            self._res.apply_outputs(
                np.asarray(free_after), np.asarray(nt_after)
            )
        t1 = time.perf_counter()
        sync_ms = (t1 - t0) * 1e3
        # the cost the TICK pays: dispatch + readback wait.  Synchronous
        # solves call result() immediately, so sync_ms contains the whole
        # device execution; pipelined solves call it a tick later, when
        # the execution already overlapped host work — charging the idle
        # gap would wrongly bench the device in the cost model.
        total_ms = prep["dispatch_ms"] + sync_ms
        model._observe("device", prep["shape_key"], total_ms)
        model.last_phases = {
            "pad_ms": prep["pad_ms"],
            "visit_ms": prep["visit_ms"],
            "dispatch_ms": prep["dispatch_ms"],
            "sync_ms": sync_ms,
        }
        model._maybe_paranoid_check(prep, out)
        if not out.flags.c_contiguous:  # pragma: no cover - np.asarray copy
            out = np.ascontiguousarray(out)
        return out


class GreedyCutScanModel:
    """Stateless apart from jit's compile cache and the device residency.

    backend: "auto" uses the jitted kernel on an accelerator and the numpy
    implementation on CPU hosts (identical semantics; the XLA while-loop is
    slower than numpy on CPU); "jax"/"numpy" force a path.  With an
    accelerator visible, "auto" runs a per-solve cost model (measured host
    vs device times per padded shape, periodically re-probed sync latency)
    instead of a one-shot permanent decision.
    """

    def __init__(
        self,
        worker_floor: int = 8,
        batch_floor: int = 8,
        resource_floor: int = 4,
        variant_floor: int = 1,
        backend: str = "auto",
    ):
        self.worker_floor = worker_floor
        self.batch_floor = batch_floor
        self.resource_floor = resource_floor
        self.variant_floor = variant_floor
        self.backend = backend
        # which path the last solve actually ran (host-native / host-numpy
        # / device-jax / device-sharded); bench.py and the DecisionRecords
        # report it, with last_backend_reason naming WHY it was chosen
        self.last_backend: str | None = None
        self.last_backend_reason: str = ""
        self._use_numpy: bool | None = (
            None if backend == "auto" else (backend == "numpy")
        )
        # persistent padded buffers, keyed by bucket shape: steady-state
        # ticks reuse the same host arrays (and therefore the same
        # compiled program and device buffer donation) instead of
        # re-allocating and re-zeroing every call
        self._buffers: dict[tuple, dict] = {}
        # counts NEW bucket-shape allocations — each implies a fresh XLA
        # compilation on the jit path, so a steady-state tick must not
        # increment it (asserted by bench.py --smoke)
        self.shape_allocations = 0
        # per-phase latency of the last solve() in ms (pad/visit/dispatch/
        # sync) — consumed by the tick's phase breakdown
        self.last_phases: dict = {}
        # device residency (parallel/resident.py), built on first device
        # solve; None until then
        self._res = None
        # per-shape EWMA of measured end-to-end solve ms, host vs device —
        # the adaptive backend decision reads these
        self._cost: dict[str, dict[tuple, float]] = {"host": {}, "device": {}}
        self._solves_since_device = 0
        # paranoid mode: every Nth RESIDENT device solve re-runs the same
        # padded inputs through a fresh full-upload solve and asserts
        # bitwise count equality (0 = off); wired to `--paranoid-tick`
        self.paranoid_resident = 0
        self._resident_solves = 0
        self.paranoid_checks = 0

    # -- backend selection -------------------------------------------------
    def _sticky_host(self) -> bool | None:
        """Process-sticky part of the backend decision: True = host
        forever (forced numpy, CPU-pinned env, CPU jax backend, failed
        init), False = device forced, None = accelerator visible — decide
        per solve (_backend_decision)."""
        if self._use_numpy is not None:
            return self._use_numpy
        import os

        if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
            # the environment pins the cpu backend: decide without
            # importing jax at all (a multi-second cost per server
            # process that the host solve never pays back)
            self._use_numpy = True
            return True
        import jax

        try:
            backend = jax.default_backend()
        except RuntimeError:
            # the configured accelerator backend failed to initialize
            # (e.g. an unhealthy TPU relay at process start): the solve
            # must keep working on the host — and the choice is sticky,
            # because jax caches the failed init for the process anyway
            self._use_numpy = True
            import logging

            logging.getLogger(__name__).warning(
                "jax backend unavailable; solving on the host (numpy)",
                exc_info=True,
            )
            return True
        if backend == "cpu":
            # the XLA while-loop overhead loses to numpy on CPU hosts
            self._use_numpy = True
            return True
        return None

    def _numpy_path(self) -> bool:
        """Compatibility probe: True when the solve is host-pinned for the
        process.  With an accelerator visible the answer is per-solve
        (_backend_decision); this returns False then."""
        return self._sticky_host() is True

    def _backend_decision(self, shape_key: tuple) -> tuple[str, str]:
        """("host"|"device", reason) for THIS solve.

        The cost model compares per-shape EWMAs of measured end-to-end
        solve times.  Until a host measurement exists the original budget
        rule applies (device only when its sync round trip fits the tick
        budget); a benched device is retried after DEVICE_RETRY_SOLVES and
        the sync probe re-runs every REPROBE_INTERVAL_S, so neither a slow
        first probe nor a transiently wedged relay is permanent."""
        sticky = self._sticky_host()
        if sticky is True:
            return "host", (
                "forced-numpy" if self.backend == "numpy" else "cpu-host"
            )
        if sticky is False:
            return "device", "forced-jax"
        sync_ms = device_sync_ms(max_age_s=REPROBE_INTERVAL_S)
        if sync_ms is None:
            return "host", "sync-probe-pending"
        if sync_ms == float("inf"):
            return "host", "sync-probe-failed"
        host_est = self._cost["host"].get(shape_key)
        dev_est = self._cost["device"].get(shape_key)
        if dev_est is not None and host_est is not None:
            if dev_est <= host_est:
                return "device", "cost-model"
            if (
                self._solves_since_device >= DEVICE_RETRY_SOLVES
                and sync_ms < host_est
            ):
                return "device", "periodic-retry"
            return "host", (
                f"cost-model (device {dev_est:.1f}ms > host {host_est:.1f}ms)"
            )
        if host_est is None and dev_est is not None:
            return "device", "cost-model"
        if host_est is not None:
            # no device measurement for this shape yet: its end-to-end time
            # is at least the sync round trip — try it when that alone
            # could beat the measured host time
            if sync_ms < host_est:
                return "device", "first-measurement"
            return "host", (
                f"sync {sync_ms:.1f}ms exceeds host {host_est:.1f}ms"
            )
        # no measurements at all: the original conservative budget rule
        if sync_ms <= DISPATCH_LATENCY_BUDGET_MS:
            return "device", "sync-within-budget"
        return "host", (
            f"sync {sync_ms:.1f}ms exceeds the "
            f"{DISPATCH_LATENCY_BUDGET_MS:.0f}ms budget"
        )

    def _observe(self, kind: str, shape_key: tuple, ms: float) -> None:
        table = self._cost[kind]
        prev = table.get(shape_key)
        table[shape_key] = (
            ms if prev is None else prev + _EWMA_ALPHA * (ms - prev)
        )
        if kind == "device":
            self._solves_since_device = 0

    # -- solve -------------------------------------------------------------
    def solve(
        self,
        free: np.ndarray,       # (W, R) int32
        nt_free: np.ndarray,    # (W,) int32
        lifetime: np.ndarray,   # (W,) int32 seconds, INF_TIME when unlimited
        needs: np.ndarray,      # (B, V, R) int32
        sizes: np.ndarray,      # (B,) int32/int64
        min_time: np.ndarray,   # (B, V) int32 seconds
        priorities: list | None = None,  # accepted for model-interface
                                         # parity; rows are already in
                                         # descending priority order
        total: np.ndarray | None = None,     # (W, R) int32 pool totals
        all_mask: np.ndarray | None = None,  # (B, V, R) int32 0/1 ALL-policy
        weights: np.ndarray | None = None,   # (B, V) request weights —
                                             # consumed on the host by
                                             # run_tick's batch ordering;
                                             # accepted for interface parity
        gang_nodes: np.ndarray | None = None,    # (B,) int32 gang sizes
        gang_ok: np.ndarray | None = None,       # (W,) int32 host idleness
        group_onehot: np.ndarray | None = None,  # (W, G) int32 group map
        affinity: np.ndarray | None = None,      # (B, W) float policy
                                                 # weights (heterogeneity
                                                 # matrix rows per batch)
    ) -> np.ndarray:
        """Returns counts (B, V, W) int32 (unpadded, C-contiguous)."""
        return self.solve_async(
            free, nt_free, lifetime, needs, sizes, min_time,
            priorities=priorities, total=total, all_mask=all_mask,
            weights=weights, gang_nodes=gang_nodes, gang_ok=gang_ok,
            group_onehot=group_onehot, affinity=affinity,
        ).result()

    def solve_async(
        self, free, nt_free, lifetime, needs, sizes, min_time,
        priorities=None, total=None, all_mask=None, weights=None,
        gang_nodes=None, gang_ok=None, group_onehot=None, affinity=None,
    ):
        """Dispatch one solve; returns a handle whose `.result()` yields the
        unpadded counts.  Host backends compute eagerly (the handle is just
        a box); the device backend returns with the program ENQUEUED, so
        the caller can overlap host work with the device execution — the
        pipelined tick (scheduler/pipeline.py) maps the previous solve
        during exactly this window."""
        prep = self._prepare(
            free, nt_free, lifetime, needs, sizes, min_time, total, all_mask,
            gang_nodes=gang_nodes, gang_ok=gang_ok, group_onehot=group_onehot,
            affinity=affinity,
        )
        backend, reason = self._backend_decision(prep["shape_key"])
        self.last_backend_reason = reason
        self._solves_since_device += 1
        if backend == "host":
            return self._host_solve(prep)
        try:
            return self._device_solve(prep)
        except Exception as e:  # noqa: BLE001 - degrade, don't kill the tick
            import logging

            logging.getLogger(__name__).warning(
                "device solve dispatch failed (%s); falling back to the "
                "host solve for this tick", e, exc_info=True,
            )
            self.invalidate_resident()
            self.last_backend_reason = f"device-dispatch-failed: {e}"
            return self._host_solve(prep)

    # -- preparation (shared by every backend) ----------------------------
    def _prepare(self, free, nt_free, lifetime, needs, sizes, min_time,
                 total, all_mask, gang_nodes=None, gang_ok=None,
                 group_onehot=None, affinity=None) -> dict:
        _t0 = time.perf_counter()
        n_w, n_r = free.shape
        n_b, n_v, _ = needs.shape

        pw = self._worker_bucket(n_w)
        pb = _bucket(max(n_b, 1), self.batch_floor)
        pr = _bucket(max(n_r, 1), self.resource_floor)
        pv = _bucket(max(n_v, 1), self.variant_floor)

        if all_mask is not None and not np.any(all_mask):
            all_mask = None  # keep the common no-ALL compiled program
        has_all = all_mask is not None
        if gang_nodes is not None and not np.any(np.asarray(gang_nodes) > 0):
            gang_nodes = None  # keep the common no-gang compiled program
        has_gang = gang_nodes is not None
        if affinity is not None:
            affinity = np.asarray(affinity, dtype=np.float32)
            if (
                affinity.size == 0
                or (affinity.min() == affinity.max() and affinity.min() > 0)
            ):
                # a uniform positive matrix cannot change the visit order or
                # exclude a worker: keep the flat-objective program
                affinity = None
        has_pmask = affinity is not None and bool(np.any(affinity <= 0))

        buf = self._get_buffers(pw, pb, pr, pv, has_all)
        free_p = buf["free"]
        nt_p = buf["nt"]
        life_p = buf["life"]
        needs_p = buf["needs"]
        sizes_p = buf["sizes"]
        mt_p = buf["mt"]
        # zero whatever the PREVIOUS call wrote beyond this call's extents
        # (same bucket, smaller active region), then fill the active slices
        lw, lb, lr, lv = buf["extents"]
        if lw > n_w:
            free_p[n_w:lw] = 0
            nt_p[n_w:lw] = 0
            life_p[n_w:lw] = 0
        if lr > n_r:
            free_p[:n_w, n_r:lr] = 0
            needs_p[:n_b, :n_v, n_r:lr] = 0
        if lb > n_b:
            needs_p[n_b:lb] = 0
            sizes_p[n_b:lb] = 0
        if lv > n_v:
            needs_p[:n_b, n_v:lv] = 0
        buf["extents"] = (n_w, n_b, n_r, n_v)

        free_p[:n_w, :n_r] = free
        nt_p[:n_w] = nt_free
        life_p[:n_w] = lifetime
        needs_p[:n_b, :n_v, :n_r] = needs
        sizes_p[:n_b] = np.minimum(sizes, np.int32(2**30))
        mt_p[:n_b, :n_v] = min_time
        # absent variants must never be eligible: give them infinite
        # min_time; padded batch rows get plain zeros in the live-variant
        # columns (size 0 keeps them inert either way, but the buffer must
        # match a fresh allocation exactly across variant-count changes)
        mt_p[:, n_v:] = int(INF_TIME)
        mt_p[n_b:, :n_v] = 0
        total_p = amask_p = None
        if has_all:
            total_p = buf["total"]
            amask_p = buf["amask"]
            if lw > n_w:
                total_p[n_w:lw] = 0
            if lr > n_r:
                total_p[:n_w, n_r:lr] = 0
                amask_p[:n_b, :n_v, n_r:lr] = 0
            if lb > n_b:
                amask_p[n_b:lb] = 0
            if lv > n_v:
                amask_p[:n_b, n_v:lv] = 0
            total_p[:n_w, :n_r] = total if total is not None else free
            amask_p[:n_b, :n_v, :n_r] = all_mask
        gang_p = gok_p = goh_p = None
        pg = 0
        if has_gang:
            # gang inputs are FRESH per-solve allocations, not persistent
            # buffers: gang rows appear on a minority of ticks and keying
            # the donated-buffer cache on their presence would churn the
            # steady-state shape; the arrays are tiny ((B,), (W,), (W, G))
            n_g = group_onehot.shape[1] if group_onehot is not None else 1
            pg = _bucket(max(n_g, 1), 4)
            gang_p = np.zeros(pb, dtype=np.int32)
            gang_p[:n_b] = gang_nodes
            gok_p = np.zeros(pw, dtype=np.int32)
            if gang_ok is not None:
                gok_p[:n_w] = gang_ok
            goh_p = np.zeros((pw, pg), dtype=np.int32)
            if group_onehot is not None:
                goh_p[:n_w, :n_g] = group_onehot
        aff_p = pmask_p = None
        if affinity is not None:
            # like the gang inputs: FRESH per-solve allocations — weighted
            # objectives appear only under an active policy, and keying the
            # donated-buffer cache on their presence would churn the
            # steady-state shape; both arrays are small ((B, W))
            aff_p = np.zeros((pb, pw), dtype=np.float32)
            aff_p[:n_b, :n_w] = affinity
            if has_pmask:
                pmask_p = np.zeros((pb, pw), dtype=np.int32)
                pmask_p[:n_b, :n_w] = (affinity > 0).astype(np.int32)
        _t1 = time.perf_counter()

        scarcity = np.asarray(
            scarcity_weights(free_p.astype(np.int64).sum(axis=0))
        ).astype(np.float32)
        class_m, order_ids = host_visit_classes(
            free_p, needs_p, scarcity, all_mask=amask_p, affinity=aff_p
        )
        # bucket the mask-table dimension so steady-state ticks reuse the
        # compiled program; padding rows are all-class-0 (never referenced)
        pm = _bucket(class_m.shape[0], 4)
        if pm > class_m.shape[0]:
            pad = np.zeros((pm - class_m.shape[0], pw), dtype=np.int32)
            class_m = np.concatenate([class_m, pad], axis=0)
        _t2 = time.perf_counter()

        return {
            "free_p": free_p, "nt_p": nt_p, "life_p": life_p,
            "needs_p": needs_p, "sizes_p": sizes_p, "mt_p": mt_p,
            "total_p": total_p, "amask_p": amask_p,
            "gang_p": gang_p, "gok_p": gok_p, "goh_p": goh_p,
            "pmask_p": pmask_p,
            "class_m": class_m, "order_ids": order_ids,
            "extents": (n_b, n_v, n_w),
            "shape_key": (pw, pb, pr, pv, pm, has_all, has_gang, pg,
                          has_pmask),
            "has_all": has_all, "has_gang": has_gang,
            "has_pmask": has_pmask,
            "pad_ms": (_t1 - _t0) * 1e3,
            "visit_ms": (_t2 - _t1) * 1e3,
            "dispatch_ms": 0.0,
        }

    # -- host path ---------------------------------------------------------
    def _host_solve(self, prep) -> _ReadyCounts:
        _t0 = time.perf_counter()
        counts = self._host_counts(prep)
        _t1 = time.perf_counter()
        n_b, n_v, n_w = prep["extents"]
        out = np.ascontiguousarray(
            np.asarray(counts)[:n_b, :n_v, :n_w]
        )
        _t2 = time.perf_counter()
        self.last_phases = {
            "pad_ms": prep["pad_ms"],
            "visit_ms": prep["visit_ms"],
            "dispatch_ms": (_t1 - _t0) * 1e3,
            "sync_ms": (_t2 - _t1) * 1e3,
        }
        self._observe("host", prep["shape_key"], (_t2 - _t0) * 1e3)
        return _ReadyCounts(out)

    def _host_counts(self, prep):
        """The host solve on fully padded inputs: the native C++ scan
        (identical semantics, with saturation early-exits) when the lib is
        available, else numpy.  Gang rows are numpy-only — the native scan
        predates the all-or-nothing column groups, so a gang solve bypasses
        it rather than silently dropping the constraint."""
        from hyperqueue_tpu.utils.native import native_cut_scan

        if prep["has_gang"] or prep["has_pmask"]:
            # the native scan predates both the gang rows and the policy
            # mask: a solve carrying either bypasses it rather than
            # silently dropping the constraint
            self.last_backend = "host-numpy"
            counts, _free_after, _nt_after = greedy_cut_scan_numpy(
                prep["free_p"], prep["nt_p"], prep["life_p"],
                prep["needs_p"], prep["sizes_p"], prep["mt_p"],
                prep["class_m"], prep["order_ids"], total=prep["total_p"],
                all_mask=prep["amask_p"], gang_nodes=prep["gang_p"],
                gang_ok=prep["gok_p"], group_onehot=prep["goh_p"],
                policy_mask=prep["pmask_p"],
            )
            return counts
        counts = native_cut_scan(
            prep["free_p"], prep["nt_p"], prep["life_p"], prep["needs_p"],
            prep["sizes_p"], prep["mt_p"], prep["class_m"],
            prep["order_ids"], total=prep["total_p"],
            all_mask=prep["amask_p"],
        )
        if counts is not None:
            self.last_backend = "host-native"
            return counts
        self.last_backend = "host-numpy"
        counts, _free_after, _nt_after = greedy_cut_scan_numpy(
            prep["free_p"], prep["nt_p"], prep["life_p"], prep["needs_p"],
            prep["sizes_p"], prep["mt_p"], prep["class_m"],
            prep["order_ids"], total=prep["total_p"],
            all_mask=prep["amask_p"],
        )
        return counts

    # -- device path (resident state + donated buffers) --------------------
    _device_backend_name = "device-jax"

    def _residency(self):
        if self._res is None:
            from hyperqueue_tpu.parallel.resident import DeviceResidency

            self._res = DeviceResidency()
        return self._res

    def invalidate_resident(self) -> None:
        """Drop the device-resident state (next device solve re-uploads in
        full).  The watchdog calls this whenever a solve is abandoned or
        degraded mid-flight — the device buffers may then hold outputs the
        host never accounted for."""
        if self._res is not None:
            self._res.invalidate()

    def resident_stats(self) -> dict:
        base = {"backend": self.last_backend,
                "backend_reason": self.last_backend_reason}
        if self._res is not None:
            base.update(self._res.stats())
        base["paranoid_checks"] = self.paranoid_checks
        return base

    def _device_solve(self, prep) -> _DeviceCounts:
        _t0 = time.perf_counter()
        res = self._residency()
        free_d, nt_d, life_d, total_d = res.sync(
            prep["free_p"], prep["nt_p"], prep["life_p"], prep["total_p"]
        )
        counts, free_after, nt_after = self._kernel_dispatch(
            res, free_d, nt_d, life_d, total_d, prep
        )
        res.adopt_outputs(free_after, nt_after)
        n_b, n_v, n_w = prep["extents"]
        counts_dev = _device_slicer(n_b, n_v, n_w)(counts)
        prep["dispatch_ms"] = (time.perf_counter() - _t0) * 1e3
        self.last_backend = self._device_backend_name
        self._resident_solves += 1
        return _DeviceCounts(
            self, res, counts_dev, (free_after, nt_after), prep
        )

    def _kernel_dispatch(self, res, free_d, nt_d, life_d, total_d, prep):
        """Enqueue the jitted kernel on the resident buffers (donating
        free/nt_free); replicated inputs ride the placement cache.
        Overridden by the multichip model to shard the worker axis."""
        return greedy_cut_scan(
            free_d, nt_d, life_d,
            res.place_cached("needs", prep["needs_p"]),
            res.place_cached("sizes", prep["sizes_p"]),
            res.place_cached("min_time", prep["mt_p"]),
            res.place_cached("class_m", prep["class_m"]),
            res.place_cached("order_ids", prep["order_ids"]),
            total=total_d,
            all_mask=res.place_cached("all_mask", prep["amask_p"]),
            gang_nodes=res.place_cached("gang_nodes", prep["gang_p"]),
            gang_ok=res.place_cached("gang_ok", prep["gok_p"]),
            group_onehot=res.place_cached("group_onehot", prep["goh_p"]),
            policy_mask=res.place_cached("policy_mask", prep["pmask_p"]),
        )

    def _maybe_paranoid_check(self, prep, out: np.ndarray) -> None:
        """Resident-vs-fresh bit-exactness guard: re-run the SAME padded
        inputs through a fresh full-upload device solve and assert count
        equality.  The padded buffers are untouched between dispatch and
        result (the pipeline maps a pending solve before preparing the
        next), so the comparison is exact by construction."""
        if (
            not self.paranoid_resident
            or self._resident_solves % self.paranoid_resident != 0
        ):
            return
        self.paranoid_checks += 1
        fresh = self._fresh_device_counts(prep)
        n_b, n_v, n_w = prep["extents"]
        fresh = np.asarray(fresh)[:n_b, :n_v, :n_w]
        if not np.array_equal(out, fresh):
            raise ResidentParanoidError(
                "paranoid-resident: device-resident counts diverge from a "
                "fresh full-upload solve of the same padded inputs"
            )

    def _fresh_device_counts(self, prep):
        """Full-upload reference solve (no residency, no placement cache);
        the donated jit consumes the fresh uploads, never the resident
        buffers."""
        counts, _f, _n = greedy_cut_scan(
            prep["free_p"].copy(), prep["nt_p"].copy(), prep["life_p"],
            prep["needs_p"], prep["sizes_p"], prep["mt_p"],
            prep["class_m"], prep["order_ids"],
            total=None if prep["total_p"] is None else prep["total_p"].copy(),
            all_mask=prep["amask_p"], gang_nodes=prep["gang_p"],
            gang_ok=prep["gok_p"], group_onehot=prep["goh_p"],
            policy_mask=prep["pmask_p"],
        )
        return counts

    # -- padded-buffer management -----------------------------------------
    def _get_buffers(self, pw: int, pb: int, pr: int, pv: int,
                     has_all: bool) -> dict:
        """Persistent padded host buffers for one bucket shape.

        The kernel's inputs change every tick but their BUCKETED shapes
        repeat; reusing the arrays avoids a full allocate+memset per call
        and keeps the jit cache keyed on stable shapes.  A new key means a
        new XLA compilation on the device path — counted in
        `shape_allocations` so the smoke bench can assert steady-state
        ticks trigger none.
        """
        key = (pw, pb, pr, pv, has_all)
        buf = self._buffers.get(key)
        if buf is not None:
            # true LRU: a hit moves the shape to the end so the steady-state
            # bucket is never the eviction victim when rare shapes pass by
            self._buffers.pop(key)
            self._buffers[key] = buf
        if buf is None:
            self.shape_allocations += 1
            buf = {
                "free": np.zeros((pw, pr), dtype=np.int32),
                "nt": np.zeros(pw, dtype=np.int32),
                "life": np.zeros(pw, dtype=np.int32),
                "needs": np.zeros((pb, pv, pr), dtype=np.int32),
                "sizes": np.zeros(pb, dtype=np.int32),
                "mt": np.zeros((pb, pv), dtype=np.int32),
                "extents": (0, 0, 0, 0),
            }
            if has_all:
                buf["total"] = np.zeros((pw, pr), dtype=np.int32)
                buf["amask"] = np.zeros((pb, pv, pr), dtype=np.int32)
            self._buffers[key] = buf
            # bound the cache: bucket shapes are few (powers of two), but
            # a pathological workload must not grow this without limit
            while len(self._buffers) > 8:
                self._buffers.pop(next(iter(self._buffers)))
        return buf

    def _worker_bucket(self, n_w: int) -> int:
        return _bucket(n_w, self.worker_floor)
