"""Greedy cut-scan scheduling model: bucketing + compile-cache around the kernel.

The kernel (ops/assign.py) needs static shapes; real ticks have varying worker
counts, batch counts, resource counts and variant counts. This wrapper pads
every dimension up to a bucket (powers of two with a small floor) so that in
steady state every tick hits one already-compiled program — the same trick the
reference uses to keep its MILP warm is unnecessary there but essential under
XLA (see SURVEY.md §7 "Fixed shapes on TPU").

Padding is semantically inert: padded workers have zero free resources and
zero task slots; padded batches have size 0; padded variants are all-zero
need rows which `_variant_capacity` masks off.
"""

from __future__ import annotations

import numpy as np

from hyperqueue_tpu.ops.assign import (
    greedy_cut_scan,
    greedy_cut_scan_numpy,
    host_visit_classes,
    scarcity_weights,
)
from hyperqueue_tpu.utils.constants import INF_TIME


def _bucket(n: int, floor: int) -> int:
    size = floor
    while size < n:
        size *= 2
    return size


# One-shot device sync-latency probe, shared by all models in the process.
# None = not yet resolved; float = measured round-trip ms (inf = probe
# failed). Probed in a BACKGROUND daemon thread: in-process (an exclusively
# attached TPU cannot be re-initialized from a subprocess), and without
# ever blocking the caller (this environment's relay is known to WEDGE —
# a hung probe simply never resolves and the host solve stays selected).
_DEVICE_SYNC_MS: float | None = None
_PROBE_STARTED = False
_PROBE_DONE = None  # threading.Event once started

# A tick must complete in single-digit milliseconds; a device whose
# dispatch+readback round trip alone exceeds this is not worth using for
# the solve (e.g. a TPU reached through a network relay with ~70 ms RTT —
# the kernel is sub-millisecond ON the device, but the scheduler runs on
# a host that cannot see the result sooner than the relay allows).
DISPATCH_LATENCY_BUDGET_MS = 5.0


def device_sync_ms(wait_s: float = 0.0) -> float | None:
    """Current known device sync round trip in ms.

    Starts the background probe on first call; returns None while it is
    unresolved (callers treat that as "use the host solve for now").
    `wait_s` > 0 blocks up to that long for a result — benchmarks use it
    for a stable backend choice; the server never passes it."""
    global _PROBE_STARTED, _PROBE_DONE
    if not _PROBE_STARTED:
        import threading

        _PROBE_STARTED = True
        _PROBE_DONE = threading.Event()

        def _probe():
            global _DEVICE_SYNC_MS
            import time

            try:
                import jax
                import jax.numpy as jnp

                f = jax.jit(lambda v: (v * 2).sum())
                x = jax.device_put(jnp.arange(256, dtype=jnp.int32))
                np.asarray(f(x))  # compile + first transfer
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    np.asarray(f(x))
                    ts.append((time.perf_counter() - t0) * 1000)
                _DEVICE_SYNC_MS = min(ts)
            except Exception:
                _DEVICE_SYNC_MS = float("inf")
            finally:
                _PROBE_DONE.set()

        threading.Thread(
            target=_probe, name="hq-device-probe", daemon=True
        ).start()
    if wait_s > 0:
        _PROBE_DONE.wait(wait_s)
    return _DEVICE_SYNC_MS


class GreedyCutScanModel:
    """Stateless apart from jit's own compile cache.

    backend: "auto" uses the jitted kernel on an accelerator and the numpy
    implementation on CPU hosts (identical semantics; the XLA while-loop is
    slower than numpy on CPU); "jax"/"numpy" force a path.
    """

    def __init__(
        self,
        worker_floor: int = 8,
        batch_floor: int = 8,
        resource_floor: int = 4,
        variant_floor: int = 1,
        backend: str = "auto",
    ):
        self.worker_floor = worker_floor
        self.batch_floor = batch_floor
        self.resource_floor = resource_floor
        self.variant_floor = variant_floor
        self.backend = backend
        # which path the last solve actually ran (host-native / host-numpy
        # / device-jax); bench.py reports it
        self.last_backend: str | None = None
        self._use_numpy: bool | None = (
            None if backend == "auto" else (backend == "numpy")
        )
        # persistent padded buffers, keyed by bucket shape: steady-state
        # ticks reuse the same host arrays (and therefore the same
        # compiled program and device buffer donation) instead of
        # re-allocating and re-zeroing every call
        self._buffers: dict[tuple, dict] = {}
        # counts NEW bucket-shape allocations — each implies a fresh XLA
        # compilation on the jit path, so a steady-state tick must not
        # increment it (asserted by bench.py --smoke)
        self.shape_allocations = 0
        # per-phase latency of the last solve() in ms (pad/visit/dispatch/
        # sync) — consumed by the tick's phase breakdown
        self.last_phases: dict = {}

    def _numpy_path(self) -> bool:
        if self._use_numpy is None:
            import os

            if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
                # the environment pins the cpu backend: decide without
                # importing jax at all (a multi-second cost per server
                # process that the host solve never pays back)
                self._use_numpy = True
                return True
            import jax

            try:
                backend = jax.default_backend()
            except RuntimeError:
                # the configured accelerator backend failed to initialize
                # (e.g. an unhealthy TPU relay at process start): the solve
                # must keep working on the host — and the choice is sticky,
                # because jax caches the failed init for the process anyway
                self._use_numpy = True
                import logging

                logging.getLogger(__name__).warning(
                    "jax backend unavailable; solving on the host (numpy)",
                    exc_info=True,
                )
                return True
            if backend == "cpu":
                # the XLA while-loop overhead loses to numpy on CPU hosts
                self._use_numpy = True
            else:
                # an accelerator is visible — but only worth using when the
                # host can actually get the answer back within the tick
                # budget (a tunneled chip with tens of ms of relay RTT runs
                # the kernel in <1 ms and then sits on the result; the host
                # solve at ~16 ms for 1M x 1k beats it end to end). The
                # probe runs in the background: until it resolves, solve on
                # the host WITHOUT caching the decision (never blocks the
                # server's event loop; a wedged relay simply never resolves)
                sync_ms = device_sync_ms()
                if sync_ms is None:
                    return True  # provisional — retry next solve
                self._use_numpy = sync_ms > DISPATCH_LATENCY_BUDGET_MS
                if self._use_numpy:
                    import logging

                    logging.getLogger(__name__).warning(
                        "device sync round trip %.1f ms exceeds the %.0f ms "
                        "tick budget: solving on the host (numpy) instead",
                        sync_ms, DISPATCH_LATENCY_BUDGET_MS,
                    )
        return self._use_numpy

    def solve(
        self,
        free: np.ndarray,       # (W, R) int32
        nt_free: np.ndarray,    # (W,) int32
        lifetime: np.ndarray,   # (W,) int32 seconds, INF_TIME when unlimited
        needs: np.ndarray,      # (B, V, R) int32
        sizes: np.ndarray,      # (B,) int32/int64
        min_time: np.ndarray,   # (B, V) int32 seconds
        priorities: list | None = None,  # accepted for model-interface
                                         # parity; rows are already in
                                         # descending priority order
        total: np.ndarray | None = None,     # (W, R) int32 pool totals
        all_mask: np.ndarray | None = None,  # (B, V, R) int32 0/1 ALL-policy
        weights: np.ndarray | None = None,   # (B, V) request weights —
                                             # consumed on the host by
                                             # run_tick's batch ordering;
                                             # accepted for interface parity
    ) -> np.ndarray:
        """Returns counts (B, V, W) int32 (unpadded)."""
        import time as _time

        _t0 = _time.perf_counter()
        n_w, n_r = free.shape
        n_b, n_v, _ = needs.shape

        pw = self._worker_bucket(n_w)
        pb = _bucket(max(n_b, 1), self.batch_floor)
        pr = _bucket(max(n_r, 1), self.resource_floor)
        pv = _bucket(max(n_v, 1), self.variant_floor)

        if all_mask is not None and not np.any(all_mask):
            all_mask = None  # keep the common no-ALL compiled program
        has_all = all_mask is not None

        buf = self._get_buffers(pw, pb, pr, pv, has_all)
        free_p = buf["free"]
        nt_p = buf["nt"]
        life_p = buf["life"]
        needs_p = buf["needs"]
        sizes_p = buf["sizes"]
        mt_p = buf["mt"]
        # zero whatever the PREVIOUS call wrote beyond this call's extents
        # (same bucket, smaller active region), then fill the active slices
        lw, lb, lr, lv = buf["extents"]
        if lw > n_w:
            free_p[n_w:lw] = 0
            nt_p[n_w:lw] = 0
            life_p[n_w:lw] = 0
        if lr > n_r:
            free_p[:n_w, n_r:lr] = 0
            needs_p[:n_b, :n_v, n_r:lr] = 0
        if lb > n_b:
            needs_p[n_b:lb] = 0
            sizes_p[n_b:lb] = 0
        if lv > n_v:
            needs_p[:n_b, n_v:lv] = 0
        buf["extents"] = (n_w, n_b, n_r, n_v)

        free_p[:n_w, :n_r] = free
        nt_p[:n_w] = nt_free
        life_p[:n_w] = lifetime
        needs_p[:n_b, :n_v, :n_r] = needs
        sizes_p[:n_b] = np.minimum(sizes, np.int32(2**30))
        mt_p[:n_b, :n_v] = min_time
        # absent variants must never be eligible: give them infinite
        # min_time; padded batch rows get plain zeros in the live-variant
        # columns (size 0 keeps them inert either way, but the buffer must
        # match a fresh allocation exactly across variant-count changes)
        mt_p[:, n_v:] = int(INF_TIME)
        mt_p[n_b:, :n_v] = 0
        total_p = amask_p = None
        if has_all:
            total_p = buf["total"]
            amask_p = buf["amask"]
            if lw > n_w:
                total_p[n_w:lw] = 0
            if lr > n_r:
                total_p[:n_w, n_r:lr] = 0
                amask_p[:n_b, :n_v, n_r:lr] = 0
            if lb > n_b:
                amask_p[n_b:lb] = 0
            if lv > n_v:
                amask_p[:n_b, n_v:lv] = 0
            total_p[:n_w, :n_r] = total if total is not None else free
            amask_p[:n_b, :n_v, :n_r] = all_mask
        _t1 = _time.perf_counter()

        scarcity = np.asarray(
            scarcity_weights(free_p.astype(np.int64).sum(axis=0))
        ).astype(np.float32)
        class_m, order_ids = host_visit_classes(
            free_p, needs_p, scarcity, all_mask=amask_p
        )
        # bucket the mask-table dimension so steady-state ticks reuse the
        # compiled program; padding rows are all-class-0 (never referenced)
        pm = _bucket(class_m.shape[0], 4)
        if pm > class_m.shape[0]:
            pad = np.zeros((pm - class_m.shape[0], pw), dtype=np.int32)
            class_m = np.concatenate([class_m, pad], axis=0)
        _t2 = _time.perf_counter()

        counts = self._solve_padded(
            free_p, nt_p, life_p, needs_p, sizes_p, mt_p, class_m, order_ids,
            total_p=total_p, amask_p=amask_p,
        )
        _t3 = _time.perf_counter()
        out = np.asarray(counts)[:n_b, :n_v, :n_w]
        _t4 = _time.perf_counter()
        self.last_phases = {
            "pad_ms": (_t1 - _t0) * 1e3,
            "visit_ms": (_t2 - _t1) * 1e3,
            "dispatch_ms": (_t3 - _t2) * 1e3,
            "sync_ms": (_t4 - _t3) * 1e3,
        }
        return out

    def _get_buffers(self, pw: int, pb: int, pr: int, pv: int,
                     has_all: bool) -> dict:
        """Persistent padded host buffers for one bucket shape.

        The kernel's inputs change every tick but their BUCKETED shapes
        repeat; reusing the arrays avoids a full allocate+memset per call
        and keeps the jit cache keyed on stable shapes.  A new key means a
        new XLA compilation on the device path — counted in
        `shape_allocations` so the smoke bench can assert steady-state
        ticks trigger none.
        """
        key = (pw, pb, pr, pv, has_all)
        buf = self._buffers.get(key)
        if buf is not None:
            # true LRU: a hit moves the shape to the end so the steady-state
            # bucket is never the eviction victim when rare shapes pass by
            self._buffers.pop(key)
            self._buffers[key] = buf
        if buf is None:
            self.shape_allocations += 1
            buf = {
                "free": np.zeros((pw, pr), dtype=np.int32),
                "nt": np.zeros(pw, dtype=np.int32),
                "life": np.zeros(pw, dtype=np.int32),
                "needs": np.zeros((pb, pv, pr), dtype=np.int32),
                "sizes": np.zeros(pb, dtype=np.int32),
                "mt": np.zeros((pb, pv), dtype=np.int32),
                "extents": (0, 0, 0, 0),
            }
            if has_all:
                buf["total"] = np.zeros((pw, pr), dtype=np.int32)
                buf["amask"] = np.zeros((pb, pv, pr), dtype=np.int32)
            self._buffers[key] = buf
            # bound the cache: bucket shapes are few (powers of two), but
            # a pathological workload must not grow this without limit
            while len(self._buffers) > 8:
                self._buffers.pop(next(iter(self._buffers)))
        return buf

    def _worker_bucket(self, n_w: int) -> int:
        return _bucket(n_w, self.worker_floor)

    def _solve_padded(
        self, free_p, nt_p, life_p, needs_p, sizes_p, mt_p, class_m,
        order_ids, total_p=None, amask_p=None,
    ):
        """Run the kernel on fully padded inputs; overridden by the
        multi-chip model (models/multichip.py) to shard the worker axis."""
        if self._numpy_path():
            # host solve: the native C++ scan (identical semantics, with
            # saturation early-exits) when the lib is available, else numpy
            from hyperqueue_tpu.utils.native import native_cut_scan

            counts = native_cut_scan(
                free_p, nt_p, life_p, needs_p, sizes_p, mt_p, class_m,
                order_ids, total=total_p, all_mask=amask_p,
            )
            if counts is not None:
                self.last_backend = "host-native"
                return counts
            self.last_backend = "host-numpy"
            counts, _free_after, _nt_after = greedy_cut_scan_numpy(
                free_p, nt_p, life_p, needs_p, sizes_p, mt_p, class_m,
                order_ids, total=total_p, all_mask=amask_p,
            )
            return counts
        self.last_backend = "device-jax"
        counts, _free_after, _nt_after = greedy_cut_scan(
            free_p, nt_p, life_p, needs_p, sizes_p, mt_p, class_m, order_ids,
            total=total_p, all_mask=amask_p,
        )
        return counts
