"""MILP scheduling model: the host-solver accuracy oracle.

Reference: crates/tako/src/internal/scheduler/solver.rs builds one integer
program per tick (variables per (worker, batch, variant), worker resource
constraints, priority blocking) and solves it with an LP backend; this model
re-creates that decision quality on the host via scipy's HiGHS MILP, for use
as a second `--scheduler` backend and as the makespan/accuracy oracle the
greedy TPU kernel is tested against (SURVEY §7.6).

Priority dominance is enforced structurally instead of with big-M weights:
batches are grouped by priority level and each level is solved as its own
maximization over the capacity left by higher levels — exactly the
cut-with-gap-relaxation semantics the reference's blocking variables encode,
with no conditioning problems.

This is a HOST model (numpy + scipy): tens of workers x dozens of batches
solve in milliseconds, which is plenty for the oracle role and for small
clusters; the jitted greedy kernel remains the scale path.
"""

from __future__ import annotations

import logging

import numpy as np

logger = logging.getLogger(__name__)


class MilpModel:
    """Same interface as GreedyCutScanModel.solve; exact per-level packing."""

    def __init__(self, time_limit_secs: float = 10.0):
        # budget for the WHOLE tick (split across priority levels): the
        # solve runs synchronously inside the server's scheduler loop, so it
        # must finish well under the worker-heartbeat reaper limit (~32 s)
        self.time_limit_secs = time_limit_secs

    def solve(
        self,
        free: np.ndarray,       # (W, R) int32
        nt_free: np.ndarray,    # (W,) int32
        lifetime: np.ndarray,   # (W,) int32 seconds
        needs: np.ndarray,      # (B, V, R) int32
        sizes: np.ndarray,      # (B,) int32
        min_time: np.ndarray,   # (B, V) int32 seconds
        priorities: list | None = None,  # per-batch priority (row order =
                                         # descending priority when absent)
    ) -> np.ndarray:
        from scipy.optimize import Bounds, LinearConstraint, milp
        from scipy.sparse import lil_matrix

        free = np.asarray(free, dtype=np.int64).copy()
        nt_free = np.asarray(nt_free, dtype=np.int64).copy()
        lifetime = np.asarray(lifetime)
        needs = np.asarray(needs, dtype=np.int64)
        # copied: decremented per level below, and asarray aliases the
        # caller's buffer when the dtype already matches
        sizes = np.array(sizes, dtype=np.int64, copy=True)
        min_time = np.asarray(min_time)
        n_b, n_v, n_r = needs.shape
        n_w = free.shape[0]
        counts = np.zeros((n_b, n_v, n_w), dtype=np.int32)

        if priorities is None:
            # run_tick hands batches in descending priority order; treat each
            # row as its own level unless told otherwise... rows sharing a
            # level must be solved jointly, so default to one level per
            # distinct row index is WRONG for equal priorities — callers
            # that care (run_tick via priorities kwarg) pass the real levels.
            priorities = list(range(n_b, 0, -1))

        levels: dict = {}
        for bi, p in enumerate(priorities):
            levels.setdefault(p, []).append(bi)

        import time as _time

        deadline = _time.monotonic() + self.time_limit_secs
        level_keys = sorted(levels, reverse=True)
        for li, level in enumerate(level_keys):
            batch_ids = levels[level]
            remaining_budget = max(deadline - _time.monotonic(), 0.1)
            level_budget = remaining_budget / (len(level_keys) - li)
            # candidate variables: (b, v, w) with a usable variant that fits
            # worker lifetime and a positive remaining size
            variables = []
            for b in batch_ids:
                if sizes[b] <= 0:
                    continue
                for v in range(n_v):
                    if not (needs[b, v] > 0).any():
                        continue  # absent variant row
                    for w in range(n_w):
                        if min_time[b, v] > lifetime[w]:
                            continue
                        if (needs[b, v] > free[w]).any():
                            continue
                        if nt_free[w] <= 0:
                            continue
                        variables.append((b, v, w))
            if not variables:
                continue
            n_x = len(variables)
            # objective: maximize assigned tasks (milp minimizes)
            c = -np.ones(n_x)

            rows = []
            lo = []
            hi = []
            a = lil_matrix(
                (n_w * (n_r + 1) + len(batch_ids), n_x), dtype=np.float64
            )
            row = 0
            # per worker per resource capacity
            for w in range(n_w):
                for r in range(n_r):
                    touched = False
                    for xi, (b, v, ww) in enumerate(variables):
                        if ww == w and needs[b, v, r]:
                            a[row, xi] = float(needs[b, v, r])
                            touched = True
                    if touched:
                        lo.append(0.0)
                        hi.append(float(free[w, r]))
                        row += 1
                # task-slot cap
                touched = False
                for xi, (b, v, ww) in enumerate(variables):
                    if ww == w:
                        a[row, xi] = 1.0
                        touched = True
                if touched:
                    lo.append(0.0)
                    hi.append(float(nt_free[w]))
                    row += 1
            # per-batch size cap
            for b in batch_ids:
                touched = False
                for xi, (bb, v, w) in enumerate(variables):
                    if bb == b:
                        a[row, xi] = 1.0
                        touched = True
                if touched:
                    lo.append(0.0)
                    hi.append(float(sizes[b]))
                    row += 1
            a = a[:row].tocsr()

            upper = np.array(
                [min(int(sizes[b]), int(nt_free[w])) for b, v, w in variables],
                dtype=np.float64,
            )
            result = milp(
                c,
                constraints=LinearConstraint(a, np.array(lo), np.array(hi)),
                integrality=np.ones(n_x),
                bounds=Bounds(0, upper),
                options={"time_limit": level_budget},
            )
            # status 1 = time/iteration limit with a feasible incumbent in
            # result.x; discarding it would assign nothing at this level
            # every tick on instances that persistently exceed the budget
            if result.x is None or result.status not in (0, 1):
                logger.warning("milp level %s failed: %s", level,
                               result.message)
                continue
            x = np.round(result.x).astype(np.int64)
            for xi, (b, v, w) in enumerate(variables):
                if x[xi] <= 0:
                    continue
                counts[b, v, w] += int(x[xi])
                free[w] -= needs[b, v] * x[xi]
                nt_free[w] -= x[xi]
                sizes[b] -= x[xi]
        return counts
