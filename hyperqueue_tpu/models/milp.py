"""MILP scheduling model: the host-solver accuracy oracle.

Reference: crates/tako/src/internal/scheduler/solver.rs builds ONE integer
program per tick — variables per (worker, batch, variant) with a
share-density x request-weight objective (solver.rs:520-549), priority
blocking variables with gap relaxation (solver.rs:211-330), min-utilization
all-or-nothing worker constraints (solver.rs:479-518) and multi-node gang
count variables per worker group (solver.rs:177-209) — and solves it with an
LP backend. This model re-creates that decision quality on the host via
scipy's HiGHS MILP, for use as a second `--scheduler` backend and as the
makespan/accuracy oracle the greedy TPU kernel is tested against (SURVEY
§7.6).

Priority dominance is enforced by LEXICOGRAPHIC solves over one joint
variable set instead of the reference's blocking variables: levels are
maximized highest-first, each next solve pinning the previous levels'
achieved scores as lower-bound constraints while every variable stays free.
This yields the same cut-with-gap-relaxation outcome (lower levels only fill
capacity higher levels cannot use) and — unlike solving each level on the
residual capacity — lets a lower-priority task help satisfy a shared
constraint such as a min-utilization floor, exactly like the reference's one
joint program.

Per-level score: task count when every request weight in the level is 1.0
(the packing objective the golden tests pin), else the reference's
share-density x weight value (solver.rs:528-546), so `--weight` biases which
same-priority class wins under this backend too.

This is a HOST model (numpy + scipy): tens of workers x dozens of batches
solve in milliseconds, which is plenty for the oracle role and for small
clusters; the jitted greedy kernel remains the scale path.
"""

from __future__ import annotations

import logging

import numpy as np
from hyperqueue_tpu.utils import clock

logger = logging.getLogger(__name__)


class MilpModel:
    """Same interface as GreedyCutScanModel.solve; joint lexicographic MILP."""

    # run_tick routes min-utilization workers through the joint program
    # instead of the greedy carve-out (reference solver.rs:479-518)
    supports_cpu_floor = True

    def __init__(self, time_limit_secs: float = 10.0):
        # budget for the WHOLE tick (split across priority levels): the
        # solve runs synchronously inside the server's scheduler loop, so it
        # must finish well under the worker-heartbeat reaper limit (~32 s)
        self.time_limit_secs = time_limit_secs

    def solve(
        self,
        free: np.ndarray,       # (W, R) int32
        nt_free: np.ndarray,    # (W,) int32
        lifetime: np.ndarray,   # (W,) int32 seconds
        needs: np.ndarray,      # (B, V, R) int32
        sizes: np.ndarray,      # (B,) int32
        min_time: np.ndarray,   # (B, V) int32 seconds
        priorities: list | None = None,  # per-batch priority (row order =
                                         # descending priority when absent)
        total: np.ndarray | None = None,     # (W, R) pool totals
        all_mask: np.ndarray | None = None,  # (B, V, R) 0/1 ALL-policy
        weights: np.ndarray | None = None,   # (B, V) request weights
        cpu_floor: np.ndarray | None = None,  # (W,) min-utilization floors
    ) -> np.ndarray:
        from scipy.optimize import Bounds, LinearConstraint, milp
        from scipy.sparse import lil_matrix

        free = np.asarray(free, dtype=np.int64)
        nt_free = np.asarray(nt_free, dtype=np.int64)
        lifetime = np.asarray(lifetime)
        needs = np.asarray(needs, dtype=np.int64)
        sizes = np.asarray(sizes, dtype=np.int64)
        min_time = np.asarray(min_time)
        if total is not None:
            total = np.asarray(total, dtype=np.int64)
        n_b, n_v, n_r = needs.shape
        n_w = free.shape[0]
        counts = np.zeros((n_b, n_v, n_w), dtype=np.int32)

        if priorities is None:
            # every batch row its own dominance level is wrong for rows that
            # SHARE a priority (they must pack jointly); with no information
            # the safe default is one joint level (callers that care —
            # run_tick — always pass the real levels)
            priorities = [0] * n_b

        if weights is None:
            weights = np.ones((n_b, n_v), dtype=np.float64)
        else:
            weights = np.asarray(weights, dtype=np.float64)

        # --- candidate variables over ALL levels: (b, v, w) ---
        # per-variable resource needs (ALL-policy entries take the worker's
        # whole pool and require it untouched, solver.rs:120-124)
        variables: list[tuple[int, int, int]] = []
        var_needs: list[np.ndarray] = []
        var_upper: list[int] = []
        for b in range(n_b):
            if sizes[b] <= 0:
                continue
            for v in range(n_v):
                is_all = (
                    all_mask[b, v] > 0
                    if all_mask is not None
                    else np.zeros(n_r, dtype=bool)
                )
                if not (needs[b, v] > 0).any() and not is_all.any():
                    continue  # absent variant row
                for w in range(n_w):
                    if min_time[b, v] > lifetime[w]:
                        continue
                    if nt_free[w] <= 0:
                        continue
                    nv = needs[b, v].copy()
                    if is_all.any():
                        if total is None:
                            continue
                        if (
                            (free[w][is_all] != total[w][is_all])
                            | (total[w][is_all] <= 0)
                        ).any():
                            continue  # pool not fully idle
                        nv[is_all] = total[w][is_all]
                    if (nv > free[w]).any():
                        continue
                    variables.append((b, v, w))
                    var_needs.append(nv)
                    cap = min(int(sizes[b]), int(nt_free[w]))
                    if is_all.any():
                        cap = min(cap, 1)
                    var_upper.append(cap)
        if not variables:
            return counts
        n_x = len(variables)

        # min-utilization bool variables, one per floored worker
        floors = {}
        if cpu_floor is not None:
            cpu_floor = np.asarray(cpu_floor, dtype=np.int64)
            for w in range(n_w):
                if cpu_floor[w] > 0:
                    floors[w] = n_x + len(floors)
        n_y = len(floors)
        n_all = n_x + n_y

        # --- shared constraint matrix ---
        rows = lil_matrix((n_w * (n_r + 1) + n_b + 2 * n_y, n_all))
        lo: list[float] = []
        hi: list[float] = []
        row = 0
        by_worker: dict[int, list[int]] = {}
        by_batch: dict[int, list[int]] = {}
        for xi, (b, v, w) in enumerate(variables):
            by_worker.setdefault(w, []).append(xi)
            by_batch.setdefault(b, []).append(xi)
        for w, xis in by_worker.items():
            for r in range(n_r):
                touched = False
                for xi in xis:
                    if var_needs[xi][r]:
                        rows[row, xi] = float(var_needs[xi][r])
                        touched = True
                if touched:
                    lo.append(0.0)
                    hi.append(float(free[w, r]))
                    row += 1
            for xi in xis:
                rows[row, xi] = 1.0
            lo.append(0.0)
            hi.append(float(nt_free[w]))
            row += 1
        for b, xis in by_batch.items():
            for xi in xis:
                rows[row, xi] = 1.0
            lo.append(0.0)
            hi.append(float(sizes[b]))
            row += 1
        # min-utilization: cpu use on w is 0, or at least the floor
        # (reference add_min_utilization, solver.rs:479-518): with bool y_w,
        #   sum(cpu) - floor*y >= 0  and  sum(cpu) - free_cpu*y <= 0
        for w, yi in floors.items():
            for xi in by_worker.get(w, []):
                if var_needs[xi][0]:
                    rows[row, xi] = float(var_needs[xi][0])
                    rows[row + 1, xi] = float(var_needs[xi][0])
            rows[row, yi] = -float(cpu_floor[w])
            lo.append(0.0)
            hi.append(np.inf)
            row += 1
            rows[row, yi] = -float(free[w, 0])
            lo.append(-np.inf)
            hi.append(0.0)
            row += 1
        rows = rows[:row].tocsr()
        base_constraints = [LinearConstraint(rows, np.array(lo), np.array(hi))]

        # --- per-level lexicographic objective rows ---
        # share-density x weight value (solver.rs:528-546) with a tiny
        # lower-worker-index bonus as the tie-break the reference folds into
        # the objective
        res_sums = np.maximum(free, 0).sum(axis=0).astype(np.float64)
        value = np.zeros(n_all)
        for xi, (b, v, w) in enumerate(variables):
            share = sum(
                var_needs[xi][r] / res_sums[r]
                for r in range(n_r)
                if var_needs[xi][r] > 0 and res_sums[r] > 0
            )
            value[xi] = share * weights[b, v] * (
                1.0 + 1e-6 * (n_w - w) / max(n_w, 1)
            )

        levels: dict = {}
        for bi, p in enumerate(priorities):
            levels.setdefault(p, []).append(bi)
        level_keys = sorted(levels, reverse=True)

        level_rows = []
        for level in level_keys:
            batch_set = set(levels[level])
            weighted = any(
                abs(weights[b, v] - 1.0) > 1e-9
                for b in batch_set
                for v in range(n_v)
            )
            srow = np.zeros(n_all)
            for xi, (b, v, w) in enumerate(variables):
                if b in batch_set:
                    # count objective with a value tie-break, or pure value
                    # when the level carries non-default weights
                    srow[xi] = (
                        value[xi] if weighted else 1.0 + 1e-6 * value[xi]
                    )
            level_rows.append(srow)

        deadline = clock.monotonic() + self.time_limit_secs
        integrality = np.ones(n_all)
        upper = np.array(
            var_upper + [1] * n_y, dtype=np.float64
        )
        pins: list = []
        x_final = None
        for li, srow in enumerate(level_rows):
            if not srow.any():
                continue
            budget = max(deadline - clock.monotonic(), 0.1) / (
                len(level_rows) - li
            )
            result = milp(
                -srow,
                constraints=base_constraints + pins,
                integrality=integrality,
                bounds=Bounds(0, upper),
                options={"time_limit": budget},
            )
            # status 1 = time limit with a feasible incumbent in result.x;
            # discarding it would assign nothing on over-budget instances
            if result.x is None or result.status not in (0, 1):
                logger.warning(
                    "milp level %s failed: %s", level_keys[li], result.message
                )
                continue
            x_final = result.x
            achieved = float(srow @ result.x)
            # pin this level's score (small slack absorbs solver tolerance)
            pins.append(
                LinearConstraint(srow[None, :], achieved - 1e-6, np.inf)
            )

        if x_final is None:
            return counts
        x = np.round(np.asarray(x_final)[:n_x]).astype(np.int64)
        for xi, (b, v, w) in enumerate(variables):
            if x[xi] > 0:
                counts[b, v, w] = int(x[xi])
        return counts
