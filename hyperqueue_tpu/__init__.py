"""hyperqueue_tpu — a TPU-native distributed task-graph execution framework.

Capability target: It4innovations/hyperqueue (see SURVEY.md). A single server
process holds a task graph; workers connect over TCP; a centralized scheduler
assigns tasks to workers subject to rich resource requests (CPUs, GPUs,
fractional amounts, non-fungible indexed resources, NUMA groups, multi-node
gangs). Tasks are OS processes or Python functions. There is no data plane
between tasks; the framework moves control messages and stdout/stderr streams.

The TPU-native part: the per-tick scheduling assignment (which the reference
solves with a CPU MILP, reference crates/tako/src/internal/scheduler/solver.rs)
is reframed as a dense batch×worker constraint solve executed by a JAX solver
(`hyperqueue_tpu.ops.assign`), jit-compiled with fixed (bucketed) shapes so one
compiled program serves every tick.

Layout:
  ids, resources/   — data model (IDs, fixed-point amounts, requests, descriptors)
  scheduler/        — batches -> dense snapshot -> solve -> mapping
  ops/              — JAX kernels (the dense assignment solver)
  models/           — scheduler policy models (greedy cut-scan, auction refinement)
  parallel/         — jax.sharding Mesh utilities for the multi-chip solver
  server/           — core state, reactor, RPC, jobs, client handling
  worker/           — worker runtime, resource pools/allocator, task launcher
  transport/        — framing, auth, encryption
  events/           — event streamer, journal, restore
  client/           — CLI and output formatting
  api/              — Python user API (Client, Job, LocalCluster)
  utils/            — small shared helpers
"""

__version__ = "0.3.0"

JOURNAL_VERSION = 1
PROTOCOL_VERSION = 2  # v2: shared compute-message bodies + never-restart=-1
