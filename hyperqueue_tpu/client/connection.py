"""Synchronous client connection for CLI commands.

Each CLI invocation opens one authenticated connection on the client plane
(reference client/mod.rs does the same via its async runtime).

Connection failures get bounded retry with jittered exponential backoff so
CLI commands ride out a server restart window instead of failing on the
first refused connect. The access record is re-read from the server dir on
every attempt — a restarted server publishes a NEW instance dir with fresh
ports and keys, so a cached record would retry against a dead address
forever. The window is HQ_CLIENT_RETRY_SECS (default 15; 0 disables).

Caveat (documented, deliberate): a request whose connection dies after the
send is retried against the new connection, so a non-idempotent request
(submit) can be applied twice if the dying server already processed it —
the at-least-once window every ack-less RPC has.
"""

from __future__ import annotations

import asyncio
import os
import random
import time
from pathlib import Path

from hyperqueue_tpu.transport.auth import (
    ROLE_CLIENT,
    ROLE_SERVER,
    do_authentication,
)
from hyperqueue_tpu.utils import serverdir
from hyperqueue_tpu.utils.retry import jittered_backoff
from hyperqueue_tpu.utils import clock

def _env_retry_secs() -> float:
    raw = os.environ.get("HQ_CLIENT_RETRY_SECS", "15")
    try:
        return float(raw)
    except ValueError:
        import logging

        logging.getLogger("hq.client").warning(
            "ignoring malformed HQ_CLIENT_RETRY_SECS=%r; using 15", raw
        )
        return 15.0


_BACKOFF_BASE = 0.2
_BACKOFF_CAP = 2.0
# per-attempt bound on connect+auth: a wedged-but-listening server (a
# SIGSTOPped federation shard awaiting its fence, a paused VM) accepts
# the TCP handshake and then never answers the auth exchange — without
# this the client hangs forever instead of retrying against the fresh
# access record a failover successor publishes
_HANDSHAKE_TIMEOUT = 10.0

# transient transport failures worth retrying; AuthError and malformed
# access records are NOT here — retrying a bad key never helps.
# asyncio.TimeoutError covers the per-attempt handshake bound above
# (it subclasses OSError on 3.11+, listed explicitly for older runtimes)
_RETRIABLE = (ConnectionError, OSError, asyncio.IncompleteReadError,
              asyncio.TimeoutError)


class ClientError(Exception):
    """Server-reported error. `code`/`owner` carry the machine-readable
    half of coded errors (ISSUE 17): code="wrong-shard" + owner=<shard>
    is the redirect hint a federated client retries on; code="migrating"
    means the job is sealed mid-move — retry shortly."""

    code: str | None = None
    owner: int | None = None


class ClientSession:
    """Sync facade: runs its own event loop for request/response exchanges.

    `retry_window`: seconds to keep retrying transient connection failures
    (None = HQ_CLIENT_RETRY_SECS; 0 = fail on the first error, used by
    callers with their own polling loop like `hq server wait`).
    """

    def __init__(self, server_dir: Path, retry_window: float | None = None):
        self.server_dir = Path(server_dir)
        # env read per session, not at import: long-lived embedders (API
        # client, tests) may set HQ_CLIENT_RETRY_SECS after the module is
        # first imported
        self.retry_window = (
            _env_retry_secs() if retry_window is None else retry_window
        )
        self._rng = random.Random()
        self.access = None
        self._loop = asyncio.new_event_loop()
        try:
            self._conn = self._loop.run_until_complete(
                self._connect_with_retry()
            )
        except BaseException:
            self._loop.close()
            raise

    async def _connect(self):
        # re-load per attempt: a restarted server means a new instance dir
        # (new ports AND new plane keys)
        self.access = serverdir.load_access(self.server_dir)
        if not self.access.client_port:
            raise RuntimeError(
                "access record has no client plane (worker-only split file?)"
            )
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(
                self.access.host, self.access.client_port
            ),
            timeout=_HANDSHAKE_TIMEOUT,
        )
        try:
            return await asyncio.wait_for(
                do_authentication(
                    reader,
                    writer,
                    ROLE_CLIENT,
                    ROLE_SERVER,
                    self.access.client_key_bytes(),
                ),
                timeout=_HANDSHAKE_TIMEOUT,
            )
        except BaseException:
            # a failed handshake must not leak its socket — the retry loop
            # can make a dozen attempts per CLI call during a restart
            # (BaseException also covers the wait_for cancellation)
            writer.close()
            raise

    def _retries_exhausted(self, deadline: float) -> bool:
        return self.retry_window <= 0 or clock.monotonic() >= deadline

    async def _connect_with_retry(self, deadline: float | None = None):
        # `deadline` lets request() span ONE retry window across its
        # send/reconnect cycles instead of granting each reconnect a fresh
        # window (which would stack to a multiple of HQ_CLIENT_RETRY_SECS)
        if deadline is None:
            deadline = clock.monotonic() + self.retry_window
        delay = _BACKOFF_BASE
        while True:
            try:
                return await self._connect()
            except FileNotFoundError:
                # no access record: distinguish "no server was ever started
                # / it stopped cleanly" (no hq-current symlink — fail fast
                # with the clear message) from "a new instance dir is being
                # published right now" (symlink flipped, access file lands
                # a moment later — a genuine restart window, retry)
                if not (
                    self.server_dir / serverdir.CURRENT_LINK
                ).is_symlink():
                    raise
                if self._retries_exhausted(deadline):
                    raise
            except _RETRIABLE:
                if self._retries_exhausted(deadline):
                    raise
            sleep_for, delay = jittered_backoff(
                delay, _BACKOFF_CAP, self._rng,
                remaining=deadline - clock.monotonic(),
            )
            await asyncio.sleep(sleep_for)

    def request(self, msg: dict, timeout: float | None = None) -> dict:
        async def go():
            await self._conn.send(msg)
            return await self._conn.recv()

        deadline = clock.monotonic() + self.retry_window
        while True:
            coro = asyncio.wait_for(go(), timeout) if timeout else go()
            try:
                response = self._loop.run_until_complete(coro)
                break
            except asyncio.TimeoutError:
                # the caller's per-request deadline — never retried (on
                # 3.11+ TimeoutError subclasses OSError, so this must be
                # caught BEFORE the retriable set)
                raise
            except _RETRIABLE:
                if self._retries_exhausted(deadline):
                    raise
                # server restart window: reconnect (fresh access record)
                # and re-send — the reconnect shares THIS request's
                # deadline, so the whole exchange stays bounded by one
                # retry window
                self._conn.close()
                self._conn = self._loop.run_until_complete(
                    self._connect_with_retry(deadline=deadline)
                )
        if isinstance(response, dict) and response.get("op") == "error":
            err = ClientError(response.get("message", "server error"))
            err.code = response.get("code")
            err.owner = response.get("owner")
            raise err
        return response

    def close(self) -> None:
        self._conn.close()
        self._loop.run_until_complete(self._conn.wait_closed())
        self._loop.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FederatedSession:
    """ClientSession-shaped facade over a federated server dir (ISSUE 11).

    Routes each request to the shard that owns it — through a cached
    :class:`~hyperqueue_tpu.client.routing.Resolver` over the federation
    root's ownership log (modulo partition until a job migrates), so any
    request naming a job routes directly; a shard that answers
    ``wrong-shard`` (the job migrated since the cache was read) triggers
    one refresh-and-retry toward the owner it names, and ``migrating``
    (the job is mid-move) a short wait-and-retry. Cluster-wide reads
    (job_list, worker_list) fan out to every live shard and merge;
    submits/open_job pick a shard round-robin from a random start (pin
    with HQ_SHARD). Per-shard ClientSessions open lazily and are reused,
    each with the full reconnect/retry machinery — so a request that
    lands during a shard failover rides it out exactly like against a
    restarting standalone server.
    """

    # ops fanned out to every shard, responses merged; a shard with no
    # running server is skipped (a cleanly-stopped shard's jobs are
    # still listed by its siblings)
    _FAN_OUT = frozenset({"job_list", "worker_list", "stop_server"})

    def __init__(self, server_dir: Path, retry_window: float | None = None,
                 shard_count: int | None = None):
        from hyperqueue_tpu.client.routing import Resolver

        self.server_dir = Path(server_dir)
        self.retry_window = retry_window
        if shard_count is None:
            fed = serverdir.load_federation(self.server_dir)
            if fed is None:
                raise ValueError(f"no federation at {server_dir}")
            shard_count = fed["shard_count"]
        # ALL job routing goes through the resolver (ISSUE 17): ownership
        # map when one exists, the boot-time modulo otherwise. Its shard
        # count folds in shards added online, which the descriptor count
        # the caller read may predate.
        self.resolver = Resolver(self.server_dir, shard_count)
        self.shard_count = self.resolver.shard_count
        self._sessions: dict[int, ClientSession] = {}
        env_shard = os.environ.get("HQ_SHARD")
        self._pin_submits = env_shard not in (None, "")
        if self._pin_submits:
            try:
                self._submit_shard = int(env_shard) % self.shard_count
            except ValueError:
                import logging

                logging.getLogger("hq.client").warning(
                    "ignoring malformed HQ_SHARD=%r; picking randomly",
                    env_shard,
                )
                self._pin_submits = False
        if not self._pin_submits:
            self._submit_shard = random.randrange(self.shard_count)

    # --- shard sessions -------------------------------------------------
    def shard_session(self, shard_id: int) -> ClientSession:
        session = self._sessions.get(shard_id)
        if session is None:
            try:
                session = ClientSession(
                    serverdir.shard_path(self.server_dir, shard_id),
                    retry_window=self.retry_window,
                )
            except FileNotFoundError as e:
                # sessions open lazily INSIDE request(), past the CLI's
                # construction-time FileNotFoundError handling — surface
                # a clean client error, not a raw traceback
                raise ClientError(str(e)) from e
            self._sessions[shard_id] = session
        return session

    def _drop_session(self, shard_id: int) -> None:
        """Forget a shard's cached session, closing its socket + private
        event loop (popping without close would leak both)."""
        session = self._sessions.pop(shard_id, None)
        if session is not None:
            try:
                session.close()
            except Exception:  # noqa: BLE001 - already broken
                pass

    def session_for_job(self, job_id: int) -> ClientSession:
        return self.shard_session(self.resolver.shard_for_job(job_id))

    def _request_routed(self, job_id: int, msg: dict, timeout) -> dict:
        """Job-routed request with ONE wrong-shard redirect: a stale
        route (the job migrated after the resolver's read — or HQ_SHARD
        pinned the old owner) answers code="wrong-shard" with the owner;
        refresh the map and retry there. code="migrating" waits out the
        move's seal window, then routes by the refreshed map."""
        deadline = clock.monotonic() + 10.0
        redirected = False
        while True:
            try:
                return self.session_for_job(job_id).request(msg, timeout)
            except ClientError as e:
                if e.code == "migrating" and clock.monotonic() < deadline:
                    time.sleep(0.2)
                    self.resolver.refresh()
                    continue
                if e.code != "wrong-shard" or redirected:
                    raise
                redirected = True
                self.resolver.refresh()
                if e.owner is not None:
                    return self.shard_session(int(e.owner)).request(
                        msg, timeout
                    )

    def submit_session(self) -> ClientSession:
        """The shard for a NEW job: round-robin from a random start so
        independent clients spread; HQ_SHARD pins it."""
        shard = self._submit_shard
        if not self._pin_submits:
            self._submit_shard = (shard + 1) % self.shard_count
        return self.shard_session(shard)

    # worker-targeted ops: worker ids are allocated PER SHARD and collide
    # across shards, so these must name their shard explicitly — routing
    # a bare id anywhere would silently hit the wrong shard's worker
    _WORKER_OPS = frozenset({"worker_stop", "worker_info"})

    # --- routing --------------------------------------------------------
    def request(self, msg: dict, timeout: float | None = None) -> dict:
        op = msg.get("op")
        if op in self._WORKER_OPS or (
            op == "worker_list" and msg.get("shard") is not None
        ):
            shard = msg.pop("shard", None)
            if shard is None:
                raise ClientError(
                    "federation: worker ids are per shard; pass --shard K"
                )
            return self.shard_session(int(shard)).request(msg, timeout)
        if op in self._FAN_OUT:
            return self._fan_out(msg, timeout)
        if "job_ids" in msg:
            return self._by_job_ids(msg, timeout)
        if "job_id" in msg and msg["job_id"] is not None:
            return self._request_routed(msg["job_id"], msg, timeout)
        if op in ("submit", "open_job"):
            job_id = (msg.get("job") or {}).get("job_id")
            if job_id:
                return self._request_routed(job_id, msg, timeout)
            return self.submit_session().request(msg, timeout)
        shard = msg.pop("shard", None)
        if shard in ("all", -1, "-1") and op in (
            "server_info", "server_stats", "reset_metrics", "alerts",
            "accounting", "profile",
        ):
            # per-shard fan-out: one record per shard (tick latencies and
            # lease states are per-shard facts — never summed; a
            # reset_metrics window must cover every shard's registry)
            records = [
                resp if resp is not None
                else {"op": op, "shard_id": k, "error": str(err)}
                for k, resp, err in self._per_shard(msg, timeout)
            ]
            return {"op": op, "shards": records}
        try:
            shard_id = int(shard) if shard is not None else 0
        except (TypeError, ValueError):
            # a typo'd --shard must not silently answer with shard 0's
            # state (e.g. its lease/promoted flags) labeled as another's
            raise ClientError(
                f"invalid shard selector {shard!r}; pass "
                f"0..{self.shard_count - 1} or 'all'"
            ) from None
        if not (0 <= shard_id < self.shard_count):
            raise ClientError(
                f"shard {shard_id} outside 0..{self.shard_count - 1}"
            )
        return self.shard_session(shard_id).request(msg, timeout)

    def _per_shard(self, msg: dict, timeout):
        """Request every shard in turn, yielding (shard, response, error)
        with error set (and the dead session dropped) instead of raising
        — a down shard must not fail a cluster-wide read."""
        for shard in range(self.shard_count):
            try:
                yield shard, self.shard_session(shard).request(
                    dict(msg), timeout
                ), None
            except (FileNotFoundError, ConnectionError, OSError,
                    ClientError) as e:
                self._drop_session(shard)
                yield shard, None, e

    def _by_job_ids(self, msg: dict, timeout) -> dict:
        if not msg["job_ids"]:
            # empty selector: any shard answers the empty request
            return self.shard_session(0).request(msg, timeout)
        migrating_deadline = clock.monotonic() + 10.0
        for attempt in range(32):
            groups: dict[int, list[int]] = {}
            for job_id in msg["job_ids"]:
                groups.setdefault(
                    self.resolver.shard_for_job(job_id), []
                ).append(job_id)
            responses = []
            try:
                for shard, ids in sorted(groups.items()):
                    sub = dict(msg)
                    sub["job_ids"] = ids
                    responses.append(
                        self.shard_session(shard).request(sub, timeout)
                    )
            except ClientError as e:
                # a group routed to a shard that lost (or is losing)
                # those jobs: refresh the map and re-group. wrong-shard
                # answers each imply a REAL committed migration (a
                # long-blocked `job wait` sees one per rebalancer move,
                # so a single retry is not enough); migrating is a
                # transient seal window and gets a bounded wait instead
                if e.code == "wrong-shard" and attempt < 31:
                    self.resolver.refresh()
                    continue
                if e.code == "migrating" and (
                    clock.monotonic() < migrating_deadline
                ):
                    time.sleep(0.2)
                    self.resolver.refresh()
                    continue
                raise
            return _merge_responses(responses)

    def _fan_out(self, msg: dict, timeout) -> dict:
        responses = []
        errors: list[Exception] = []
        for _shard, resp, err in self._per_shard(msg, timeout):
            # a shard with no running server is skipped (its siblings
            # still answer); errors kept in case ALL are down
            if resp is not None:
                responses.append(resp)
            else:
                errors.append(err)
        if not responses:
            raise errors[0] if errors else ClientError("no live shards")
        return _merge_responses(responses)

    def close(self) -> None:
        for session in self._sessions.values():
            try:
                session.close()
            except Exception:  # noqa: BLE001 - close the rest regardless
                pass
        self._sessions.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _merge_responses(responses: list[dict]) -> dict:
    """Merge per-shard responses of one fan-out op: lists concatenate,
    numbers sum, everything else keeps the first shard's value (`op` and
    friends are identical across shards anyway)."""
    if len(responses) == 1:
        return responses[0]
    merged: dict = dict(responses[0])
    for resp in responses[1:]:
        for key, value in resp.items():
            if key not in merged:
                merged[key] = value
            elif isinstance(value, list) and isinstance(merged[key], list):
                merged[key] = merged[key] + value
            elif isinstance(value, (int, float)) and not isinstance(
                value, bool
            ) and isinstance(merged[key], (int, float)):
                merged[key] = merged[key] + value
    return merged


def open_session(server_dir: Path, retry_window: float | None = None):
    """ClientSession for a classic server dir, FederatedSession when
    `server_dir` is a federation root — the CLI's one entry point."""
    fed = serverdir.load_federation(Path(server_dir))
    if fed is None:
        return ClientSession(server_dir, retry_window=retry_window)
    return FederatedSession(
        server_dir, retry_window=retry_window,
        shard_count=fed["shard_count"],
    )


class SubmitStream:
    """Pipelined chunked submit over one ClientSession (ISSUE 10).

    Chunks are tagged with (stream uid, chunk index) and sent without
    waiting for each response; the client keeps a bounded in-flight
    window (HQ_SUBMIT_WINDOW, default 8) and reads per-chunk acks as the
    window fills — so a giant array streams to the server at pipeline
    speed with bounded memory on BOTH ends.

    Exactly-once across failures: on a transport error (server restart
    window) the stream reconnects through the session's retry machinery
    and re-sends every unacked chunk. The server deduplicates on
    (uid, index) — journaled with each chunk — so replayed chunks yield
    idempotent duplicate acks, never duplicate tasks. After the first ack
    the job id is pinned into the header, so chunks replayed against a
    restored server land on the SAME job.

    `n_tasks` is the stream's acknowledged task coverage (counted from
    the chunks themselves, so a chunk whose first ack was lost and whose
    replay acked `dup` still counts once); `dup_chunks` counts acks the
    server deduplicated.
    """

    def __init__(self, session: ClientSession, header: dict,
                 window: int | None = None, uid: str | None = None):
        from hyperqueue_tpu.utils.trace import new_trace_id

        self._fed: FederatedSession | None = None
        if isinstance(session, FederatedSession):
            # a stream lives on ONE shard at a time: the owning shard for
            # a pinned job id, a submit shard otherwise. The federated
            # session is KEPT (ISSUE 17): if the job migrates mid-stream
            # the shard answers a coded error and the stream re-resolves,
            # switches shards, and replays its unacked chunks — the
            # destination imported the stream's applied-index set, so the
            # replay dedups exactly-once.
            self._fed = session
            job_id = header.get("job_id")
            session = (
                session.session_for_job(job_id)
                if job_id else session.submit_session()
            )
        self.session = session
        self.header = dict(header)
        if window is None:
            try:
                window = int(os.environ.get("HQ_SUBMIT_WINDOW", "8"))
            except ValueError:
                window = 8
        self.window = max(window, 1)
        self.uid = uid or new_trace_id()
        self.job_id: int | None = None
        self.n_tasks = 0
        self.dup_chunks = 0
        self._next_index = 0
        self._unacked: dict[int, dict] = {}
        self._sealed = False
        self._redirects = 0

    # --- wire helpers (session-loop, with reconnect + replay) -----------
    def _replay_unacked(self) -> None:
        for i in sorted(self._unacked):
            frame = self._unacked[i]
            if self.job_id is not None:
                frame["job"]["job_id"] = self.job_id
            self.session._loop.run_until_complete(
                self.session._conn.send(frame)
            )

    def _recover(self, deadline: float) -> None:
        """Reconnect and replay every unacked chunk, retrying the whole
        sequence (a second connection flap mid-replay must keep retrying
        within the SAME window, not abort the stream)."""
        while True:
            if self.session._retries_exhausted(deadline):
                raise ConnectionError(
                    "submit stream: retry window exhausted"
                )
            self.session._conn.close()
            self.session._conn = self.session._loop.run_until_complete(
                self.session._connect_with_retry(deadline=deadline)
            )
            try:
                self._replay_unacked()
                return
            except _RETRIABLE:
                continue

    def _with_retry(self, op) -> dict | None:
        """Run one recv step; on a transport error reconnect + replay the
        unacked chunks, then retry the step. (Sends do NOT use this — a
        replay already re-sends the failed frame, so retrying the send
        itself would put a duplicate on the wire whose extra ack desyncs
        the session's request/response protocol.)"""
        deadline = clock.monotonic() + self.session.retry_window
        while True:
            try:
                return self.session._loop.run_until_complete(op())
            except _RETRIABLE:
                self._recover(deadline)

    def _recv_ack(self) -> None:
        async def step():
            return await self.session._conn.recv()

        ack = self._with_retry(step)
        if not isinstance(ack, dict) or ack.get("op") == "error":
            code = ack.get("code") if isinstance(ack, dict) else None
            if code in ("wrong-shard", "migrating") and (
                self._fed is not None
            ):
                self._follow_migration(ack.get("owner"), code)
                return self._recv_ack()
            msg = (ack or {}).get("message", "server error")
            err = ClientError(msg)
            if isinstance(ack, dict):
                err.code = ack.get("code")
                err.owner = ack.get("owner")
            raise err
        index = ack["i"]
        frame = self._unacked.pop(index, None)
        if self.job_id is None:
            self.job_id = ack["job_id"]
            self.header["job_id"] = self.job_id
        if ack.get("dup"):
            self.dup_chunks += 1
        # count tasks from the FRAME on its first ack, not from the
        # server's n_tasks field: a chunk applied before a connection
        # drop acks `dup` (n_tasks=0) on the replay, and the stream's
        # total must still cover it
        if frame is not None:
            self.n_tasks += _frame_task_count(frame)

    def _follow_migration(self, owner, code: str) -> None:
        """The stream's job moved (or is moving) mid-stream: switch to
        the owning shard's session and replay every unacked chunk there.
        Bounded — a stream bouncing between shards means routing itself
        is broken, and looping would mask that."""
        self._redirects += 1
        if self._redirects > 8:
            raise ClientError(
                "submit stream redirected too many times; "
                "federation routing is inconsistent"
            )
        fed = self._fed
        # the abandoned session's socket may hold unread error responses
        # for chunks still in flight when the first error arrived; drop
        # it from the cache so no later request reads a stale reply
        for shard_id, cached in list(fed._sessions.items()):
            if cached is self.session:
                fed._drop_session(shard_id)
        fed.resolver.refresh()
        if code == "migrating" or owner is None:
            # mid-move seal window: wait for the commit to land, then
            # route by the refreshed ownership map
            time.sleep(0.25)
            fed.resolver.refresh()
            owner = fed.resolver.shard_for_job(
                self.job_id
                if self.job_id is not None
                else self.header.get("job_id")
            )
        self.session = fed.shard_session(int(owner))
        self._replay_unacked()

    def _send_frame(self, frame: dict) -> None:
        while len(self._unacked) >= self.window:
            self._recv_ack()
        self._unacked[frame["i"]] = frame
        try:
            self.session._loop.run_until_complete(
                self.session._conn.send(frame)
            )
        except _RETRIABLE:
            # the frame is already in _unacked: recovery's replay sends
            # it exactly once on the new connection — do NOT also retry
            # the send (the extra duplicate would earn an extra ack that
            # finish() never drains, desyncing the session)
            self._recover(clock.monotonic() + self.session.retry_window)

    # --- public API -------------------------------------------------------
    def send_chunk(self, array: dict | None = None,
                   tasks: list | None = None, last: bool = False) -> None:
        """Queue one chunk: an array description ({"id_range": [lo, hi)}
        or {"ids": [...]} plus shared body/request/...) or a graph task
        list. Blocks only while the in-flight window is full."""
        if self._sealed:
            raise ClientError("submit stream already finished")
        from hyperqueue_tpu.transport.framing import attach_trace
        from hyperqueue_tpu.utils.trace import new_trace_id

        frame: dict = {
            "op": "submit_chunk",
            "uid": self.uid,
            "i": self._next_index,
            "rid": self._next_index,
            "job": dict(self.header),
        }
        if array is not None:
            frame["array"] = array
        if tasks is not None:
            frame["tasks"] = tasks
        if last:
            frame["last"] = True
            self._sealed = True
        attach_trace(frame, new_trace_id(), sent_at=clock.now())
        self._next_index += 1
        self._send_frame(frame)

    def finish(self) -> tuple[int, int]:
        """Seal the stream (empty final chunk if none was marked last),
        drain every outstanding ack, and return (job_id, n_tasks)."""
        if not self._sealed:
            self.send_chunk(last=True)
        while self._unacked:
            self._recv_ack()
        return self.job_id, self.n_tasks


def _frame_task_count(frame: dict) -> int:
    """Tasks carried by one submit_chunk frame (client-side count for the
    stream total — independent of whether the server ack was a dup)."""
    array = frame.get("array")
    if array:
        id_range = array.get("id_range")
        if id_range is not None:
            return int(id_range[1]) - int(id_range[0])
        return len(array.get("ids") or ())
    return len(frame.get("tasks") or ())


def _resolve_stream_dir(server_dir: Path, shard: int = 0) -> Path:
    """Streaming surfaces (journal stream, dashboard, subscribe) attach
    to ONE server: against a federation root, resolve to a shard's
    nested dir (default shard 0 — pass `shard`, or the shard dir itself,
    for another; cross-shard event-stream merging is not a thing, each
    shard's journal is its own lineage)."""
    server_dir = Path(server_dir)
    fed = serverdir.load_federation(server_dir)
    if fed is not None:
        return serverdir.shard_path(server_dir, shard)
    return server_dir


def _streaming_request(server_dir: Path, request: dict, on_subscribed=None,
                       shard: int = 0, on_connected=None):
    """One authenticated client connection turned into a frame generator:
    send `request`, yield every received frame until the server closes or
    the consumer breaks out. Blocking-recv based (read_frame is not
    cancellation-safe, so no wait_for timeouts may wrap it).
    on_subscribed, when given, is called once the request is on the wire —
    before the first frame is read. on_connected, when given, receives a
    zero-arg CANCELLER safe to call from another thread: it schedules a
    connection close on this generator's loop, waking the blocked recv
    (how FleetFeed.stop() unwedges its feed threads)."""
    server_dir = _resolve_stream_dir(server_dir, shard)

    async def _connect():
        access = serverdir.load_access(Path(server_dir))
        reader, writer = await asyncio.open_connection(
            access.host, access.client_port
        )
        conn = await do_authentication(
            reader, writer, ROLE_CLIENT, ROLE_SERVER, access.client_key_bytes()
        )
        await conn.send(request)
        return conn

    loop = asyncio.new_event_loop()
    conn = None
    try:
        # bound the connect+auth+send preamble like ClientSession does (a
        # wedged server must not hang the stream consumer forever); the
        # recv loop below legitimately blocks between frames
        conn = loop.run_until_complete(
            asyncio.wait_for(_connect(), _HANDSHAKE_TIMEOUT)
        )
        if on_connected is not None:
            on_connected(lambda: loop.call_soon_threadsafe(conn.close))
        if on_subscribed is not None:
            on_subscribed()
        while True:
            yield loop.run_until_complete(conn.recv())
    finally:
        # the consumer may break out of the generator at any point
        # (dashboard quit, Ctrl-C in `hq journal stream`): close the
        # authenticated connection before the loop, or the socket leaks
        if conn is not None:
            try:
                conn.close()
                loop.run_until_complete(conn.wait_closed())
            except Exception:
                pass
        loop.close()


def subscribe(server_dir: Path, filters=(), sample_interval: float = 0.0,
              buffer: int = 4096, overviews: bool = False,
              on_subscribed=None, shard: int = 0, on_connected=None):
    """Generator of frames from the server's `subscribe` RPC: coalesced
    lifecycle-event frames ({"op": "events", "records": [...]}) plus
    periodic metric samples ({"op": "sample", ...}) when sample_interval
    is set. This is the push feed `hq top` and the autoscaler consume —
    no polling; a consumer that falls behind the server's bounded
    per-subscriber queue receives a final {"op": "sub_dropped"} frame."""
    request = {
        "op": "subscribe",
        "filter": list(filters),
        "sample_interval": sample_interval,
        "buffer": buffer,
        "overviews": overviews,
    }
    for msg in _streaming_request(server_dir, request, on_subscribed,
                                  shard=shard, on_connected=on_connected):
        yield msg
        if msg.get("op") == "sub_dropped":
            return


def stream_events(server_dir: Path, history: bool = False, filters=(),
                  on_subscribed=None, overviews: bool = False,
                  shard: int = 0):
    """Generator of event records from the server's client-plane stream;
    shared by `hq journal stream` and the dashboard."""
    request = {
        "op": "stream_events", "history": history,
        "filter": list(filters),
        # ask the server to force worker hw overviews on while this
        # stream is attached (dashboards; SetOverviewIntervalOverride)
        "overviews": overviews,
    }
    yield from _streaming_request(server_dir, request, on_subscribed,
                                  shard=shard)
