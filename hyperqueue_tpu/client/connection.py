"""Synchronous client connection for CLI commands.

Each CLI invocation opens one authenticated connection on the client plane
(reference client/mod.rs does the same via its async runtime).

Connection failures get bounded retry with jittered exponential backoff so
CLI commands ride out a server restart window instead of failing on the
first refused connect. The access record is re-read from the server dir on
every attempt — a restarted server publishes a NEW instance dir with fresh
ports and keys, so a cached record would retry against a dead address
forever. The window is HQ_CLIENT_RETRY_SECS (default 15; 0 disables).

Caveat (documented, deliberate): a request whose connection dies after the
send is retried against the new connection, so a non-idempotent request
(submit) can be applied twice if the dying server already processed it —
the at-least-once window every ack-less RPC has.
"""

from __future__ import annotations

import asyncio
import os
import random
import time
from pathlib import Path

from hyperqueue_tpu.transport.auth import (
    ROLE_CLIENT,
    ROLE_SERVER,
    do_authentication,
)
from hyperqueue_tpu.utils import serverdir
from hyperqueue_tpu.utils.retry import jittered_backoff

def _env_retry_secs() -> float:
    raw = os.environ.get("HQ_CLIENT_RETRY_SECS", "15")
    try:
        return float(raw)
    except ValueError:
        import logging

        logging.getLogger("hq.client").warning(
            "ignoring malformed HQ_CLIENT_RETRY_SECS=%r; using 15", raw
        )
        return 15.0


_BACKOFF_BASE = 0.2
_BACKOFF_CAP = 2.0

# transient transport failures worth retrying; AuthError and malformed
# access records are NOT here — retrying a bad key never helps
_RETRIABLE = (ConnectionError, OSError, asyncio.IncompleteReadError)


class ClientError(Exception):
    pass


class ClientSession:
    """Sync facade: runs its own event loop for request/response exchanges.

    `retry_window`: seconds to keep retrying transient connection failures
    (None = HQ_CLIENT_RETRY_SECS; 0 = fail on the first error, used by
    callers with their own polling loop like `hq server wait`).
    """

    def __init__(self, server_dir: Path, retry_window: float | None = None):
        self.server_dir = Path(server_dir)
        # env read per session, not at import: long-lived embedders (API
        # client, tests) may set HQ_CLIENT_RETRY_SECS after the module is
        # first imported
        self.retry_window = (
            _env_retry_secs() if retry_window is None else retry_window
        )
        self._rng = random.Random()
        self.access = None
        self._loop = asyncio.new_event_loop()
        try:
            self._conn = self._loop.run_until_complete(
                self._connect_with_retry()
            )
        except BaseException:
            self._loop.close()
            raise

    async def _connect(self):
        # re-load per attempt: a restarted server means a new instance dir
        # (new ports AND new plane keys)
        self.access = serverdir.load_access(self.server_dir)
        if not self.access.client_port:
            raise RuntimeError(
                "access record has no client plane (worker-only split file?)"
            )
        reader, writer = await asyncio.open_connection(
            self.access.host, self.access.client_port
        )
        try:
            return await do_authentication(
                reader,
                writer,
                ROLE_CLIENT,
                ROLE_SERVER,
                self.access.client_key_bytes(),
            )
        except BaseException:
            # a failed handshake must not leak its socket — the retry loop
            # can make a dozen attempts per CLI call during a restart
            writer.close()
            raise

    def _retries_exhausted(self, deadline: float) -> bool:
        return self.retry_window <= 0 or time.monotonic() >= deadline

    async def _connect_with_retry(self, deadline: float | None = None):
        # `deadline` lets request() span ONE retry window across its
        # send/reconnect cycles instead of granting each reconnect a fresh
        # window (which would stack to a multiple of HQ_CLIENT_RETRY_SECS)
        if deadline is None:
            deadline = time.monotonic() + self.retry_window
        delay = _BACKOFF_BASE
        while True:
            try:
                return await self._connect()
            except FileNotFoundError:
                # no access record: distinguish "no server was ever started
                # / it stopped cleanly" (no hq-current symlink — fail fast
                # with the clear message) from "a new instance dir is being
                # published right now" (symlink flipped, access file lands
                # a moment later — a genuine restart window, retry)
                if not (
                    self.server_dir / serverdir.CURRENT_LINK
                ).is_symlink():
                    raise
                if self._retries_exhausted(deadline):
                    raise
            except _RETRIABLE:
                if self._retries_exhausted(deadline):
                    raise
            sleep_for, delay = jittered_backoff(
                delay, _BACKOFF_CAP, self._rng,
                remaining=deadline - time.monotonic(),
            )
            await asyncio.sleep(sleep_for)

    def request(self, msg: dict, timeout: float | None = None) -> dict:
        async def go():
            await self._conn.send(msg)
            return await self._conn.recv()

        deadline = time.monotonic() + self.retry_window
        while True:
            coro = asyncio.wait_for(go(), timeout) if timeout else go()
            try:
                response = self._loop.run_until_complete(coro)
                break
            except asyncio.TimeoutError:
                # the caller's per-request deadline — never retried (on
                # 3.11+ TimeoutError subclasses OSError, so this must be
                # caught BEFORE the retriable set)
                raise
            except _RETRIABLE:
                if self._retries_exhausted(deadline):
                    raise
                # server restart window: reconnect (fresh access record)
                # and re-send — the reconnect shares THIS request's
                # deadline, so the whole exchange stays bounded by one
                # retry window
                self._conn.close()
                self._conn = self._loop.run_until_complete(
                    self._connect_with_retry(deadline=deadline)
                )
        if isinstance(response, dict) and response.get("op") == "error":
            raise ClientError(response.get("message", "server error"))
        return response

    def close(self) -> None:
        self._conn.close()
        self._loop.run_until_complete(self._conn.wait_closed())
        self._loop.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SubmitStream:
    """Pipelined chunked submit over one ClientSession (ISSUE 10).

    Chunks are tagged with (stream uid, chunk index) and sent without
    waiting for each response; the client keeps a bounded in-flight
    window (HQ_SUBMIT_WINDOW, default 8) and reads per-chunk acks as the
    window fills — so a giant array streams to the server at pipeline
    speed with bounded memory on BOTH ends.

    Exactly-once across failures: on a transport error (server restart
    window) the stream reconnects through the session's retry machinery
    and re-sends every unacked chunk. The server deduplicates on
    (uid, index) — journaled with each chunk — so replayed chunks yield
    idempotent duplicate acks, never duplicate tasks. After the first ack
    the job id is pinned into the header, so chunks replayed against a
    restored server land on the SAME job.

    `n_tasks` is the stream's acknowledged task coverage (counted from
    the chunks themselves, so a chunk whose first ack was lost and whose
    replay acked `dup` still counts once); `dup_chunks` counts acks the
    server deduplicated.
    """

    def __init__(self, session: ClientSession, header: dict,
                 window: int | None = None, uid: str | None = None):
        from hyperqueue_tpu.utils.trace import new_trace_id

        self.session = session
        self.header = dict(header)
        if window is None:
            try:
                window = int(os.environ.get("HQ_SUBMIT_WINDOW", "8"))
            except ValueError:
                window = 8
        self.window = max(window, 1)
        self.uid = uid or new_trace_id()
        self.job_id: int | None = None
        self.n_tasks = 0
        self.dup_chunks = 0
        self._next_index = 0
        self._unacked: dict[int, dict] = {}
        self._sealed = False

    # --- wire helpers (session-loop, with reconnect + replay) -----------
    def _replay_unacked(self) -> None:
        for i in sorted(self._unacked):
            frame = self._unacked[i]
            if self.job_id is not None:
                frame["job"]["job_id"] = self.job_id
            self.session._loop.run_until_complete(
                self.session._conn.send(frame)
            )

    def _recover(self, deadline: float) -> None:
        """Reconnect and replay every unacked chunk, retrying the whole
        sequence (a second connection flap mid-replay must keep retrying
        within the SAME window, not abort the stream)."""
        while True:
            if self.session._retries_exhausted(deadline):
                raise ConnectionError(
                    "submit stream: retry window exhausted"
                )
            self.session._conn.close()
            self.session._conn = self.session._loop.run_until_complete(
                self.session._connect_with_retry(deadline=deadline)
            )
            try:
                self._replay_unacked()
                return
            except _RETRIABLE:
                continue

    def _with_retry(self, op) -> dict | None:
        """Run one recv step; on a transport error reconnect + replay the
        unacked chunks, then retry the step. (Sends do NOT use this — a
        replay already re-sends the failed frame, so retrying the send
        itself would put a duplicate on the wire whose extra ack desyncs
        the session's request/response protocol.)"""
        deadline = time.monotonic() + self.session.retry_window
        while True:
            try:
                return self.session._loop.run_until_complete(op())
            except _RETRIABLE:
                self._recover(deadline)

    def _recv_ack(self) -> None:
        async def step():
            return await self.session._conn.recv()

        ack = self._with_retry(step)
        if not isinstance(ack, dict) or ack.get("op") == "error":
            msg = (ack or {}).get("message", "server error")
            raise ClientError(msg)
        index = ack["i"]
        frame = self._unacked.pop(index, None)
        if self.job_id is None:
            self.job_id = ack["job_id"]
            self.header["job_id"] = self.job_id
        if ack.get("dup"):
            self.dup_chunks += 1
        # count tasks from the FRAME on its first ack, not from the
        # server's n_tasks field: a chunk applied before a connection
        # drop acks `dup` (n_tasks=0) on the replay, and the stream's
        # total must still cover it
        if frame is not None:
            self.n_tasks += _frame_task_count(frame)

    def _send_frame(self, frame: dict) -> None:
        while len(self._unacked) >= self.window:
            self._recv_ack()
        self._unacked[frame["i"]] = frame
        try:
            self.session._loop.run_until_complete(
                self.session._conn.send(frame)
            )
        except _RETRIABLE:
            # the frame is already in _unacked: recovery's replay sends
            # it exactly once on the new connection — do NOT also retry
            # the send (the extra duplicate would earn an extra ack that
            # finish() never drains, desyncing the session)
            self._recover(time.monotonic() + self.session.retry_window)

    # --- public API -------------------------------------------------------
    def send_chunk(self, array: dict | None = None,
                   tasks: list | None = None, last: bool = False) -> None:
        """Queue one chunk: an array description ({"id_range": [lo, hi)}
        or {"ids": [...]} plus shared body/request/...) or a graph task
        list. Blocks only while the in-flight window is full."""
        if self._sealed:
            raise ClientError("submit stream already finished")
        from hyperqueue_tpu.transport.framing import attach_trace
        from hyperqueue_tpu.utils.trace import new_trace_id

        frame: dict = {
            "op": "submit_chunk",
            "uid": self.uid,
            "i": self._next_index,
            "rid": self._next_index,
            "job": dict(self.header),
        }
        if array is not None:
            frame["array"] = array
        if tasks is not None:
            frame["tasks"] = tasks
        if last:
            frame["last"] = True
            self._sealed = True
        attach_trace(frame, new_trace_id(), sent_at=time.time())
        self._next_index += 1
        self._send_frame(frame)

    def finish(self) -> tuple[int, int]:
        """Seal the stream (empty final chunk if none was marked last),
        drain every outstanding ack, and return (job_id, n_tasks)."""
        if not self._sealed:
            self.send_chunk(last=True)
        while self._unacked:
            self._recv_ack()
        return self.job_id, self.n_tasks


def _frame_task_count(frame: dict) -> int:
    """Tasks carried by one submit_chunk frame (client-side count for the
    stream total — independent of whether the server ack was a dup)."""
    array = frame.get("array")
    if array:
        id_range = array.get("id_range")
        if id_range is not None:
            return int(id_range[1]) - int(id_range[0])
        return len(array.get("ids") or ())
    return len(frame.get("tasks") or ())


def _streaming_request(server_dir: Path, request: dict, on_subscribed=None):
    """One authenticated client connection turned into a frame generator:
    send `request`, yield every received frame until the server closes or
    the consumer breaks out. Blocking-recv based (read_frame is not
    cancellation-safe, so no wait_for timeouts may wrap it).
    on_subscribed, when given, is called once the request is on the wire —
    before the first frame is read."""

    async def _connect():
        access = serverdir.load_access(Path(server_dir))
        reader, writer = await asyncio.open_connection(
            access.host, access.client_port
        )
        conn = await do_authentication(
            reader, writer, ROLE_CLIENT, ROLE_SERVER, access.client_key_bytes()
        )
        await conn.send(request)
        return conn

    loop = asyncio.new_event_loop()
    conn = None
    try:
        conn = loop.run_until_complete(_connect())
        if on_subscribed is not None:
            on_subscribed()
        while True:
            yield loop.run_until_complete(conn.recv())
    finally:
        # the consumer may break out of the generator at any point
        # (dashboard quit, Ctrl-C in `hq journal stream`): close the
        # authenticated connection before the loop, or the socket leaks
        if conn is not None:
            try:
                conn.close()
                loop.run_until_complete(conn.wait_closed())
            except Exception:
                pass
        loop.close()


def subscribe(server_dir: Path, filters=(), sample_interval: float = 0.0,
              buffer: int = 4096, overviews: bool = False,
              on_subscribed=None):
    """Generator of frames from the server's `subscribe` RPC: coalesced
    lifecycle-event frames ({"op": "events", "records": [...]}) plus
    periodic metric samples ({"op": "sample", ...}) when sample_interval
    is set. This is the push feed `hq top` and the autoscaler consume —
    no polling; a consumer that falls behind the server's bounded
    per-subscriber queue receives a final {"op": "sub_dropped"} frame."""
    request = {
        "op": "subscribe",
        "filter": list(filters),
        "sample_interval": sample_interval,
        "buffer": buffer,
        "overviews": overviews,
    }
    for msg in _streaming_request(server_dir, request, on_subscribed):
        yield msg
        if msg.get("op") == "sub_dropped":
            return


def stream_events(server_dir: Path, history: bool = False, filters=(),
                  on_subscribed=None, overviews: bool = False):
    """Generator of event records from the server's client-plane stream;
    shared by `hq journal stream` and the dashboard."""
    request = {
        "op": "stream_events", "history": history,
        "filter": list(filters),
        # ask the server to force worker hw overviews on while this
        # stream is attached (dashboards; SetOverviewIntervalOverride)
        "overviews": overviews,
    }
    yield from _streaming_request(server_dir, request, on_subscribed)
