"""Synchronous client connection for CLI commands.

Each CLI invocation opens one authenticated connection on the client plane
(reference client/mod.rs does the same via its async runtime).
"""

from __future__ import annotations

import asyncio
from pathlib import Path

from hyperqueue_tpu.transport.auth import (
    ROLE_CLIENT,
    ROLE_SERVER,
    do_authentication,
)
from hyperqueue_tpu.utils import serverdir


class ClientError(Exception):
    pass


class ClientSession:
    """Sync facade: runs its own event loop for request/response exchanges."""

    def __init__(self, server_dir: Path):
        self.access = serverdir.load_access(Path(server_dir))
        if not self.access.client_port:
            raise RuntimeError(
                "access record has no client plane (worker-only split file?)"
            )
        self._loop = asyncio.new_event_loop()
        self._conn = self._loop.run_until_complete(self._connect())

    async def _connect(self):
        reader, writer = await asyncio.open_connection(
            self.access.host, self.access.client_port
        )
        return await do_authentication(
            reader,
            writer,
            ROLE_CLIENT,
            ROLE_SERVER,
            self.access.client_key_bytes(),
        )

    def request(self, msg: dict, timeout: float | None = None) -> dict:
        async def go():
            await self._conn.send(msg)
            return await self._conn.recv()

        coro = asyncio.wait_for(go(), timeout) if timeout else go()
        response = self._loop.run_until_complete(coro)
        if isinstance(response, dict) and response.get("op") == "error":
            raise ClientError(response.get("message", "server error"))
        return response

    def close(self) -> None:
        self._conn.close()
        self._loop.run_until_complete(self._conn.wait_closed())
        self._loop.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def stream_events(server_dir: Path, history: bool = False, filters=(),
                  on_subscribed=None, overviews: bool = False):
    """Generator of event records from the server's client-plane stream.

    Blocking-recv based (read_frame is not cancellation-safe, so no
    wait_for timeouts may wrap it); shared by `hq journal stream` and the
    dashboard. on_subscribed, when given, is called once the subscription
    request is on the wire — before the first record is read."""

    async def _connect():
        access = serverdir.load_access(Path(server_dir))
        reader, writer = await asyncio.open_connection(
            access.host, access.client_port
        )
        conn = await do_authentication(
            reader, writer, ROLE_CLIENT, ROLE_SERVER, access.client_key_bytes()
        )
        await conn.send(
            {"op": "stream_events", "history": history,
             "filter": list(filters),
             # ask the server to force worker hw overviews on while this
             # stream is attached (dashboards; SetOverviewIntervalOverride)
             "overviews": overviews}
        )
        return conn

    loop = asyncio.new_event_loop()
    conn = None
    try:
        conn = loop.run_until_complete(_connect())
        if on_subscribed is not None:
            on_subscribed()
        while True:
            msg = loop.run_until_complete(conn.recv())
            yield msg
    finally:
        # the consumer may break out of the generator at any point
        # (dashboard quit, Ctrl-C in `hq journal stream`): close the
        # authenticated connection before the loop, or the socket leaks
        if conn is not None:
            try:
                conn.close()
                loop.run_until_complete(conn.wait_closed())
            except Exception:
                pass
        loop.close()
