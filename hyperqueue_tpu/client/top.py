"""`hq top`: live cluster view fed by the subscribe RPC.

Unlike the dashboard (which polls request/response RPCs on an interval),
top consumes the server's PUSH feed — one subscription delivers lifecycle
events as they happen plus a metric sample every refresh interval, so the
view updates without a single poll. The same feed is the programmatic
signal source for the autoscaler (queue depth, pending reasons, per-worker
load); top is its human face.

``--once`` prints a single sample (JSON under ``--output-mode json``) and
exits — the scriptable/testing entry point.
"""

from __future__ import annotations

import json
import sys
import time
from collections import deque
from pathlib import Path

# lifecycle kinds worth showing in the event ticker (worker overviews are
# high-frequency noise at a 2 s cadence)
_TICKER_SKIP = ("worker-overview",)


def _fmt_age(seconds: float) -> str:
    if seconds >= 3600:
        return f"{seconds / 3600:.1f}h"
    if seconds >= 60:
        return f"{seconds / 60:.1f}m"
    return f"{seconds:.0f}s"


def _render(sample: dict, ticker: deque, dropped: int) -> str:
    lines = []
    lines.append(
        f"hq top — up {_fmt_age(sample.get('uptime', 0.0))}, "
        f"{sample.get('n_workers', 0)} worker(s), "
        f"{sample.get('n_jobs', 0)} job(s), "
        f"tick {sample.get('tick', 0)}"
        + (f", last tick {sample['tick_last_ms']:.2f} ms"
           if sample.get("tick_last_ms") else "")
    )
    lines.append(
        f"tasks: {sample.get('running', 0)} running, "
        f"{sample.get('ready', 0)} ready, "
        f"{sample.get('mn_queued', 0)} gang-queued, "
        f"{sample.get('tasks_known', 0)} known"
    )
    job_counts = sample.get("job_counts") or {}
    if job_counts:
        lines.append(
            "jobs: " + ", ".join(
                f"{n} {status}" for status, n in sorted(job_counts.items())
            )
        )
    reasons = sample.get("pending_reasons") or {}
    if reasons:
        lines.append(
            "waiting: " + ", ".join(
                f"{n} {code}" for code, n in sorted(reasons.items())
            )
        )
    lag = sample.get("lag") or {}
    if lag:
        cells = []
        for plane in ("solve", "journal", "rpc", "fanout", "loop"):
            row = lag.get(plane)
            if row:
                cells.append(f"{plane} {row['last_ms']:.1f}/{row['max_ms']:.1f}")
        if cells:
            lines.append("loop lag ms (last/max): " + "  ".join(cells))
    if sample.get("stalls"):
        lines.append(f"reactor stalls captured: {sample['stalls']}")
    workers = sample.get("workers") or []
    if workers:
        lines.append("")
        lines.append(f"{'worker':>8} {'host':<20} {'running':>8} "
                     f"{'prefilled':>10} {'cpu%':>6}")
        for w in sorted(workers, key=lambda w: w["id"])[:32]:
            cpu = w.get("cpu")
            lines.append(
                f"{w['id']:>8} {str(w.get('hostname', ''))[:20]:<20} "
                f"{w.get('running', 0):>8} {w.get('prefilled', 0):>10} "
                f"{cpu:>6.1f}" if cpu is not None else
                f"{w['id']:>8} {str(w.get('hostname', ''))[:20]:<20} "
                f"{w.get('running', 0):>8} {w.get('prefilled', 0):>10} "
                f"{'-':>6}"
            )
        if len(workers) > 32:
            lines.append(f"  … {len(workers) - 32} more worker(s)")
    if ticker:
        lines.append("")
        lines.append("recent events:")
        for rec in list(ticker)[-10:]:
            t = time.strftime("%H:%M:%S", time.localtime(rec.get("time", 0)))
            rest = {
                k: v for k, v in rec.items()
                if k not in ("time", "seq", "event", "desc", "metrics", "hw")
            }
            lines.append(f"  {t} {rec.get('event')} {rest}")
    if dropped:
        lines.append(f"(events dropped: {dropped})")
    return "\n".join(lines)


def run_top(server_dir: Path, interval: float = 1.0, once: bool = False,
            output_mode: str = "cli") -> int:
    """Drive the live view until interrupted (or one sample with --once)."""
    from hyperqueue_tpu.client.connection import subscribe

    ticker: deque = deque(maxlen=64)
    last_sample: dict | None = None
    dropped = 0
    is_tty = sys.stdout.isatty()
    try:
        for msg in subscribe(
            server_dir,
            sample_interval=max(interval, 0.2),
            overviews=not once,
        ):
            op = msg.get("op")
            if op == "events":
                for rec in msg.get("records") or ():
                    if not str(rec.get("event", "")).startswith(_TICKER_SKIP):
                        ticker.append(rec)
                continue
            if op == "sub_dropped":
                dropped = msg.get("dropped", 0)
                print("subscription dropped: this consumer fell behind "
                      "the server's bounded event queue", file=sys.stderr)
                return 1
            if op != "sample":
                continue  # sub_live handshake
            last_sample = msg
            if once:
                if output_mode == "json":
                    out = dict(msg)
                    out.pop("op", None)
                    print(json.dumps(out))
                else:
                    print(_render(msg, ticker, dropped))
                return 0
            frame = _render(msg, ticker, dropped)
            if is_tty:
                # home + clear-below: steady redraw without flicker
                sys.stdout.write("\x1b[H\x1b[J" + frame + "\n")
            else:
                sys.stdout.write(frame + "\n---\n")
            sys.stdout.flush()
    except KeyboardInterrupt:
        return 0
    # stream ended server-side
    if last_sample is None:
        print("subscription closed before the first sample", file=sys.stderr)
        return 1
    return 0
