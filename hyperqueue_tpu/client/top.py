"""`hq top`: live cluster view fed by the subscribe RPC.

Unlike the dashboard (which polls request/response RPCs on an interval),
top consumes the server's PUSH feed — one subscription delivers lifecycle
events as they happen plus a metric sample every refresh interval, so the
view updates without a single poll. The same feed is the programmatic
signal source for the autoscaler (queue depth, pending reasons, per-worker
load); top is its human face.

``--once`` prints a single sample (JSON under ``--output-mode json``) and
exits — the scriptable/testing entry point.
"""

from __future__ import annotations

import json
import sys
import time
from collections import deque
from pathlib import Path

# lifecycle kinds worth showing in the event ticker (worker overviews are
# high-frequency noise at a 2 s cadence)
_TICKER_SKIP = ("worker-overview",)


def _fmt_age(seconds: float) -> str:
    if seconds >= 3600:
        return f"{seconds / 3600:.1f}h"
    if seconds >= 60:
        return f"{seconds / 60:.1f}m"
    return f"{seconds:.0f}s"


def _render(sample: dict, ticker: deque, dropped: int) -> str:
    lines = []
    lines.append(
        f"hq top — up {_fmt_age(sample.get('uptime', 0.0))}, "
        f"{sample.get('n_workers', 0)} worker(s), "
        f"{sample.get('n_jobs', 0)} job(s), "
        f"tick {sample.get('tick', 0)}"
        + (f", last tick {sample['tick_last_ms']:.2f} ms"
           if sample.get("tick_last_ms") else "")
    )
    lines.append(
        f"tasks: {sample.get('running', 0)} running, "
        f"{sample.get('ready', 0)} ready, "
        f"{sample.get('mn_queued', 0)} gang-queued, "
        f"{sample.get('tasks_known', 0)} known"
    )
    job_counts = sample.get("job_counts") or {}
    if job_counts:
        lines.append(
            "jobs: " + ", ".join(
                f"{n} {status}" for status, n in sorted(job_counts.items())
            )
        )
    reasons = sample.get("pending_reasons") or {}
    if reasons:
        lines.append(
            "waiting: " + ", ".join(
                f"{n} {code}" for code, n in sorted(reasons.items())
            )
        )
    lag = sample.get("lag") or {}
    if lag:
        cells = []
        for plane in ("solve", "journal", "rpc", "fanout", "loop"):
            row = lag.get(plane)
            if row:
                cells.append(f"{plane} {row['last_ms']:.1f}/{row['max_ms']:.1f}")
        if cells:
            lines.append("loop lag ms (last/max): " + "  ".join(cells))
    if sample.get("stalls"):
        lines.append(f"reactor stalls captured: {sample['stalls']}")
    profile = sample.get("profile") or {}
    if profile:
        lines.append(
            "plane cpu%: " + "  ".join(
                f"{plane} {share * 100:.1f}"
                for plane, share in sorted(
                    profile.items(), key=lambda kv: -kv[1]
                )
            )
        )
    workers = sample.get("workers") or []
    if workers:
        lines.append("")
        lines.append(f"{'worker':>8} {'host':<20} {'running':>8} "
                     f"{'prefilled':>10} {'cpu%':>6} planes")
        for w in sorted(workers, key=lambda w: w["id"])[:32]:
            cpu = w.get("cpu")
            planes = w.get("planes") or {}
            plane_cell = " ".join(
                f"{p}:{v * 100:.0f}%"
                for p, v in sorted(planes.items(), key=lambda kv: -kv[1])
            )
            lines.append(
                (f"{w['id']:>8} {str(w.get('hostname', ''))[:20]:<20} "
                 f"{w.get('running', 0):>8} {w.get('prefilled', 0):>10} "
                 f"{cpu:>6.1f}" if cpu is not None else
                 f"{w['id']:>8} {str(w.get('hostname', ''))[:20]:<20} "
                 f"{w.get('running', 0):>8} {w.get('prefilled', 0):>10} "
                 f"{'-':>6}")
                + (f" {plane_cell}" if plane_cell else "")
            )
        if len(workers) > 32:
            lines.append(f"  … {len(workers) - 32} more worker(s)")
    if ticker:
        lines.append("")
        lines.append("recent events:")
        for rec in list(ticker)[-10:]:
            t = time.strftime("%H:%M:%S", time.localtime(rec.get("time", 0)))
            rest = {
                k: v for k, v in rec.items()
                if k not in ("time", "seq", "event", "desc", "metrics", "hw")
            }
            lines.append(f"  {t} {rec.get('event')} {rest}")
    if dropped:
        lines.append(f"(events dropped: {dropped})")
    return "\n".join(lines)


def _alert_badge(sample: dict | None, state: str) -> str:
    """SLO alert badge for one shard (ISSUE 18). A DOWN shard has no
    sample to carry its badge, but its very downness IS the
    shard-availability SLO breach — render that instead of a blank."""
    if sample is None:
        return "avail!" if state != "up" else "-"
    alerts = sample.get("alerts") or {}
    firing = alerts.get("firing", 0)
    if not firing:
        return "ok"
    worst = alerts.get("worst") or "page"
    mark = "!" if worst == "page" else "~"
    return f"{firing}{mark}{worst}"


def _fleet_row(shard: int, state: str, sample: dict | None) -> str:
    """One shard's line in the fleet table (DOWN shards render a row —
    that is the whole point; the client never crashes on a dead shard)."""
    badge = _alert_badge(sample, state)
    if sample is None:
        return f"{shard:>5} {state.upper():<9} {'-':>5} {'-':>7} " \
               f"{'-':>7} {'-':>7} {'-':>7} {'-':>6} {'-':>8} {'-':>5} " \
               f"{badge:>7}"
    fed = sample.get("federation") or {}
    lag = (sample.get("lag") or {}).get("loop") or {}
    label = "UP"
    if fed.get("promoted"):
        label = "UP*"  # promoted successor
    borrowed = fed.get("workers_borrowed", 0)
    return (
        f"{shard:>5} {label:<9} "
        f"{fed.get('lease_epoch', '-'):>5} "
        f"{sample.get('n_workers', 0):>7} "
        f"{borrowed:>7} "
        f"{sample.get('running', 0):>7} "
        f"{sample.get('ready', 0) + sample.get('mn_queued', 0):>7} "
        f"{len(sample.get('pending_reasons') or {}):>6} "
        + (f"{lag['last_ms']:>8.1f} " if lag.get("last_ms") is not None
           else f"{'-':>8} ")
        + f"{sample.get('alloc_quarantined', 0):>5} "
        + f"{badge:>7}"
    )


def _backlog_convergence(samples: dict) -> str | None:
    """One line tracking the rebalancer's target function: max/mean
    backlog ratio across live shards (the hysteresis band is 1.5x — the
    line makes a rebalance visibly converge under `hq top`)."""
    backlogs = {
        k: (s.get("ready", 0) + s.get("mn_queued", 0))
        for k, s in samples.items() if s is not None
    }
    if len(backlogs) < 2:
        return None
    mean = sum(backlogs.values()) / len(backlogs)
    hot = max(backlogs, key=backlogs.get)
    if mean <= 0:
        return "backlog: balanced (all empty)"
    ratio = backlogs[hot] / mean
    return (
        f"backlog: max {backlogs[hot]} (shard {hot}) mean {mean:.1f} "
        f"ratio {ratio:.2f}x"
        + (" — imbalanced (rebalance target >1.50x)" if ratio > 1.5
           else " — converged")
    )


def _render_fleet(states: dict, samples: dict, ticker: deque,
                  lend_flows: dict, ownership: dict | None = None) -> str:
    """The fleet view: per-shard health rows + backlog convergence +
    in-flight migrations + lending flows + merged event ticker.
    Everything but the ownership block comes off the FleetFeed — no
    polling; the ownership block is one lock-free log read per frame."""
    up = sum(1 for s in states.values() if s == "up")
    lines = [
        f"hq fleet — {len(states)} shard(s), {up} up",
        f"{'shard':>5} {'state':<9} {'epoch':>5} {'workers':>7} "
        f"{'borrow':>7} {'running':>7} {'backlog':>7} {'wait':>6} "
        f"{'lag ms':>8} {'quar':>5} {'alerts':>7}",
    ]
    for shard in sorted(states):
        state = "up" if states[shard] == "up" else "down"
        lines.append(_fleet_row(shard, state, samples.get(shard)))
    conv = _backlog_convergence(samples)
    if conv:
        lines.append(conv)
    if ownership:
        for rec in ownership.get("in_flight") or ():
            lines.append(
                f"migrating: job {rec['job']} shard {rec['from']} -> "
                f"{rec['to']} ({rec['phase']}, {rec['mig']})"
            )
        if ownership.get("moved"):
            lines.append(
                f"ownership: epoch {ownership.get('epoch', 0)}, "
                f"{ownership['moved']} job(s) on non-home shards"
            )
    if lend_flows:
        lines.append(
            "lend flows: " + "  ".join(
                f"{a}→{b} ×{n}"
                for (a, b), n in sorted(lend_flows.items())
            )
        )
    if ticker:
        lines.append("")
        lines.append("recent events:")
        for rec in list(ticker)[-10:]:
            t = time.strftime("%H:%M:%S", time.localtime(rec.get("time", 0)))
            rest = {
                k: v for k, v in rec.items()
                if k not in ("time", "seq", "event", "desc", "metrics",
                             "hw", "shard")
            }
            lines.append(
                f"  {t} [shard {rec.get('shard')}] "
                f"{rec.get('event')} {rest}"
            )
    return "\n".join(lines)


def _note_lend_flow(rec: dict, lend_flows: dict) -> None:
    """Fold one structured lend event into the flow counters: the
    lender's worker-lost carries `lent_to`, the borrower's
    worker-connected carries `lent_from` (no string parsing — ISSUE 15).
    Counted from the lender side only, so one move is one increment."""
    if rec.get("event") == "worker-lost" and rec.get("lent_to") is not None:
        key = (rec.get("shard"), rec["lent_to"])
        lend_flows[key] = lend_flows.get(key, 0) + 1


def run_fleet_top(server_dir: Path, interval: float = 1.0,
                  once: bool = False, output_mode: str = "cli") -> int:
    """`hq top` against a federation root: the whole fleet as one view,
    fed by one FleetFeed (a subscribe stream per shard, merged). A
    killed shard flips to DOWN and back to UP after its successor
    promotes — the view rides failovers, it never crashes on them."""
    from hyperqueue_tpu.client.fleet import FleetFeed, fleet_snapshot

    def ownership_block() -> dict | None:
        from hyperqueue_tpu.utils.ownership import OwnershipStore

        try:
            omap = OwnershipStore(server_dir).load()
        except OSError:
            return None
        return {
            "epoch": omap.epoch,
            "moved": len(omap.assignments),
            "in_flight": omap.in_flight(),
        }

    if once:
        samples = fleet_snapshot(server_dir, sample_interval=min(
            max(interval, 0.2), 1.0
        ))
        states = {
            k: ("up" if s is not None else "down")
            for k, s in samples.items()
        }
        if output_mode == "json":
            out = {
                str(k): (
                    {kk: vv for kk, vv in s.items() if kk != "op"}
                    if s is not None else None
                )
                for k, s in samples.items()
            }
            print(json.dumps({"shards": out}))
        else:
            print(_render_fleet(states, samples, deque(), {},
                                ownership_block()))
        return 0

    ticker: deque = deque(maxlen=64)
    lend_flows: dict = {}
    is_tty = sys.stdout.isatty()
    feed = FleetFeed(server_dir, sample_interval=max(interval, 0.2))
    try:
        with feed:
            for frame in feed.frames():
                op = frame.get("op")
                if op == "events":
                    for rec in frame.get("records") or ():
                        _note_lend_flow(rec, lend_flows)
                        if not str(rec.get("event", "")).startswith(
                            _TICKER_SKIP
                        ):
                            ticker.append(rec)
                elif op not in ("sample", "shard-down", "shard-up"):
                    continue
                view = _render_fleet(
                    dict(feed.states), dict(feed.last_sample), ticker,
                    lend_flows, ownership_block(),
                )
                if is_tty:
                    sys.stdout.write("\x1b[H\x1b[J" + view + "\n")
                else:
                    sys.stdout.write(view + "\n---\n")
                sys.stdout.flush()
    except KeyboardInterrupt:
        return 0
    return 0


def run_top(server_dir: Path, interval: float = 1.0, once: bool = False,
            output_mode: str = "cli", shard: int | None = None) -> int:
    """Drive the live view until interrupted (or one sample with --once).

    Against a federation root this is the FLEET view (all shards, DOWN
    rows included) unless ``--shard K`` focuses one shard — which uses
    the classic single-server view over that shard's subscribe feed."""
    from hyperqueue_tpu.client.connection import subscribe
    from hyperqueue_tpu.utils import serverdir

    fed = serverdir.load_federation(Path(server_dir))
    if shard is None and fed is not None:
        return run_fleet_top(server_dir, interval=interval, once=once,
                             output_mode=output_mode)
    if shard is not None:
        # the info|stats --shard convention: a typo'd selector fails
        # loudly instead of hanging on a nonexistent shard dir, and a
        # classic dir must not silently ignore the flag
        if fed is None:
            print(f"--shard needs a federation root; {server_dir} is a "
                  "classic server dir", file=sys.stderr)
            return 1
        count = int(fed["shard_count"])
        if not (0 <= shard < count):
            print(f"shard {shard} outside 0..{count - 1}", file=sys.stderr)
            return 1

    ticker: deque = deque(maxlen=64)
    last_sample: dict | None = None
    dropped = 0
    is_tty = sys.stdout.isatty()
    try:
        for msg in subscribe(
            server_dir,
            sample_interval=max(interval, 0.2),
            overviews=not once,
            shard=shard or 0,
        ):
            op = msg.get("op")
            if op == "events":
                for rec in msg.get("records") or ():
                    if not str(rec.get("event", "")).startswith(_TICKER_SKIP):
                        ticker.append(rec)
                continue
            if op == "sub_dropped":
                dropped = msg.get("dropped", 0)
                print("subscription dropped: this consumer fell behind "
                      "the server's bounded event queue", file=sys.stderr)
                return 1
            if op != "sample":
                continue  # sub_live handshake
            last_sample = msg
            if once:
                if output_mode == "json":
                    out = dict(msg)
                    out.pop("op", None)
                    print(json.dumps(out))
                else:
                    print(_render(msg, ticker, dropped))
                return 0
            frame = _render(msg, ticker, dropped)
            if is_tty:
                # home + clear-below: steady redraw without flicker
                sys.stdout.write("\x1b[H\x1b[J" + frame + "\n")
            else:
                sys.stdout.write(frame + "\n---\n")
            sys.stdout.flush()
    except KeyboardInterrupt:
        return 0
    # stream ended server-side
    if last_sample is None:
        print("subscription closed before the first sample", file=sys.stderr)
        return 1
    return 0
