"""Output formatting: CLI tables, JSON, quiet.

Reference: crates/hyperqueue/src/client/output/{cli,json,quiet}.rs — every
command renders through an Output backend selected by --output-mode so
scripts can rely on stable JSON while humans get tables.
"""

from __future__ import annotations

import json
import sys


class Output:
    def table(self, header: list[str], rows: list[list]) -> None:
        raise NotImplementedError

    def record(self, data: dict) -> None:
        raise NotImplementedError

    def message(self, text: str) -> None:
        raise NotImplementedError

    def value(self, value) -> None:
        raise NotImplementedError


class CliOutput(Output):
    def table(self, header, rows):
        widths = [len(h) for h in header]
        str_rows = [[str(c) for c in row] for row in rows]
        for row in str_rows:
            for i, cell in enumerate(row):
                widths[i] = max(widths[i], len(cell))
        line = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        print(line)
        print(
            "|"
            + "|".join(f" {h.ljust(w)} " for h, w in zip(header, widths))
            + "|"
        )
        print(line)
        for row in str_rows:
            print(
                "|"
                + "|".join(f" {c.ljust(w)} " for c, w in zip(row, widths))
                + "|"
            )
        if rows:
            print(line)

    def record(self, data):
        rows = [[k, v] for k, v in data.items()]
        self.table(["key", "value"], rows)

    def message(self, text):
        print(text)

    def value(self, value):
        print(value)


class JsonOutput(Output):
    def table(self, header, rows):
        print(
            json.dumps(
                [dict(zip(header, row)) for row in rows], default=str
            )
        )

    def record(self, data):
        print(json.dumps(data, default=str))

    def message(self, text):
        print(json.dumps({"message": text}))

    def value(self, value):
        print(json.dumps(value, default=str))


class QuietOutput(Output):
    def table(self, header, rows):
        # id + status per line for single-key entity listings (reference
        # output/test_quiet.py: "1 FINISHED"); full rows for multi-key
        # tables (task lists, alloc info) where dropping columns would
        # lose the identifying ids
        lowered = [str(h).lower() for h in header]
        status_idx = (
            lowered.index("status") if "status" in lowered else None
        )
        compact = status_idx not in (None, 0) and lowered[0] == "id"
        for row in rows:
            if compact:
                print(f"{row[0]} {row[status_idx]}")
            else:
                print(" ".join(str(c) for c in row))

    def record(self, data):
        pass

    def message(self, text):
        pass

    def value(self, value):
        print(value)


def make_output(mode: str) -> Output:
    if mode == "json":
        return JsonOutput()
    if mode == "quiet":
        return QuietOutput()
    return CliOutput()


def fail(message: str) -> "NoReturn":  # noqa: F821
    print(f"error: {message}", file=sys.stderr)
    raise SystemExit(1)
