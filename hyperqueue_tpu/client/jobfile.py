"""TOML Job Definition Files.

Reference: crates/hyperqueue/src/client/commands/submit/{jobfile,defs}.rs +
docs/jobs/jobfile.md — jobs with task graphs, per-task resource requests and
OR-variants, described declaratively:

    name = "my-job"
    max_fails = 1

    [[task]]
    id = 0
    command = ["python", "prepare.py"]

    [[task]]
    id = 1
    command = ["python", "train.py"]
    deps = [0]
    [[task.request]]
    resources = { "cpus" = "8", "gpus" = "1" }
    time_request = 60.0

    [[task.request]]          # second entry = OR-variant
    resources = { "cpus" = "16" }
"""

from __future__ import annotations

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: the API-compatible backport
    import tomli as tomllib
from pathlib import Path

from hyperqueue_tpu.resources.amount import amount_from_str
from hyperqueue_tpu.utils.parsing import parse_crash_limit


class JobFileError(ValueError):
    pass


def _parse_crash_limit(value) -> int:
    return parse_crash_limit(value, exc_type=JobFileError)


def _request_to_wire(requests: list[dict]) -> dict:
    variants = []
    for req in requests:
        entries = []
        for name, amount in (req.get("resources") or {}).items():
            if amount == "all":
                entries.append({"name": name, "amount": 0, "policy": "all"})
            else:
                entries.append(
                    {
                        "name": name,
                        "amount": amount_from_str(str(amount)),
                        "policy": req.get("policy", "compact"),
                    }
                )
        variants.append(
            {
                "n_nodes": int(req.get("nodes", 0)),
                "min_time": float(req.get("time_request", 0.0)),
                "entries": entries,
            }
        )
    return {"variants": variants} if variants else {}


def load_job_file(path: str | Path, submit_dir: str) -> dict:
    """Parse a TOML job file into a submit message job description."""
    with open(path, "rb") as f:
        data = tomllib.load(f)

    tasks = []
    seen_ids: set[int] = set()
    for i, t in enumerate(data.get("task", [])):
        task_id = int(t.get("id", i))
        if task_id in seen_ids:
            raise JobFileError(f"duplicate task id {task_id}")
        seen_ids.add(task_id)
        command = t.get("command")
        if not command or not isinstance(command, list):
            raise JobFileError(f"task {task_id}: 'command' array is required")
        body = {
            "cmd": [str(c) for c in command],
            "env": {str(k): str(v) for k, v in (t.get("env") or {}).items()},
            "cwd": t.get("cwd"),
            "stdout": t.get("stdout"),
            "stderr": t.get("stderr"),
            "submit_dir": submit_dir,
        }
        deps = [int(d) for d in t.get("deps", [])]
        for d in deps:
            if d not in seen_ids:
                raise JobFileError(
                    f"task {task_id} depends on {d} which is not defined above it"
                )
        tasks.append(
            {
                "id": task_id,
                "body": body,
                "request": _request_to_wire(t.get("request", [])),
                "deps": deps,
                "priority": int(t.get("priority", 0)),
                "crash_limit": _parse_crash_limit(t.get("crash_limit", 5)),
            }
        )
    if not tasks:
        raise JobFileError("job file defines no tasks")

    return {
        "name": data.get("name", Path(path).stem),
        "submit_dir": submit_dir,
        "max_fails": data.get("max_fails"),
        "tasks": tasks,
    }
