"""Terminal dashboard: cluster / jobs / autoalloc screens over event-sourced
state, live or replayed from a journal.

Reference: crates/hyperqueue/src/dashboard/ — a ratatui TUI with a root
screen switching between cluster overview (worker table + count chart),
worker detail (config + per-CPU utilization), jobs (table + task chart), and
autoalloc (queues + allocations) screens, fed by DashboardData timelines
built from the event stream; `--replay` scrubs a finished journal offline
(ui/screens/*, data/fetch.rs).

Rendering is split into pure line-producing functions (unit-testable) and a
thin curses loop (keyboard: 1/2/3 or Tab screens, j/k select, Enter worker
detail, left/right time scrub in replay, space jumps back to the end, q
quit).
"""

from __future__ import annotations

import time

from hyperqueue_tpu.client.dashboard_data import DashboardData
from hyperqueue_tpu.utils import clock

SCREENS = ("cluster", "jobs", "autoalloc")


def _bar(frac: float, width: int = 16) -> str:
    filled = int(max(0.0, min(frac, 1.0)) * width)
    return "[" + "#" * filled + "-" * (width - filled) + f"]{frac * 100:4.0f}%"


def _fmt_t(t: float) -> str:
    return time.strftime("%H:%M:%S", time.localtime(t)) if t else "-"


def _sparkline(series: list[tuple[float, float]], width: int,
               maximum: float | None = None) -> str:
    """One-line unicode chart (reference worker_count_chart / utilization
    charts condensed to a sparkline)."""
    if not series:
        return ""
    ticks = "▁▂▃▄▅▆▇█"
    values = [v for _, v in series[-max(width, 1):]]
    top = maximum if maximum is not None else max(values) or 1.0
    return "".join(
        ticks[min(int(v / top * (len(ticks) - 1)), len(ticks) - 1)]
        for v in values
    )


# ---------------------------------------------------------------------------
# screens (pure)
# ---------------------------------------------------------------------------

def render_header(data: DashboardData, screen: str, now: float,
                  mode: str, width: int = 78) -> list[str]:
    tabs = " ".join(
        f"[{i + 1}:{name.upper()}]" if name == screen else f" {i + 1}:{name} "
        for i, name in enumerate(SCREENS)
    )
    n_workers = sum(1 for w in data.workers.values() if w.is_connected)
    line = (
        f"hq dashboard ({mode})  {_fmt_t(now)}  workers={n_workers} "
        f"jobs={len(data.jobs)}  {tabs}"
    )
    return [line[:width], "=" * width]


def render_cluster(data: DashboardData, selected: int, width: int = 78,
                   height: int = 30) -> list[str]:
    lines = ["WORKERS  (Enter: detail, j/k: select)"]
    workers = sorted(data.workers.values(), key=lambda w: w.worker_id)
    count_chart = _sparkline(
        [(t, float(n)) for t, n in data.worker_series], 40
    )
    if count_chart:
        lines.append(f"  connected over time: {count_chart}")
    if not workers:
        lines.append("  (no workers seen)")
    for i, w in enumerate(workers[: height - 4]):
        cpu = w.last_hw.get("cpu_usage_percent")
        cpu_s = _bar(cpu / 100.0, 10) if cpu is not None else ""
        state = "up" if w.is_connected else f"lost({w.lost_reason[:12]})"
        marker = ">" if i == selected else " "
        lines.append(
            f" {marker}#{w.worker_id:<4} {w.hostname[:20]:<20} "
            f"{w.group[:10]:<10} {state:<18} run={len(w.running):<4} "
            f"done={w.tasks_done:<5} {cpu_s}"[:width]
        )
    return lines


def render_worker_detail(data: DashboardData, worker_id: int,
                         width: int = 78, height: int = 30) -> list[str]:
    w = data.workers.get(worker_id)
    if w is None:
        return [f"worker {worker_id}: unknown"]
    lines = [
        f"WORKER #{w.worker_id} {w.hostname}  group={w.group}  "
        f"{'connected ' + _fmt_t(w.connected_at) if w.is_connected else 'LOST ' + _fmt_t(w.lost_at) + ' ' + w.lost_reason}",
        "-" * width,
        f"running tasks: {len(w.running)}   finished here: {w.tasks_done}",
    ]
    for job_id, task_id in sorted(w.running)[:8]:
        lines.append(f"   job {job_id} task {task_id}")
    # task timeline: concurrent running tasks over time + recent spans
    # (reference dashboard worker screen timeline charts)
    series = w.running_series()
    if series:
        lines.append(
            "task timeline: " + _sparkline(series, width - 17)
        )
        recent_spans = list(w.task_history)[-6:]
        for span in reversed(recent_spans):
            end = span.ended_at or data.last_time
            lines.append(
                f"   {span.job_id}@{span.task_id:<6} {span.status:<9} "
                f"{end - span.started_at:6.1f}s"
            )
    hw = w.last_hw
    if hw:
        mem_total = hw.get("mem_total_bytes", 0)
        mem_avail = hw.get("mem_available_bytes", 0)
        if mem_total:
            used = 1.0 - mem_avail / mem_total
            lines.append(f"mem  {_bar(used)}  of {mem_total / 2**30:.1f} GiB")
        cpu = hw.get("cpu_usage_percent")
        if cpu is not None:
            lines.append(f"cpu  {_bar(cpu / 100.0)}")
        lines.append(
            "util history: "
            + _sparkline(list(w.cpu_history), width - 16, maximum=100.0)
        )
        per_core = hw.get("cpu_per_core_percent") or []
        if per_core:
            lines.append("PER-CPU UTILIZATION")
            # grid of per-core bars, 4 per row (reference cpu_util_table.rs)
            row = []
            for i, pct in enumerate(per_core):
                row.append(f"cpu{i:<3}{_bar(pct / 100.0, 8)}")
                if len(row) == 4:
                    lines.append("  " + "  ".join(row))
                    row = []
            if row:
                lines.append("  " + "  ".join(row))
        gpus = hw.get("gpus") or []
        if gpus:
            lines.append("GPUS")
            for g in gpus:
                lines.append(
                    f"  {g.get('vendor', '?'):<7}{str(g.get('id', ''))[:16]:<16}"
                    f" util {_bar(g.get('usage_percent', 0) / 100.0, 8)}"
                    f" mem {_bar(g.get('mem_usage_percent', 0) / 100.0, 8)}"
                )
    return [ln[:width] for ln in lines[:height]]


def render_jobs(data: DashboardData, selected: int, width: int = 78,
                height: int = 30) -> list[str]:
    lines = ["JOBS  (j/k: select)"]
    jobs = sorted(data.jobs.values(), key=lambda j: -j.job_id)
    if not jobs:
        lines.append("  (no jobs)")
    table_rows = max(4, (height - 4) // 2)
    for i, job in enumerate(jobs[:table_rows]):
        c = job.counters()
        marker = ">" if i == selected else " "
        lines.append(
            f" {marker}#{job.job_id:<4} {job.name[:18]:<18} "
            f"{job.status():<9} {_bar(job.progress())} "
            f"run={c['running']:<4} fail={c['failed']:<4} "
            f"open={'y' if job.is_open else 'n'}"[:width]
        )
    if jobs and 0 <= selected < len(jobs):
        job = jobs[selected]
        c = job.counters()
        lines.append("-" * width)
        lines.append(
            f"JOB #{job.job_id} {job.name}  submitted {_fmt_t(job.submitted_at)}"
            + (f"  completed {_fmt_t(job.completed_at)}" if job.completed_at
               else "")
        )
        lines.append(
            f"  tasks {job.n_tasks}: " + "  ".join(
                f"{k}={v}" for k, v in c.items() if v
            )
        )
        # running-task timeline for the job (reference job timeline
        # chart), from the data layer's span history so restarted
        # instances count like on the worker-detail screen
        series = data.job_running_series(job.job_id)
        if series:
            lines.append(
                "  running over time: " + _sparkline(series, width - 22)
            )
        recent = sorted(
            job.tasks.items(),
            key=lambda kv: -(kv[1].finished_at or kv[1].started_at),
        )[: height - len(lines) - 1]
        for task_id, tv in recent:
            dur = ""
            if tv.started_at:
                end = tv.finished_at or data.last_time
                dur = f" {end - tv.started_at:6.1f}s"
            err = f" {tv.error[:24]}" if tv.error else ""
            lines.append(
                f"   task {task_id:<6} {tv.status:<9}{dur} "
                f"on {list(tv.workers)}{err}"[:width]
            )
    return lines[:height]


def render_autoalloc(data: DashboardData, selected: int, width: int = 78,
                     height: int = 30) -> list[str]:
    lines = ["AUTOALLOC QUEUES"]
    queues = sorted(data.queues.values(), key=lambda q: q.queue_id)
    if not queues:
        lines.append("  (no allocation queues)")
    for i, q in enumerate(queues):
        by_status: dict[str, int] = {}
        for a in q.allocations.values():
            by_status[a.status] = by_status.get(a.status, 0) + 1
        marker = ">" if i == selected else " "
        stat = " ".join(f"{k}={v}" for k, v in sorted(by_status.items()))
        lines.append(
            f" {marker}queue {q.queue_id:<3} {q.manager:<6} "
            f"state={q.state:<7} allocs: {stat or '-'}"[:width]
        )
    if queues and 0 <= selected < len(queues):
        q = queues[selected]
        lines.append("-" * width)
        lines.append(f"ALLOCATIONS of queue {q.queue_id}")
        # per-allocation drill-down: member workers joined via HQ_ALLOC_ID
        # (reference dashboard allocation detail screen)
        members: dict[str, list] = {}
        for w in data.workers.values():
            if w.alloc_id:
                members.setdefault(w.alloc_id, []).append(w)
        allocs = sorted(q.allocations.values(), key=lambda a: -a.queued_at)
        for a in allocs[: max(height - len(lines) - 1, 0)]:
            span = ""
            if a.started_at:
                end = a.ended_at or data.last_time
                span = (f" waited {a.started_at - a.queued_at:5.0f}s"
                        f" ran {end - a.started_at:6.0f}s")
            lines.append(
                f"   {a.allocation_id[:20]:<20} {a.status:<9} "
                f"workers={a.worker_count} queued {_fmt_t(a.queued_at)}{span}"
            )
            for w in members.get(a.allocation_id, ()):
                state = "up" if w.is_connected else "lost"
                lines.append(
                    f"      worker #{w.worker_id} {w.hostname[:16]:<16} "
                    f"{state:<5} running={len(w.running)} "
                    f"done={w.tasks_done}"
                )
    return lines[:height]


def render_screen(data: DashboardData, ui: dict, width: int = 78,
                  height: int = 30) -> list[str]:
    """Full frame for the current UI state (pure; curses loop just blits)."""
    mode = ui.get("mode", "live")
    now = ui.get("now", data.last_time)
    lines = render_header(data, ui.get("screen", "cluster"), now, mode, width)
    if ui.get("detail_worker") is not None:
        lines += render_worker_detail(
            data, ui["detail_worker"], width, height - len(lines)
        )
    elif ui.get("screen") == "jobs":
        lines += render_jobs(data, ui.get("selected", 0), width,
                             height - len(lines))
    elif ui.get("screen") == "autoalloc":
        lines += render_autoalloc(data, ui.get("selected", 0), width,
                                  height - len(lines))
    else:
        lines += render_cluster(data, ui.get("selected", 0), width,
                                height - len(lines))
    if mode == "replay":
        lo, hi = ui.get("span", (0.0, 0.0))
        frac = 0.0 if hi <= lo else (now - lo) / (hi - lo)
        lines.append(
            f"replay {_fmt_t(lo)} {_bar(frac, width - 30)} {_fmt_t(hi)}"
        )
    return lines[:height]


# ---------------------------------------------------------------------------
# event intake
# ---------------------------------------------------------------------------

def _stream_events_into(server_dir, data: DashboardData, lock,
                        subscribed) -> None:
    """Background daemon thread: live event stream feeding the reducer.

    Subscribes FIRST and signals `subscribed`, so the snapshot seed taken
    afterwards cannot race with events emitted in between — anything in the
    gap is both in the snapshot and (re-)applied from the stream, which the
    reducer tolerates. Uses the shared blocking stream client (read_frame is
    not cancellation-safe); the thread is a daemon and dies with the
    process."""
    from hyperqueue_tpu.client.connection import stream_events

    try:
        for msg in stream_events(
            server_dir, history=False, on_subscribed=subscribed.set,
            overviews=True,
        ):
            if msg.get("op") == "event":
                with lock:
                    data.add_event(msg["record"])
    except (ConnectionError, OSError, EOFError):
        pass


# ---------------------------------------------------------------------------
# curses loop
# ---------------------------------------------------------------------------

def _curses_loop(stdscr, data: DashboardData, lock, mode: str,
                 interval: float) -> None:
    import curses

    curses.curs_set(0)
    stdscr.nodelay(True)
    ui = {"screen": "cluster", "selected": 0, "detail_worker": None,
          "mode": mode}
    view_cache: tuple[float, DashboardData] | None = None  # (now, view)

    while True:
        with lock:
            span = data.time_span()
            if mode == "replay":
                ui.setdefault("now", span[1])
                ui["span"] = span
                if ui["now"] >= span[1]:
                    view = data
                else:
                    # rebuild the prefix view only on seek, never per frame
                    if view_cache is None or view_cache[0] != ui["now"]:
                        view_cache = (ui["now"], data.at(ui["now"]))
                    view = view_cache[1]
            else:
                ui["now"] = data.last_time or clock.now()
                view = data
            # clamp selection to the current screen's list
            if ui["screen"] == "jobs":
                n_rows = len(view.jobs)
            elif ui["screen"] == "autoalloc":
                n_rows = len(view.queues)
            else:
                n_rows = len(view.workers)
            ui["selected"] = max(0, min(ui["selected"], max(n_rows - 1, 0)))
            height, width = stdscr.getmaxyx()
            lines = render_screen(
                view, ui, max(width - 1, 40), max(height - 1, 10)
            )
        stdscr.erase()
        for y, line in enumerate(lines[: height - 1]):
            try:
                stdscr.addstr(y, 0, line[: width - 1])
            except Exception:  # noqa: BLE001 - last-cell writes can raise
                pass
        stdscr.refresh()

        key = stdscr.getch()
        if key == -1:
            time.sleep(interval if mode == "live" else 0.05)
            continue
        ch = chr(key) if 0 <= key < 256 else ""
        import curses as _c

        if ch in ("q", "Q"):
            return
        if ch in ("1", "2", "3"):
            ui["screen"] = SCREENS[int(ch) - 1]
            ui["selected"] = 0
            ui["detail_worker"] = None
        elif ch == "\t":
            idx = (SCREENS.index(ui["screen"]) + 1) % len(SCREENS)
            ui["screen"] = SCREENS[idx]
            ui["selected"] = 0
            ui["detail_worker"] = None
        elif ch == "j" or key == _c.KEY_DOWN:
            ui["selected"] += 1
        elif ch == "k" or key == _c.KEY_UP:
            ui["selected"] = max(0, ui["selected"] - 1)
        elif ch == "\n" and ui["screen"] == "cluster":
            with lock:
                workers = sorted(data.workers)
            if workers:
                sel = min(ui["selected"], len(workers) - 1)
                ui["detail_worker"] = workers[sel]
        elif key == 27 or ch == "b":  # esc: back from detail
            ui["detail_worker"] = None
        elif mode == "replay" and (key in (_c.KEY_LEFT, _c.KEY_RIGHT)
                                   or ch in ("h", "l")):
            lo, hi = span
            step = max((hi - lo) / 50.0, 0.5)
            direction = 1 if (key == _c.KEY_RIGHT or ch == "l") else -1
            ui["now"] = min(max(ui.get("now", hi) + direction * step, lo), hi)
        elif ch == " " and mode == "replay":
            ui["now"] = span[1]


def run_dashboard(server_dir, interval: float = 1.0, replay=None,
                  stream=None) -> None:
    """Entry: live against a server (default) or offline journal replay.

    stream: test/plain hook — when stdout is not a tty, render one frame as
    plain text per refresh instead of entering curses.
    """
    import sys
    import threading

    lock = threading.Lock()
    if replay is not None:
        from hyperqueue_tpu.client.dashboard_data import load_journal

        data = load_journal(replay)
        mode = "replay"
        stop = None
    else:
        from hyperqueue_tpu.client.connection import ClientSession
        from hyperqueue_tpu.client.dashboard_data import seed_from_server

        # live events are reduced into state only; the raw record log is a
        # replay-mode concern and would grow without bound on a long-lived
        # dashboard (one overview event per worker per second)
        data = DashboardData(retain_events=False)
        mode = "live"
        stop = None
        subscribed = threading.Event()
        thread = threading.Thread(
            target=_stream_events_into,
            args=(server_dir, data, lock, subscribed),
            daemon=True,
        )
        thread.start()
        # subscribe-then-seed closes the lost-event window: the snapshot is
        # taken strictly after the stream subscription is on the wire
        subscribed.wait(timeout=10.0)
        with ClientSession(server_dir) as session, lock:
            seed_from_server(data, session)

    if stream is not None or not sys.stdout.isatty():
        # plain mode: print frames (used by tests and piped invocations)
        out = stream or sys.stdout
        try:
            for _ in range(3 if mode == "live" else 1):
                if mode == "live":
                    time.sleep(interval)
                with lock:
                    ui = {"screen": "cluster", "selected": 0, "mode": mode,
                          "now": data.last_time, "span": data.time_span()}
                    frame = render_screen(data, ui)
                print("\n".join(frame), file=out, flush=True)
        finally:
            if stop is not None:
                stop.set()
        return

    import curses

    try:
        curses.wrapper(_curses_loop, data, lock, mode, interval)
    finally:
        if stop is not None:
            stop.set()
