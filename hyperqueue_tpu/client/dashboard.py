"""Live terminal dashboard.

Reference: crates/hyperqueue/src/dashboard/ (ratatui TUI with cluster
overview / worker detail / job screens fed by event replay + live stream).
This implementation is a read-only ANSI terminal view over the same client
ops + live event stream; screens cycle with the interval refresh.
"""

from __future__ import annotations

import time


CSI = "\x1b["


def _clear() -> str:
    return CSI + "2J" + CSI + "H"


def _bar(frac: float, width: int = 20) -> str:
    filled = int(max(0.0, min(frac, 1.0)) * width)
    return "[" + "#" * filled + "-" * (width - filled) + f"] {frac * 100:3.0f}%"


def render(info: dict, workers: list[dict], jobs: list[dict],
           events: list[dict]) -> str:
    lines = []
    lines.append(
        f"HyperQueue-TPU server {info.get('server_uid', '')}  "
        f"uptime {time.time() - info.get('started_at', time.time()):.0f}s  "
        f"workers {info.get('n_workers', 0)}  jobs {info.get('n_jobs', 0)}"
    )
    lines.append("=" * 78)
    lines.append("WORKERS")
    if not workers:
        lines.append("  (none connected)")
    for w in workers[:16]:
        res = " ".join(
            f"{k}={v / 10_000:g}" for k, v in w.get("resources", {}).items()
        )
        hw = (w.get("overview") or {}).get("hw") or {}
        cpu = (
            f" cpu={_bar(hw['cpu_usage_percent'] / 100, 10)}"
            if "cpu_usage_percent" in hw
            else ""
        )
        lines.append(
            f"  #{w['id']:<4} {w['hostname'][:24]:<24} group={w['group']:<10}"
            f" running={w['n_running']:<4} {res}{cpu}"
        )
    if len(workers) > 16:
        lines.append(f"  ... and {len(workers) - 16} more")
    lines.append("-" * 78)
    lines.append("JOBS")
    for j in sorted(jobs, key=lambda j: -j["id"])[:12]:
        c = j["counters"]
        total = j["n_tasks"] or 1
        done = c["finished"] + c["failed"] + c["canceled"]
        lines.append(
            f"  #{j['id']:<4} {j['name'][:20]:<20} {j['status']:<9}"
            f" {_bar(done / total)} run={c['running']} fail={c['failed']}"
        )
    lines.append("-" * 78)
    lines.append("RECENT EVENTS")
    for e in events[-8:]:
        stamp = time.strftime("%H:%M:%S", time.localtime(e.get("time", 0)))
        detail = {
            k: v for k, v in e.items() if k not in ("time", "event")
        }
        lines.append(f"  {stamp} {e.get('event', '?'):<18} {detail}")
    return _clear() + "\n".join(lines)


def run_dashboard(server_dir, interval: float = 1.0) -> None:
    from hyperqueue_tpu.client.connection import ClientSession

    events: list[dict] = []
    with ClientSession(server_dir) as session:
        while True:
            info = session.request({"op": "server_info"})
            workers = session.request({"op": "worker_list"})["workers"]
            jobs = session.request({"op": "job_list"})["jobs"]
            print(render(info, workers, jobs, events), flush=True)
            time.sleep(interval)
