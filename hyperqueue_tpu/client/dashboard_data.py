"""Event-sourced dashboard state with time-travel.

Reference: crates/hyperqueue/src/dashboard/data/ — DashboardData holds
per-worker / per-job / per-allocation timelines built purely from the event
stream (live or journal replay), so the dashboard can replay a finished
journal offline and scrub through time (data/timelines/*.rs).

This mirror keeps every consumed record and rebuilds state `at(t)` by
replaying the prefix — events are cheap dict updates, and a rebuild only
happens on seek, so scrubbing a journal of tens of thousands of records is
instant in practice.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from hyperqueue_tpu.utils import clock

OVERVIEW_HISTORY = 512  # per-worker (t, cpu%) samples kept for the chart


@dataclass
class TaskSpan:
    """One task's stay on a worker (timeline chart fodder)."""

    job_id: int
    task_id: int
    started_at: float
    ended_at: float = 0.0  # 0 = still running
    status: str = "running"


@dataclass
class WorkerState:
    worker_id: int
    hostname: str = ""
    group: str = "default"
    resources: dict = field(default_factory=dict)  # name -> units
    alloc_id: str = ""  # autoalloc allocation this worker belongs to
    connected_at: float = 0.0
    lost_at: float = 0.0
    lost_reason: str = ""
    last_hw: dict = field(default_factory=dict)
    cpu_history: deque = field(default_factory=lambda: deque(maxlen=OVERVIEW_HISTORY))
    running: set = field(default_factory=set)  # (job, task)
    tasks_done: int = 0
    # recent task spans on this worker, newest last (worker-detail timeline)
    task_history: deque = field(
        default_factory=lambda: deque(maxlen=OVERVIEW_HISTORY)
    )

    def running_series(self) -> list[tuple[float, float]]:
        """(t, concurrent running tasks) step series from the span history."""
        return fold_spans(self.task_history)

    @property
    def is_connected(self) -> bool:
        return self.lost_at == 0.0


def fold_spans(spans) -> list[tuple[float, float]]:
    """TaskSpans -> (t, concurrent count) step series. Starts sort before
    ends at equal timestamps (-d) so a zero-duration span never dips the
    count negative."""
    deltas: list[tuple[float, int]] = []
    for span in spans:
        deltas.append((span.started_at, +1))
        if span.ended_at:
            deltas.append((span.ended_at, -1))
    series, n = [], 0
    for t, d in sorted(deltas, key=lambda td: (td[0], -td[1])):
        n += d
        series.append((t, float(n)))
    return series


@dataclass
class TaskView:
    status: str = "waiting"
    started_at: float = 0.0
    finished_at: float = 0.0
    workers: tuple = ()
    error: str = ""


@dataclass
class JobState:
    job_id: int
    name: str = "job"
    n_tasks: int = 0
    submitted_at: float = 0.0
    completed_at: float = 0.0
    final_status: str = ""
    is_open: bool = False
    paused: bool = False
    # reason-code -> pending-task count from the server's latest
    # DecisionRecord (job_info `pending_reasons`).  Snapshot semantics: the
    # event stream does not carry reason updates, so this reflects the
    # last seed/refresh (seed_from_server) and is cleared when the job
    # completes; replay-mode dashboards never have it.
    pending_reasons: dict = field(default_factory=dict)
    tasks: dict = field(default_factory=dict)  # task_id -> TaskView

    def pending_summary(self) -> str:
        """"30 insufficient-capacity, 7 gang-incomplete" or ""."""
        if not self.pending_reasons:
            return ""
        from hyperqueue_tpu.scheduler.decision import format_reason_counts

        return format_reason_counts(self.pending_reasons)

    def counters(self) -> dict:
        out = {"waiting": 0, "running": 0, "finished": 0, "failed": 0,
               "canceled": 0}
        seen = 0
        for t in self.tasks.values():
            out[t.status] = out.get(t.status, 0) + 1
            seen += 1
        out["waiting"] += max(self.n_tasks - seen, 0)
        return out

    def status(self) -> str:
        if self.final_status:
            return self.final_status
        c = self.counters()
        if c["running"]:
            return "running"
        return "waiting"

    def progress(self) -> float:
        if not self.n_tasks:
            return 0.0
        c = self.counters()
        return (c["finished"] + c["failed"] + c["canceled"]) / self.n_tasks


@dataclass
class AllocationView:
    allocation_id: str
    status: str = "queued"
    queued_at: float = 0.0
    started_at: float = 0.0
    ended_at: float = 0.0
    worker_count: int = 1


@dataclass
class QueueState:
    queue_id: int
    manager: str = ""
    state: str = "active"
    allocations: dict = field(default_factory=dict)


class DashboardData:
    """State reducer over the server event stream.

    retain_events=False (live mode) keeps only the reduced state: the raw
    record log exists for replay/time-travel and would grow without bound on
    a long-lived live dashboard."""

    def __init__(self, retain_events: bool = True):
        self.retain_events = retain_events
        self.workers: dict[int, WorkerState] = {}
        self.jobs: dict[int, JobState] = {}
        self.queues: dict[int, QueueState] = {}
        self.events: list[dict] = []      # consumed records (replay mode)
        # (t, n_connected); bounded — feeds a fixed-width sparkline
        self.worker_series: deque = deque(maxlen=4096)
        self.last_time: float = 0.0

    # ------------------------------------------------------------------
    def add_event(self, record: dict) -> None:
        if self.retain_events:
            self.events.append(record)
        self._apply(record)

    def _apply(self, record: dict) -> None:
        kind = record.get("event", "")
        t = float(record.get("time", 0.0))
        if t > self.last_time:
            self.last_time = t

        if kind == "worker-connected":
            wid = record.get("id", 0)
            self.workers[wid] = WorkerState(
                worker_id=wid,
                hostname=record.get("hostname", ""),
                group=record.get("group", "default"),
                resources=record.get("resources") or {},
                alloc_id=record.get("alloc_id", ""),
                connected_at=t,
            )
            self._mark_worker_count(t)
        elif kind == "worker-lost":
            w = self.workers.get(record.get("id", 0))
            if w is not None:
                w.lost_at = t
                w.lost_reason = record.get("reason", "")
                w.running.clear()
                # drop the utilization snapshot: a lost worker's last sample
                # must not render as live load while (and after) its
                # replacement reconnects under a new id
                w.last_hw = {}
            self._mark_worker_count(t)
        elif kind == "worker-overview":
            wid = record.get("id", 0)
            w = self.workers.get(wid)
            if w is None:
                # a reconnected worker re-registers under a NEW id and its
                # first overview can outrun the worker-connected record in
                # the stream; create the state so fresh utilization is
                # never attributed to the stale pre-reconnect entry
                w = self.workers[wid] = WorkerState(
                    worker_id=wid, connected_at=t
                )
                self._mark_worker_count(t)
            # prefer the structured gauge samples piggybacked by the worker
            # runtime (the metrics plane); fall back to the raw hw dict for
            # journals written before the metrics plane existed
            gauges = {
                s.get("name"): s.get("value")
                for s in record.get("metrics") or []
                if not s.get("labels")
            }
            w.last_hw = record.get("hw", {}) or {}
            cpu = gauges.get(
                "hq_worker_cpu_percent", w.last_hw.get("cpu_usage_percent")
            )
            if cpu is not None:
                w.cpu_history.append((t, float(cpu)))
        elif kind == "job-submitted":
            job_id = record.get("job", 0)
            desc = record.get("desc", {}) or {}
            job = self.jobs.get(job_id)
            if job is None:
                job = self.jobs[job_id] = JobState(job_id=job_id)
                job.submitted_at = t
                job.name = desc.get("name", "job")
            job.n_tasks += record.get("n_tasks", 0)
            job.is_open = bool(desc.get("open", job.is_open))
        elif kind == "job-opened":
            job_id = record.get("job", 0)
            job = self.jobs.setdefault(job_id, JobState(job_id=job_id))
            job.name = record.get("name", job.name)
            job.is_open = True
            if not job.submitted_at:
                job.submitted_at = t
        elif kind == "job-closed":
            job = self.jobs.get(record.get("job", 0))
            if job is not None:
                job.is_open = False
        elif kind == "job-paused":
            job = self.jobs.get(record.get("job", 0))
            if job is not None:
                job.paused = True
        elif kind == "job-resumed":
            job = self.jobs.get(record.get("job", 0))
            if job is not None:
                job.paused = False
                job.pending_reasons.pop("queue-paused", None)
        elif kind == "job-completed":
            job = self.jobs.get(record.get("job", 0))
            if job is not None:
                job.completed_at = t
                job.final_status = record.get("status", "finished")
                job.pending_reasons = {}  # nothing pending anymore
        elif kind == "task-started":
            job = self.jobs.setdefault(
                record.get("job", 0), JobState(job_id=record.get("job", 0))
            )
            task = job.tasks.setdefault(record.get("task", 0), TaskView())
            task.status = "running"
            task.started_at = t
            task.workers = tuple(record.get("workers") or ())
            for wid in task.workers:
                w = self.workers.get(wid)
                if w is not None:
                    w.running.add((job.job_id, record.get("task", 0)))
                    w.task_history.append(TaskSpan(
                        job_id=job.job_id,
                        task_id=record.get("task", 0),
                        started_at=t,
                    ))
        elif kind == "task-restarted":
            job = self.jobs.get(record.get("job", 0))
            if job is not None:
                task = job.tasks.setdefault(record.get("task", 0), TaskView())
                self._release_task(job.job_id, record.get("task", 0), task,
                                   at=t, status="restarted")
                task.status = "waiting"
        elif kind in ("task-finished", "task-failed", "task-canceled"):
            job = self.jobs.setdefault(
                record.get("job", 0), JobState(job_id=record.get("job", 0))
            )
            task = job.tasks.setdefault(record.get("task", 0), TaskView())
            self._release_task(job.job_id, record.get("task", 0), task,
                               count_done=kind == "task-finished",
                               at=t, status=kind.removeprefix("task-"))
            task.status = kind.removeprefix("task-")
            task.finished_at = t
            task.error = record.get("error", "")
        elif kind == "alloc-queue-created":
            qid = record.get("queue_id", 0)
            self.queues[qid] = QueueState(
                queue_id=qid, manager=record.get("manager", "")
            )
        elif kind == "alloc-queue-removed":
            self.queues.pop(record.get("queue_id", 0), None)
        elif kind == "alloc-queue-paused":
            q = self.queues.get(record.get("queue_id", 0))
            if q is not None:
                q.state = "paused"
        elif kind == "alloc-queued":
            q = self.queues.setdefault(
                record.get("queue_id", 0),
                QueueState(queue_id=record.get("queue_id", 0)),
            )
            aid = record.get("alloc", "")
            q.allocations[aid] = AllocationView(
                allocation_id=aid, queued_at=t,
                worker_count=int(record.get("worker_count", 1)),
            )
        elif kind in ("alloc-started", "alloc-finished", "alloc-failed"):
            q = self.queues.get(record.get("queue_id", 0))
            if q is not None:
                a = q.allocations.setdefault(
                    record.get("alloc", ""),
                    AllocationView(allocation_id=record.get("alloc", "")),
                )
                status = kind.removeprefix("alloc-")
                a.status = "running" if status == "started" else status
                if status == "started":
                    a.started_at = t
                else:
                    a.ended_at = t

    def _release_task(self, job_id, task_id, task: TaskView,
                      count_done: bool = False, at: float = 0.0,
                      status: str = "finished") -> None:
        for wid in task.workers:
            w = self.workers.get(wid)
            if w is not None:
                w.running.discard((job_id, task_id))
                if count_done:
                    w.tasks_done += 1
                for span in reversed(w.task_history):
                    if (span.job_id, span.task_id) == (job_id, task_id) \
                            and not span.ended_at:
                        span.ended_at = at or self.last_time
                        span.status = status
                        break

    def _mark_worker_count(self, t: float) -> None:
        n = sum(1 for w in self.workers.values() if w.is_connected)
        self.worker_series.append((t, n))

    def job_running_series(self, job_id: int) -> list[tuple[float, float]]:
        """(t, running tasks) series for ONE job, from the per-worker span
        history — restart-aware (every instance's span counts), so the
        jobs screen agrees with the worker-detail timelines."""
        return fold_spans(
            span
            for w in self.workers.values()
            for span in w.task_history
            if span.job_id == job_id
        )

    # ------------------------------------------------------------------
    def at(self, t: float) -> "DashboardData":
        """State as of time t (inclusive) — rebuilt by prefix replay, the
        time-travel primitive of replay mode."""
        out = DashboardData()
        for record in self.events:
            if float(record.get("time", 0.0)) <= t:
                out.add_event(record)
        return out

    def time_span(self) -> tuple[float, float]:
        if not self.events:
            return (0.0, 0.0)
        return (
            float(self.events[0].get("time", 0.0)),
            float(self.events[-1].get("time", 0.0)),
        )


def seed_from_server(data: DashboardData, session) -> None:
    """Seed live-mode state from a snapshot of the running server.

    A server without a journal has no event history, so a dashboard that
    connects late would render an empty cluster; the snapshot (worker list,
    job details, allocation queues) establishes current state and the live
    stream keeps it moving (the reference seeds the same way through its
    initial overview fetch, dashboard/data/fetch.rs)."""
    now = clock.now()
    for w in session.request({"op": "worker_list"})["workers"]:
        ws = WorkerState(
            worker_id=w["id"],
            hostname=w.get("hostname", ""),
            group=w.get("group", "default"),
            # worker_list carries raw fraction amounts; the
            # worker-connected event carries whole units — normalize so
            # config grouping agrees across both paths
            resources={
                k: v / 10_000 for k, v in (w.get("resources") or {}).items()
            },
            alloc_id=w.get("alloc_id", ""),
            connected_at=now,
        )
        overview = w.get("overview") or {}
        ws.last_hw = overview.get("hw", {}) or {}
        data.workers[w["id"]] = ws
    data.worker_series.append((now, len(data.workers)))

    jobs = session.request({"op": "job_list"})["jobs"]
    recent = sorted(jobs, key=lambda j: -j["id"])[:100]
    if recent:
        details = session.request(
            {"op": "job_info", "job_ids": [j["id"] for j in recent]}
        )["jobs"]
        for detail in details:
            job = JobState(
                job_id=detail["id"],
                name=detail.get("name", "job"),
                n_tasks=detail.get("n_tasks", 0),
                submitted_at=detail.get("submitted_at", 0.0),
                is_open=detail.get("is_open", False),
                paused=detail.get("paused", False),
                pending_reasons=dict(detail.get("pending_reasons") or {}),
            )
            status = detail.get("status", "")
            if status in ("finished", "failed", "canceled"):
                job.final_status = status
            for t in detail.get("tasks", []):
                tv = TaskView(
                    status=t.get("status", "waiting"),
                    started_at=t.get("started_at") or 0.0,
                    finished_at=t.get("finished_at") or 0.0,
                    workers=tuple(t.get("workers") or ()),
                    error=t.get("error", "") or "",
                )
                job.tasks[t["id"]] = tv
                if tv.status == "running":
                    for wid in tv.workers:
                        ws = data.workers.get(wid)
                        if ws is not None:
                            ws.running.add((job.job_id, t["id"]))
            data.jobs[job.job_id] = job

    try:
        alloc = session.request({"op": "alloc_list"})
    except Exception:  # noqa: BLE001 - autoalloc may be disabled
        alloc = {}
    for q in alloc.get("queues", []):
        qs = QueueState(
            queue_id=q.get("id", 0),
            manager=(q.get("params") or {}).get("manager", ""),
            state=q.get("state", "active"),
        )
        for a in q.get("allocations", []):
            qs.allocations[a["id"]] = AllocationView(
                allocation_id=a["id"],
                status=a.get("status", "queued"),
                queued_at=a.get("queued_at", 0.0),
                started_at=a.get("started_at", 0.0),
                ended_at=a.get("ended_at", 0.0),
                worker_count=int(a.get("worker_count", 1)),
            )
        data.queues[qs.queue_id] = qs
    data.last_time = now


def load_journal(path) -> DashboardData:
    """Build DashboardData from a journal file (offline replay mode)."""
    from hyperqueue_tpu.events.journal import Journal

    data = DashboardData()
    for record in Journal.read_all(path):
        data.add_event(record)
    return data
