"""Fleet aggregation plane (ISSUE 15): the layer that makes N federated
shards operable as ONE cluster.

PR 11 made the production topology a federation — N server shards behind
client-side routing, lease-fenced failover, cross-shard worker lending —
but every observability surface stayed per-shard: each shard its own
subscribe feed, its own metrics port, its own trace store. This module is
the fan-in:

``FleetFeed`` — one ``subscribe`` stream per live shard (via the PR 11
access-record machinery: every reconnect re-reads the shard's access
record, so a failed-over shard's successor is found automatically),
merged into a single arrival-ordered feed of frames tagged with a
``shard`` dimension. Shard death is ROUTINE here: a dead feed emits a
``shard-down`` marker and keeps re-resolving until the successor answers
(``shard-up``) — consumers render DOWN rows, they never crash.

``build_fleet_exposition`` / ``run_metrics_proxy`` — the metrics
federation endpoint (`hq fleet metrics-proxy --port P`): one scrape
fans out to every shard (the ``metrics_render`` RPC over the client
plane — no per-shard --metrics-port wiring needed), re-labels each
exposition with ``shard="K"`` and merges them
(utils/metrics.py relabel/merge helpers), plus a synthesized
``hq_federation_shard_up{shard=...}`` row per shard so a dead shard is
VISIBLE to scrapers instead of silently absent.

``export_fleet_trace`` — `hq fleet trace-export <out.json>`: one
Perfetto timeline with a row group per shard (ticks + solver rows from
each shard's flight recorder, boot/promotion instants from its journal's
``server-uid`` lineage, lending moves from the structured
``lent_to``/``lent_from`` worker events, and elasticity verdicts from
PR 13's ``alloc_events``).

Consumers: `hq top` against a federation root (client/top.py fleet
view), the metrics proxy, and — by design — a future fleet-level
autoscaler/policy loop, which reads exactly this feed.
"""

from __future__ import annotations

import logging
import queue
import threading
from pathlib import Path

from hyperqueue_tpu.utils import clock, serverdir

logger = logging.getLogger("hq.fleet")

#: how long a dead shard feed waits before re-resolving the access record
RETRY_DELAY_SECS = 1.0


def shard_count_of(root: Path) -> int:
    """The federation's shard count; raises ValueError for a classic
    (non-federated) server dir — fleet surfaces are federation-only."""
    fed = serverdir.load_federation(Path(root))
    if fed is None:
        raise ValueError(
            f"no federation at {root} (fleet commands need a federation "
            "root; against a classic server use the per-server commands)"
        )
    return int(fed["shard_count"])


class FleetFeed:
    """Multi-shard subscribe fan-in: one feed thread per shard, one
    arrival-ordered output queue.

    Emitted frames (all carry ``"shard": k``):

    - ``{"op": "shard-up", "shard": k}`` — the shard's subscribe stream
      is live (emitted on every successful (re)connect, including the
      failover successor coming up).
    - ``{"op": "shard-down", "shard": k, "error": str}`` — the feed
      died; emitted once per transition, then the thread keeps
      re-resolving the access record until the shard (or its successor)
      answers.
    - ``{"op": "sample", "shard": k, ...}`` — the shard's metric sample
      (server/bootstrap.py _build_sample, federation block included).
    - ``{"op": "events", "shard": k, "records": [...]}`` — coalesced
      lifecycle events; each record also gains ``"shard": k`` so flat
      consumers need no frame context.
    """

    def __init__(self, root: Path, sample_interval: float = 1.0,
                 filters: tuple = (), overviews: bool = False,
                 retry_delay: float = RETRY_DELAY_SECS,
                 buffer: int = 65536):
        self.root = Path(root)
        self.shard_count = shard_count_of(self.root)
        self.sample_interval = sample_interval
        self.filters = tuple(filters)
        self.overviews = overviews
        self.retry_delay = retry_delay
        # bounded: a stalled consumer drops the OLDEST frames per shard
        # rather than growing without bound (mirrors the server-side
        # per-subscriber bound; samples are periodic so staleness heals)
        self._queue: queue.Queue = queue.Queue(maxsize=max(buffer, 256))
        self.states: dict[int, str] = {
            k: "connecting" for k in range(self.shard_count)
        }
        self.last_sample: dict[int, dict | None] = {
            k: None for k in range(self.shard_count)
        }
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # per-shard cross-thread cancellers (see connection.subscribe
        # on_connected): stop() fires them to wake feeds parked in the
        # stream's blocking recv
        self._cancellers: dict[int, object] = {}

    # --- feed threads ---------------------------------------------------
    def _put(self, frame: dict) -> None:
        while not self._stop.is_set():
            try:
                self._queue.put(frame, timeout=0.2)
                return
            except queue.Full:
                # shed the oldest frame; the feed must never wedge on a
                # slow consumer (the server-side contract, client-side)
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    pass

    def _feed(self, shard_id: int) -> None:
        from hyperqueue_tpu.client import connection

        shard_dir = serverdir.shard_path(self.root, shard_id)
        while not self._stop.is_set():
            dropped = False
            try:
                for frame in connection.subscribe(
                    shard_dir,
                    filters=self.filters,
                    sample_interval=self.sample_interval,
                    overviews=self.overviews,
                    on_connected=(
                        lambda c: self._cancellers.__setitem__(shard_id, c)
                    ),
                ):
                    if self._stop.is_set():
                        return
                    op = frame.get("op")
                    if op == "sub_live":
                        self.states[shard_id] = "up"
                        self._put({"op": "shard-up", "shard": shard_id})
                        continue
                    if op == "sub_dropped":
                        dropped = True
                        break
                    if op == "sample":
                        frame = dict(frame)
                        frame["shard"] = shard_id
                        self.last_sample[shard_id] = frame
                        self._put(frame)
                        continue
                    if op == "events":
                        records = [
                            {**rec, "shard": shard_id}
                            for rec in frame.get("records") or ()
                        ]
                        self._put({
                            "op": "events", "shard": shard_id,
                            "records": records,
                        })
                error = "stream ended"
            except Exception as e:  # noqa: BLE001 - shard down is routine
                error = str(e) or type(e).__name__
            if self._stop.is_set():
                return
            if dropped:
                # this CONSUMER fell behind the server's bounded queue —
                # the shard is healthy; resubscribe without a (false)
                # DOWN transition
                continue
            if self.states[shard_id] != "down":
                self.states[shard_id] = "down"
                self.last_sample[shard_id] = None
                self._put({
                    "op": "shard-down", "shard": shard_id, "error": error,
                })
            # re-resolve from scratch after a beat: subscribe() re-reads
            # the access record per connect, so a promoted successor's
            # fresh instance dir is picked up here
            self._stop.wait(self.retry_delay)

    # --- lifecycle ------------------------------------------------------
    def start(self) -> "FleetFeed":
        for k in range(self.shard_count):
            t = threading.Thread(
                target=self._feed, args=(k,), daemon=True,
                name=f"hq-fleet-feed-{k}",
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        # wake feed threads parked in the subscribe stream's blocking
        # recv — without this the shard connections (sockets + server
        # subscriber slots) would linger until the next frame arrives
        for cancel in list(self._cancellers.values()):
            try:
                cancel()
            except Exception:  # noqa: BLE001 - loop may already be closed
                pass
        # wake any consumer parked in frames(timeout=None): the feed
        # threads stop producing after the event is set, so without a
        # sentinel a cross-thread stop() would leave the consumer
        # blocked in queue.get() forever
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass  # a full queue wakes the consumer by itself

    def __enter__(self) -> "FleetFeed":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def frames(self, timeout: float | None = None):
        """Generator over the merged feed (arrival order). With
        ``timeout``, stops yielding after that many seconds of silence
        — the scriptable/testing bound."""
        while not self._stop.is_set():
            try:
                frame = self._queue.get(timeout=timeout)
            except queue.Empty:
                return
            if frame is None:
                return  # stop() sentinel
            yield frame

    def __iter__(self):
        return self.frames()


def fleet_snapshot(root: Path, timeout: float = 10.0,
                   sample_interval: float = 0.5) -> dict[int, dict | None]:
    """One sample per shard (None for a DOWN shard): drives
    ``hq top --once`` against a federation root and the fleet e2e
    asserts. Waits until every shard has either delivered a sample or
    been marked down, bounded by ``timeout``."""
    feed = FleetFeed(root, sample_interval=sample_interval)
    deadline = clock.monotonic() + timeout
    decided: dict[int, dict | None] = {}
    with feed:
        while (
            len(decided) < feed.shard_count
            and clock.monotonic() < deadline
        ):
            try:
                frame = feed._queue.get(timeout=0.25)
            except queue.Empty:
                continue
            if frame is None:
                continue  # stop() sentinel
            op = frame.get("op")
            if op == "sample":
                decided[frame["shard"]] = frame
            elif op == "shard-down":
                decided.setdefault(frame["shard"], None)
    for k in range(feed.shard_count):
        decided.setdefault(k, None)
    return decided


# ------------------------------------------------------- metrics federation
def _scrape_shard(root: Path, shard_id: int,
                  retry_window: float = 2.0) -> str:
    """One shard's Prometheus exposition via the metrics_render RPC
    (client plane — works without any per-shard --metrics-port)."""
    from hyperqueue_tpu.client.connection import ClientSession

    shard_dir = serverdir.shard_path(Path(root), shard_id)
    with ClientSession(shard_dir, retry_window=retry_window) as session:
        return session.request({"op": "metrics_render"})["text"]


def _compose_exposition(texts: dict[str, str], up: dict[int, int]) -> str:
    """Merge per-shard expositions under the ``shard`` label and append
    the synthesized ``hq_federation_shard_up`` block. Shards' own copies
    of shard_up (a --failover-watch peer exports shard-labelled rows)
    are excluded — scrape success is the proxy's authoritative signal
    and the injected label must never collide with an existing one."""
    from hyperqueue_tpu.utils.metrics import merge_expositions

    body = merge_expositions(
        texts, exclude=frozenset({"hq_federation_shard_up"})
    ) if texts else ""
    up_lines = [
        "# HELP hq_federation_shard_up 1 when the shard answered the "
        "fleet scrape, 0 when it is down (the proxy synthesizes this "
        "row so dead shards stay visible)",
        "# TYPE hq_federation_shard_up gauge",
    ] + [
        f'hq_federation_shard_up{{shard="{k}"}} {v}'
        for k, v in sorted(up.items())
    ]
    return body + "\n".join(up_lines) + "\n"


def build_fleet_exposition(root: Path, retry_window: float = 2.0) -> str:
    """The federated scrape body: every live shard's exposition under a
    ``shard`` label, merged per metric, plus one synthesized
    ``hq_federation_shard_up{shard=...}`` sample per shard — 0 rows make
    dead shards VISIBLE to scrapers (the per-shard
    ``hq_federation_lease_age_seconds`` gauge vanishes exactly when the
    shard dies, which is when you need the signal)."""
    from concurrent.futures import ThreadPoolExecutor

    n = shard_count_of(root)

    def one(k: int) -> str | None:
        try:
            return _scrape_shard(root, k, retry_window)
        except Exception as e:  # noqa: BLE001 - DOWN shards are the point
            logger.debug("shard %d scrape failed: %s", k, e)
            return None

    # scrapes are blocking client RPCs — run them in parallel so one
    # slow/dead shard costs one retry window, not a serial sum
    with ThreadPoolExecutor(max_workers=max(n, 1)) as pool:
        results = list(pool.map(one, range(n)))
    texts = {str(k): t for k, t in enumerate(results) if t is not None}
    up = {k: int(t is not None) for k, t in enumerate(results)}
    return _compose_exposition(texts, up)


async def start_metrics_proxy(root: Path, port: int,
                              host: str = "0.0.0.0",
                              retry_window: float = 2.0):
    """Serve GET /metrics answering with the merged fleet exposition
    (build_fleet_exposition off-loop — its internal scrape fan-out is
    parallel, so one slow/dead shard costs one retry window, not a
    serial sum). Returns (asyncio server, bound port) — port 0 binds
    ephemeral."""
    import asyncio

    from ..utils.metrics import start_exposition_server

    async def fleet_text() -> str:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, build_fleet_exposition, root, retry_window
        )

    return await start_exposition_server(fleet_text, port, host)


def run_metrics_proxy(root: Path, port: int, host: str = "0.0.0.0") -> None:
    """`hq fleet metrics-proxy`: blocking serve loop (Ctrl-C to stop)."""
    import asyncio

    async def main():
        server, bound = await start_metrics_proxy(root, port, host)
        print(
            f"fleet metrics proxy on http://{host}:{bound}/metrics "
            f"({shard_count_of(root)} shard(s) at {root})",
            flush=True,
        )
        async with server:
            await server.serve_forever()

    asyncio.run(main())


# ----------------------------------------------------------- trace export
#: pid block per shard in the merged Perfetto export: shard k's
#: per-shard export pids (0 = server row, 1 = solver row) land at
#: BASE*k + pid, the fleet annotation row at BASE*k + 90
_PID_STRIDE = 100
_ANNOT_PID = 90


def _shard_trace_events(root: Path, k: int,
                        retry_window: float) -> tuple[list[dict], bool]:
    """One shard's contribution to the fleet timeline: (events, down).
    Runs on an executor thread — every shard collects concurrently, so a
    dead shard costs one retry window, not a serial sum (same contract
    as the metrics proxy)."""
    from hyperqueue_tpu.client.connection import (
        ClientError,
        ClientSession,
        stream_events,
    )

    base = _PID_STRIDE * k
    apid = base + _ANNOT_PID
    events: list[dict] = [{
        "ph": "M", "pid": apid, "tid": 0, "name": "process_name",
        "args": {"name": f"shard {k}: fleet"},
    }]
    shard_dir = serverdir.shard_path(Path(root), k)
    try:
        with ClientSession(
            shard_dir, retry_window=retry_window
        ) as session:
            per_shard = session.request({"op": "trace_export"})
            for ev in per_shard.get("traceEvents") or ():
                ev = dict(ev)
                ev["pid"] = base + int(ev.get("pid", 0))
                if (
                    ev.get("ph") == "M"
                    and ev.get("name") == "process_name"
                ):
                    ev["args"] = {
                        "name": f"shard {k}: "
                        f"{(ev.get('args') or {}).get('name', '')}"
                    }
                events.append(ev)
            stats = session.request({"op": "server_stats"})
            fed = stats.get("federation") or {}
            events.append({
                "ph": "C", "pid": apid, "tid": 0,
                "ts": clock.now() * 1e6, "name": "lease_epoch",
                "args": {"epoch": fed.get("lease_epoch") or 0},
            })
            try:
                decisions = session.request(
                    {"op": "alloc_events"}
                ).get("decisions") or ()
            except ClientError:
                decisions = ()
            for d in decisions:
                events.append({
                    "ph": "i", "pid": apid, "tid": 2, "s": "t",
                    "ts": float(d.get("time", 0.0)) * 1e6,
                    "cat": "elasticity",
                    "name": f"{d.get('verdict')} ({d.get('reason')})",
                    "args": d,
                })
    except Exception as e:  # noqa: BLE001 - a DOWN shard stays a row
        events.append({
            "ph": "i", "pid": apid, "tid": 0, "s": "p",
            "ts": clock.now() * 1e6, "cat": "fleet",
            "name": f"shard {k} DOWN ({e})",
        })
        return events, True
    # journal history: boots/promotions + lending moves (bounded by
    # compaction; replay stops at the live marker)
    boots = 0
    try:
        for frame in stream_events(shard_dir, history=True):
            if frame.get("op") == "stream_live":
                break
            rec = frame.get("record") or {}
            kind = rec.get("event")
            ts = float(rec.get("time", 0.0)) * 1e6
            if kind == "server-uid":
                boots += 1
                events.append({
                    "ph": "i", "pid": apid, "tid": 0, "s": "p",
                    "ts": ts, "cat": "fleet",
                    "name": (
                        f"boot {boots} "
                        f"[{rec.get('server_uid', '')[:8]}]"
                        + (" (restore/promotion)" if boots > 1 else "")
                    ),
                })
            elif kind == "worker-lost" and rec.get("lent_to") is not None:
                events.append({
                    "ph": "i", "pid": apid, "tid": 1, "s": "t",
                    "ts": ts, "cat": "lend",
                    "name": (
                        f"lend worker {rec.get('id')} "
                        f"→ shard {rec['lent_to']}"
                    ),
                    "args": rec,
                })
            elif kind == "worker-connected" and rec.get(
                "lent_from"
            ) is not None:
                events.append({
                    "ph": "i", "pid": apid, "tid": 1, "s": "t",
                    "ts": ts, "cat": "lend",
                    "name": (
                        f"borrow worker {rec.get('id')} "
                        f"← shard {rec['lent_from']}"
                    ),
                    "args": rec,
                })
    except Exception as e:  # noqa: BLE001 - history is best-effort
        logger.debug("shard %d history scan failed: %s", k, e)
    return events, False


def export_fleet_trace(root: Path, retry_window: float = 2.0) -> dict:
    """One Perfetto (Chrome trace-event JSON) timeline for the whole
    fleet: a row group per shard — its scheduler tick row + solver row
    (the per-shard ``trace_export`` verbatim, pid-shifted), a fleet
    annotation row carrying boot/promotion instants (journal
    ``server-uid`` lineage), structured lending moves, and elasticity
    verdicts (``alloc_events``). DOWN shards contribute a named row with
    a DOWN marker instead of failing the export; shards are collected in
    parallel so dead ones cost one retry window, not a serial sum."""
    from concurrent.futures import ThreadPoolExecutor

    n = shard_count_of(root)
    events: list[dict] = []
    down: list[int] = []
    with ThreadPoolExecutor(max_workers=min(n, 16)) as pool:
        futures = [
            pool.submit(_shard_trace_events, root, k, retry_window)
            for k in range(n)
        ]
        for k, future in enumerate(futures):
            shard_events, shard_down = future.result()
            events.extend(shard_events)
            if shard_down:
                down.append(k)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"shards": n, "down": down},
    }
