"""`hq journal report` — static HTML analytics from a journal file.

Reference: crates/hyperqueue/src/client/commands/journal/report.rs (856 LoC
HTML stats page). Generates a single self-contained HTML file: job table,
task state totals, worker connect/disconnect timeline, throughput per minute.
"""

from __future__ import annotations

import html
import json
import time
from collections import Counter
from pathlib import Path

from hyperqueue_tpu.events.journal import Journal


def build_report(journal_path: str | Path) -> str:
    jobs: dict[int, dict] = {}
    task_states = Counter()
    per_minute = Counter()
    workers: list[tuple[float, str, str]] = []
    first_ts = last_ts = None

    for rec in Journal.read_all(Path(journal_path)):
        ts = rec.get("time", 0.0)
        if first_ts is None:
            first_ts = ts
        last_ts = ts
        kind = rec.get("event", "")
        job_id = rec.get("job")
        if kind == "job-submitted":
            desc = rec.get("desc") or {}
            jobs[job_id] = {
                "name": desc.get("name", "?"),
                "n_tasks": rec.get("n_tasks", len(desc.get("tasks", []))),
                "submitted": ts,
                "completed": None,
                "status": "running",
            }
        elif kind == "job-completed" and job_id in jobs:
            jobs[job_id]["completed"] = ts
            jobs[job_id]["status"] = rec.get("status", "finished")
        elif kind.startswith("task-") and kind != "task-notify":
            task_states[kind.removeprefix("task-")] += 1
            if kind == "task-finished":
                per_minute[int(ts // 60)] += 1
        elif kind == "worker-connected":
            workers.append((ts, "connect", str(rec.get("id"))))
        elif kind == "worker-lost":
            workers.append((ts, "lost", str(rec.get("id"))))

    def fmt(ts):
        return (
            time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))
            if ts
            else "-"
        )

    rows = "".join(
        f"<tr><td>{jid}</td><td>{html.escape(j['name'])}</td>"
        f"<td>{j['n_tasks']}</td><td>{j['status']}</td>"
        f"<td>{fmt(j['submitted'])}</td><td>{fmt(j['completed'])}</td>"
        f"<td>{(j['completed'] - j['submitted']):.1f}s</td></tr>"
        if j["completed"]
        else f"<tr><td>{jid}</td><td>{html.escape(j['name'])}</td>"
        f"<td>{j['n_tasks']}</td><td>{j['status']}</td>"
        f"<td>{fmt(j['submitted'])}</td><td>-</td><td>-</td></tr>"
        for jid, j in sorted(jobs.items())
    )
    state_rows = "".join(
        f"<tr><td>{s}</td><td>{n}</td></tr>"
        for s, n in task_states.most_common()
    )
    worker_rows = "".join(
        f"<tr><td>{fmt(ts)}</td><td>{ev}</td><td>{wid}</td></tr>"
        for ts, ev, wid in workers
    )
    minutes = sorted(per_minute)
    throughput = (
        json.dumps([[m * 60, per_minute[m]] for m in minutes])
        if minutes
        else "[]"
    )
    span = (last_ts - first_ts) if (first_ts and last_ts) else 0.0

    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>HyperQueue-TPU report</title>
<style>
body {{ font-family: system-ui, sans-serif; margin: 2rem; }}
table {{ border-collapse: collapse; margin: 1rem 0; }}
td, th {{ border: 1px solid #ccc; padding: 4px 10px; text-align: left; }}
h2 {{ margin-top: 2rem; }}
.bar {{ background: #4a7; display: inline-block; height: 12px; }}
</style></head><body>
<h1>HyperQueue-TPU journal report</h1>
<p>{len(jobs)} job(s), {sum(task_states.values())} task events over
{span:.0f}s ({html.escape(str(journal_path))})</p>
<h2>Jobs</h2>
<table><tr><th>id</th><th>name</th><th>tasks</th><th>status</th>
<th>submitted</th><th>completed</th><th>makespan</th></tr>{rows}</table>
<h2>Task events</h2>
<table><tr><th>state</th><th>count</th></tr>{state_rows}</table>
<h2>Workers</h2>
<table><tr><th>time</th><th>event</th><th>worker</th></tr>{worker_rows}</table>
<h2>Throughput (finished tasks per minute)</h2>
<div id="chart"></div>
<script>
const data = {throughput};
const max = Math.max(1, ...data.map(d => d[1]));
document.getElementById("chart").innerHTML = data.map(d =>
  `<div>${{new Date(d[0] * 1000).toLocaleTimeString()}} ` +
  `<span class="bar" style="width:${{d[1] / max * 400}}px"></span> ${{d[1]}}</div>`
).join("");
</script>
</body></html>"""
