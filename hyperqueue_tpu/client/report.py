"""`hq journal report` — static HTML analytics from a journal file.

Reference: crates/hyperqueue/src/client/commands/journal/report.rs — traces
of running tasks and connected workers over time, per-job task-duration
statistics, per-worker utilization, resource summaries, and a time window
(--start-time/--end-time offsets) — rendered as one self-contained HTML
page. Charts are inline SVG (no external assets; this environment has zero
egress and the reference's page is likewise self-contained).

State reduction reuses the dashboard's event-sourced reducer
(client/dashboard_data.py) so the report and the TUI agree on semantics.
"""

from __future__ import annotations

import html
import statistics
import time
from collections import Counter
from pathlib import Path

from hyperqueue_tpu.client.dashboard_data import DashboardData
from hyperqueue_tpu.events.journal import Journal


def _fmt(ts: float) -> str:
    return (
        time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts)) if ts else "-"
    )


def _svg_line(series: list[tuple[float, float]], width=640, height=120,
              color="#36c") -> str:
    """Step-line SVG chart for a (t, value) series."""
    if not series:
        return "<p>(no data)</p>"
    t0, t1 = series[0][0], series[-1][0]
    span = max(t1 - t0, 1e-9)
    vmax = max((v for _, v in series), default=1.0) or 1.0
    points = []
    prev_y = None
    for t, v in series:
        x = (t - t0) / span * (width - 2) + 1
        y = height - 1 - (v / vmax) * (height - 20)
        if prev_y is not None:
            points.append(f"{x:.1f},{prev_y:.1f}")
        points.append(f"{x:.1f},{y:.1f}")
        prev_y = y
    return (
        f'<svg viewBox="0 0 {width} {height}" width="{width}" '
        f'height="{height}" style="background:#f8f8f8;border:1px solid #ddd">'
        f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
        f'points="{" ".join(points)}"/>'
        f'<text x="4" y="12" font-size="11">max {vmax:g}</text></svg>'
    )


def _collect(journal_path: Path, start_time: float | None,
             end_time: float | None):
    """Reduce the journal into DashboardData + report-only traces.

    start/end are OFFSETS in seconds from the first record (reference
    report.rs --start-time / --end-time)."""
    data = DashboardData()
    running_trace: list[tuple[float, float]] = []
    per_minute: Counter = Counter()
    running = 0
    first_ts = None

    for rec in Journal.read_all(journal_path):
        ts = float(rec.get("time", 0.0))
        if first_ts is None:
            first_ts = ts
        offset = ts - first_ts
        if start_time is not None and offset < start_time:
            continue
        if end_time is not None and offset > end_time:
            continue
        data.add_event(rec)
        kind = rec.get("event", "")
        if kind == "task-started":
            running += 1
            running_trace.append((ts, float(running)))
        elif kind in ("task-finished", "task-failed", "task-canceled",
                      "task-restarted"):
            if running > 0:
                running -= 1
                running_trace.append((ts, float(running)))
            if kind == "task-finished":
                per_minute[int(ts // 60)] += 1
    return data, running_trace, per_minute


def build_report(journal_path: str | Path, start_time: float | None = None,
                 end_time: float | None = None) -> str:
    data, running_trace, per_minute = _collect(
        Path(journal_path), start_time, end_time
    )
    lo, hi = data.time_span()
    span = hi - lo

    # ---- per-job rows with duration statistics -------------------------
    job_rows = []
    for job_id, job in sorted(data.jobs.items()):
        durations = [
            t.finished_at - t.started_at
            for t in job.tasks.values()
            if t.started_at and t.finished_at and t.status == "finished"
        ]
        c = job.counters()
        stats = (
            f"{min(durations):.2f} / {statistics.median(durations):.2f} / "
            f"{statistics.mean(durations):.2f} / {max(durations):.2f}"
            if durations
            else "-"
        )
        makespan = (
            f"{job.completed_at - job.submitted_at:.1f}s"
            if job.completed_at and job.submitted_at
            else "-"
        )
        job_rows.append(
            f"<tr><td>{job_id}</td><td>{html.escape(job.name)}</td>"
            f"<td>{job.n_tasks}</td><td>{job.status()}</td>"
            f"<td>{c['finished']}</td><td>{c['failed']}</td>"
            f"<td>{c['canceled']}</td>"
            f"<td>{_fmt(job.submitted_at)}</td><td>{makespan}</td>"
            f"<td>{stats}</td></tr>"
        )

    # ---- per-worker utilization (one pass over all tasks) --------------
    online_until = {
        wid: (w.lost_at if w.lost_at else hi)
        for wid, w in data.workers.items()
    }
    busy_by_worker: dict[int, float] = {}
    for job in data.jobs.values():
        for t in job.tasks.values():
            if not t.started_at:
                continue
            # a restarted task keeps started_at with finished_at=0 but is
            # no longer running; only terminal or still-running spans count
            if not t.finished_at and t.status != "running":
                continue
            for wid in t.workers:
                end = t.finished_at or online_until.get(wid, hi)
                busy_by_worker[wid] = busy_by_worker.get(wid, 0.0) + max(
                    end - t.started_at, 0.0
                )
    worker_rows = []
    for wid, w in sorted(data.workers.items()):
        online = max(online_until[wid] - w.connected_at, 0.0)
        busy = busy_by_worker.get(wid, 0.0)
        util = f"{busy / online * 100:.0f}%" if online > 0 else "-"
        worker_rows.append(
            f"<tr><td>{wid}</td><td>{html.escape(w.hostname)}</td>"
            f"<td>{html.escape(w.group)}</td>"
            f"<td>{_fmt(w.connected_at)}</td>"
            f"<td>{_fmt(w.lost_at) if w.lost_at else 'online'}"
            f"{' (' + html.escape(w.lost_reason) + ')' if w.lost_reason else ''}</td>"
            f"<td>{w.tasks_done}</td><td>{online:.0f}s</td>"
            f"<td>{busy:.0f}s</td><td>{util}</td></tr>"
        )

    # ---- failures ------------------------------------------------------
    failure_rows = []
    for job_id, job in sorted(data.jobs.items()):
        for task_id, t in sorted(job.tasks.items()):
            if t.status == "failed":
                failure_rows.append(
                    f"<tr><td>{job_id}</td><td>{task_id}</td>"
                    f"<td>{html.escape(t.error[:120])}</td></tr>"
                )
    failures = (
        "<table><tr><th>job</th><th>task</th><th>error</th></tr>"
        + "".join(failure_rows[:200])
        + "</table>"
        if failure_rows
        else "<p>none</p>"
    )

    # ---- allocation queues --------------------------------------------
    alloc_rows = []
    for qid, q in sorted(data.queues.items()):
        by_status = Counter(a.status for a in q.allocations.values())
        alloc_rows.append(
            f"<tr><td>{qid}</td><td>{html.escape(q.manager)}</td>"
            f"<td>{q.state}</td>"
            f"<td>{' '.join(f'{k}={v}' for k, v in sorted(by_status.items())) or '-'}</td></tr>"
        )

    # ---- charts --------------------------------------------------------
    worker_chart = _svg_line(
        [(t, float(n)) for t, n in data.worker_series], color="#383"
    )
    running_chart = _svg_line(running_trace)
    throughput_chart = _svg_line(
        [(m * 60.0, float(per_minute[m])) for m in sorted(per_minute)],
        color="#a44",
    )

    task_totals = Counter()
    for job in data.jobs.values():
        for status, n in job.counters().items():
            task_totals[status] += n
    totals = " ".join(f"{k}={v}" for k, v in sorted(task_totals.items()) if v)
    window = ""
    if start_time is not None or end_time is not None:
        window = (
            f" window [{start_time if start_time is not None else 0:g}s, "
            f"{end_time if end_time is not None else span:g}s]"
        )

    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>HyperQueue-TPU report</title>
<style>
body {{ font-family: system-ui, sans-serif; margin: 2rem; max-width: 72rem; }}
table {{ border-collapse: collapse; margin: 1rem 0; font-size: 0.9rem; }}
td, th {{ border: 1px solid #ccc; padding: 4px 10px; text-align: left; }}
h2 {{ margin-top: 2rem; }}
</style></head><body>
<h1>HyperQueue-TPU journal report</h1>
<p>{len(data.jobs)} job(s), {len(data.workers)} worker(s), tasks: {totals}
over {span:.0f}s{window} &mdash; {html.escape(str(journal_path))}</p>
<h2>Connected workers over time</h2>{worker_chart}
<h2>Running tasks over time</h2>{running_chart}
<h2>Throughput (finished tasks per minute)</h2>{throughput_chart}
<h2>Jobs</h2>
<table><tr><th>id</th><th>name</th><th>tasks</th><th>status</th>
<th>finished</th><th>failed</th><th>canceled</th><th>submitted</th>
<th>makespan</th><th>duration min/med/mean/max (s)</th></tr>
{"".join(job_rows)}</table>
<h2>Workers</h2>
<table><tr><th>id</th><th>hostname</th><th>group</th><th>connected</th>
<th>until</th><th>tasks done</th><th>online</th><th>busy</th><th>util</th></tr>
{"".join(worker_rows)}</table>
<h2>Failed tasks</h2>{failures}
<h2>Allocation queues</h2>
<table><tr><th>queue</th><th>manager</th><th>state</th><th>allocations</th></tr>
{"".join(alloc_rows) or "<tr><td colspan=4>none</td></tr>"}</table>
</body></html>"""
