"""`hq journal report` — static HTML analytics from a journal file.

Reference: crates/hyperqueue/src/client/commands/journal/report.rs — traces
of running tasks and connected workers over time (global and per resource
config, report.rs running_workers/ResCount), per-request-class duration
box plots and finished/failed counts with a T1..Tn legend
(durationsChart/countsChart), queue-wait distributions per class, per-job
task-duration statistics, per-worker utilization, failure breakdowns,
allocation-queue economics, and a time window (--start-time/--end-time
offsets) — rendered as one self-contained HTML page. Charts are inline
SVG (the reference uses a plotly CDN; this environment has zero egress so
the page must carry its own pixels).

State reduction reuses the dashboard's event-sourced reducer
(client/dashboard_data.py) so the report and the TUI agree on semantics.
"""

from __future__ import annotations

import html
import statistics
import time
from collections import Counter
from pathlib import Path

from hyperqueue_tpu.client.dashboard_data import DashboardData
from hyperqueue_tpu.events.journal import Journal


def _fmt(ts: float) -> str:
    return (
        time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts)) if ts else "-"
    )


def _svg_line(series: list[tuple[float, float]], width=640, height=120,
              color="#36c") -> str:
    """Step-line SVG chart for a (t, value) series."""
    if not series:
        return "<p>(no data)</p>"
    t0, t1 = series[0][0], series[-1][0]
    span = max(t1 - t0, 1e-9)
    vmax = max((v for _, v in series), default=1.0) or 1.0
    points = []
    prev_y = None
    for t, v in series:
        x = (t - t0) / span * (width - 2) + 1
        y = height - 1 - (v / vmax) * (height - 20)
        if prev_y is not None:
            points.append(f"{x:.1f},{prev_y:.1f}")
        points.append(f"{x:.1f},{y:.1f}")
        prev_y = y
    return (
        f'<svg viewBox="0 0 {width} {height}" width="{width}" '
        f'height="{height}" style="background:#f8f8f8;border:1px solid #ddd">'
        f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
        f'points="{" ".join(points)}"/>'
        f'<text x="4" y="12" font-size="11">max {vmax:g}</text></svg>'
    )


def _request_sig(request: dict | None) -> str:
    """Human request-class key (reference report.rs resource_rq_to_string:
    durations/counts are grouped per distinct ResourceRequest T1..Tn)."""
    parts = []
    for v in (request or {}).get("variants") or [{}]:
        if v.get("n_nodes"):
            parts.append(f"nodes: {v['n_nodes']}")
            continue
        entries = v.get("entries") or []
        if not entries:
            parts.append("cpus: 1")
            continue
        parts.append(", ".join(
            f"{e['name']}: all" if e.get("policy") == "all"
            else f"{e['name']}: {int(e['amount']) / 10_000:g}"
            for e in entries
        ))
    return " | ".join(parts)


def _config_key(resources: dict) -> str:
    """One string per worker resource config — the grouping key shared by
    the running-workers sections and the utilization traces (they must
    agree or traces silently vanish from the report)."""
    return ", ".join(
        f"{name}: {units:g}" for name, units in sorted(resources.items())
    ) or "(no resources)"


def _collect(journal_path: Path, start_time: float | None,
             end_time: float | None):
    """Reduce the journal into DashboardData + report-only traces.

    start/end are OFFSETS in seconds from the first record (reference
    report.rs --start-time / --end-time)."""
    data = DashboardData()
    running_trace: list[tuple[float, float]] = []
    per_minute: Counter = Counter()
    running = 0
    first_ts = None
    # request-class machinery (reference report.rs JournalStats.durations):
    # job -> shared request sig, (job, task) -> per-task sig override
    job_sig: dict[int, str] = {}
    task_sig: dict[tuple[int, int], str] = {}
    classes: dict[str, dict] = {}  # sig -> {finished: [], failed: [], waits: []}
    task_started_at: dict[tuple[int, int], float] = {}
    job_submitted_at: dict[int, float] = {}
    # open jobs accrete tasks over multiple submits: waits are measured
    # from the task's OWN submit event, not the job's first
    task_submitted_at: dict[tuple[int, int], float] = {}

    def class_of(job_id: int, task_id: int) -> dict:
        sig = task_sig.get((job_id, task_id)) or job_sig.get(job_id, "cpus: 1")
        cls = classes.get(sig)
        if cls is None:
            cls = classes[sig] = {"finished": [], "failed": [], "waits": []}
        return cls

    # normalized per-resource utilization per worker config over time
    # (reference report.rs w_utilization traces: 1.0 = fully allocated)
    job_request: dict[int, dict] = {}
    task_request: dict[tuple[int, int], dict] = {}
    cfg_of_worker: dict[int, str] = {}
    wres_of_worker: dict[int, dict] = {}
    cfg_totals: dict[str, Counter] = {}
    cfg_alloc: dict[str, Counter] = {}
    util_traces: dict[tuple[str, str], list] = {}
    # (job, task) -> [(wid, cfg, name, units)] charges to undo on release
    task_alloc: dict[tuple[int, int], list] = {}

    def _mark_util(cfg: str, name: str, t: float) -> None:
        total = cfg_totals.get(cfg, Counter())[name]
        if total > 0:
            util_traces.setdefault((cfg, name), []).append(
                (t, cfg_alloc[cfg][name] / total)
            )

    def _chosen_variant(job_id: int, tid: int, variant: int) -> dict:
        request = task_request.get((job_id, tid)) or job_request.get(job_id)
        variants = (request or {}).get("variants") or []
        if not variants:
            return {}
        return variants[min(variant, len(variants) - 1)]

    def _charge(key, wid: int, entries: list, t: float) -> None:
        cfg = cfg_of_worker.get(wid)
        if cfg is None:
            return
        for name, units in entries:
            cfg_alloc[cfg][name] += units
            task_alloc.setdefault(key, []).append((wid, cfg, name, units))
            _mark_util(cfg, name, t)

    def _release(key, t: float, only_wid: int | None = None) -> None:
        remaining = []
        for wid, cfg, name, units in task_alloc.pop(key, ()):
            if only_wid is not None and wid != only_wid:
                remaining.append((wid, cfg, name, units))
                continue
            cfg_alloc[cfg][name] -= units
            _mark_util(cfg, name, t)
        if remaining:
            task_alloc[key] = remaining

    for rec in Journal.read_all(journal_path):
        ts = float(rec.get("time", 0.0))
        if first_ts is None:
            first_ts = ts
        offset = ts - first_ts
        if start_time is not None and offset < start_time:
            continue
        if end_time is not None and offset > end_time:
            continue
        data.add_event(rec)
        kind = rec.get("event", "")
        if kind == "job-submitted":
            job_id = rec.get("job", 0)
            job_submitted_at.setdefault(job_id, ts)
            desc = rec.get("desc") or {}
            array = desc.get("array")
            if array is not None:
                job_sig[job_id] = _request_sig(array.get("request"))
                job_request[job_id] = array.get("request") or {}
                for tid in array.get("ids") or ():
                    task_submitted_at[(job_id, tid)] = ts
            for t in desc.get("tasks") or ():
                tid = t.get("id", 0)
                task_sig[(job_id, tid)] = _request_sig(t.get("request"))
                task_request[(job_id, tid)] = t.get("request") or {}
                task_submitted_at[(job_id, tid)] = ts
        elif kind == "worker-connected":
            wid = rec.get("id", 0)
            wres = rec.get("resources") or {}
            cfg = _config_key(wres)
            cfg_of_worker[wid] = cfg
            wres_of_worker[wid] = wres
            totals = cfg_totals.setdefault(cfg, Counter())
            cfg_alloc.setdefault(cfg, Counter())
            for name, units in wres.items():
                totals[name] += units
                _mark_util(cfg, name, ts)
        elif kind == "worker-lost":
            wid = rec.get("id", 0)
            # release the lost worker's task charges FIRST, then shrink
            # the pool — the other order records >100% utilization spikes
            for key in list(task_alloc):
                _release(key, ts, only_wid=wid)
            cfg = cfg_of_worker.pop(wid, None)
            if cfg is not None:
                for name, units in wres_of_worker.pop(wid, {}).items():
                    cfg_totals[cfg][name] -= units
                    _mark_util(cfg, name, ts)
        elif kind == "task-started":
            running += 1
            running_trace.append((ts, float(running)))
            key = (rec.get("job", 0), rec.get("task", 0))
            task_started_at[key] = ts
            submitted = task_submitted_at.get(
                key, job_submitted_at.get(key[0])
            )
            if submitted is not None:
                class_of(*key)["waits"].append(ts - submitted)
            workers = rec.get("workers") or ()
            if workers:
                v = _chosen_variant(*key, rec.get("variant", 0))
                if v.get("n_nodes"):
                    # a gang occupies each member worker WHOLE
                    for wid in workers:
                        pools = wres_of_worker.get(wid, {})
                        _charge(key, wid, list(pools.items()), ts)
                else:
                    wid = workers[0]
                    pools = wres_of_worker.get(wid, {})
                    entries = []
                    for e in v.get("entries") or [{"name": "cpus",
                                                   "amount": 10_000}]:
                        if e.get("policy") == "all":
                            # ALL-policy drains the worker's whole pool
                            entries.append(
                                (e["name"], pools.get(e["name"], 0.0))
                            )
                        else:
                            entries.append(
                                (e["name"], int(e["amount"]) / 10_000)
                            )
                    _charge(key, wid, entries, ts)
        elif kind in ("task-finished", "task-failed", "task-canceled",
                      "task-restarted"):
            key = (rec.get("job", 0), rec.get("task", 0))
            started = task_started_at.pop(key, None)
            # only tasks that actually STARTED decrement the running trace
            # (canceling a waiting task must not push the chart below the
            # true running count)
            if started is not None and running > 0:
                running -= 1
                running_trace.append((ts, float(running)))
            if kind == "task-finished":
                per_minute[int(ts // 60)] += 1
                if started is not None:
                    class_of(*key)["finished"].append(ts - started)
            elif kind == "task-failed" and started is not None:
                class_of(*key)["failed"].append(ts - started)
            _release(key, ts)
    return data, running_trace, per_minute, classes, util_traces


def _percentile(values: list[float], p: int) -> str:
    if not values:
        return "-"
    vs = sorted(values)
    idx = min(len(vs) - 1, int(round(p / 100 * (len(vs) - 1))))
    return f"{vs[idx]:.2f}s"


def _quartiles(values: list[float]) -> tuple[float, float, float, float, float]:
    vs = sorted(values)
    q = statistics.quantiles(vs, n=4) if len(vs) >= 2 else [vs[0]] * 3
    return (vs[0], q[0], q[1], q[2], vs[-1])


def _svg_boxes(groups: list[tuple[str, list[float]]], width=640) -> str:
    """Horizontal box plots (min, q1, median, q3, max) — the reference's
    plotly box traces (report.rs durationsChart) rendered as inline SVG."""
    groups = [(label, vs) for label, vs in groups if vs]
    if not groups:
        return "<p>(no data)</p>"
    vmax = max(max(vs) for _, vs in groups) or 1.0
    row_h, pad_l = 34, 110
    height = row_h * len(groups) + 24
    scale = (width - pad_l - 16) / vmax
    out = [
        f'<svg viewBox="0 0 {width} {height}" width="{width}" '
        f'height="{height}" style="background:#f8f8f8;border:1px solid #ddd">'
    ]
    for i, (label, vs) in enumerate(groups):
        lo, q1, med, q3, hi = _quartiles(vs)
        y = i * row_h + 20
        x = lambda v: pad_l + v * scale  # noqa: E731
        out.append(
            f'<text x="4" y="{y + 4}" font-size="11">'
            f'{html.escape(label)} (n={len(vs)})</text>'
            f'<line x1="{x(lo):.1f}" y1="{y}" x2="{x(hi):.1f}" y2="{y}" '
            f'stroke="#888"/>'
            f'<rect x="{x(q1):.1f}" y="{y - 8}" '
            f'width="{max(x(q3) - x(q1), 1):.1f}" height="16" '
            f'fill="#9cf" stroke="#36c"/>'
            f'<line x1="{x(med):.1f}" y1="{y - 8}" x2="{x(med):.1f}" '
            f'y2="{y + 8}" stroke="#036" stroke-width="2"/>'
        )
    out.append(
        f'<text x="{pad_l}" y="{height - 6}" font-size="10">0s</text>'
        f'<text x="{width - 60}" y="{height - 6}" font-size="10">'
        f'{vmax:.2f}s</text></svg>'
    )
    return "".join(out)


def build_report(journal_path: str | Path, start_time: float | None = None,
                 end_time: float | None = None) -> str:
    data, running_trace, per_minute, classes, util_traces = _collect(
        Path(journal_path), start_time, end_time
    )
    lo, hi = data.time_span()
    span = hi - lo

    # ---- per-job rows with duration statistics -------------------------
    job_rows = []
    for job_id, job in sorted(data.jobs.items()):
        durations = [
            t.finished_at - t.started_at
            for t in job.tasks.values()
            if t.started_at and t.finished_at and t.status == "finished"
        ]
        c = job.counters()
        stats = (
            f"{min(durations):.2f} / {statistics.median(durations):.2f} / "
            f"{statistics.mean(durations):.2f} / {max(durations):.2f}"
            if durations
            else "-"
        )
        makespan = (
            f"{job.completed_at - job.submitted_at:.1f}s"
            if job.completed_at and job.submitted_at
            else "-"
        )
        job_rows.append(
            f"<tr><td>{job_id}</td><td>{html.escape(job.name)}</td>"
            f"<td>{job.n_tasks}</td><td>{job.status()}</td>"
            f"<td>{c['finished']}</td><td>{c['failed']}</td>"
            f"<td>{c['canceled']}</td>"
            f"<td>{_fmt(job.submitted_at)}</td><td>{makespan}</td>"
            f"<td>{stats}</td></tr>"
        )

    # ---- per-worker utilization (one pass over all tasks) --------------
    online_until = {
        wid: (w.lost_at if w.lost_at else hi)
        for wid, w in data.workers.items()
    }
    busy_by_worker: dict[int, float] = {}
    for job in data.jobs.values():
        for t in job.tasks.values():
            if not t.started_at:
                continue
            # a restarted task keeps started_at with finished_at=0 but is
            # no longer running; only terminal or still-running spans count
            if not t.finished_at and t.status != "running":
                continue
            for wid in t.workers:
                end = t.finished_at or online_until.get(wid, hi)
                busy_by_worker[wid] = busy_by_worker.get(wid, 0.0) + max(
                    end - t.started_at, 0.0
                )
    worker_rows = []
    for wid, w in sorted(data.workers.items()):
        online = max(online_until[wid] - w.connected_at, 0.0)
        busy = busy_by_worker.get(wid, 0.0)
        util = f"{busy / online * 100:.0f}%" if online > 0 else "-"
        worker_rows.append(
            f"<tr><td>{wid}</td><td>{html.escape(w.hostname)}</td>"
            f"<td>{html.escape(w.group)}</td>"
            f"<td>{_fmt(w.connected_at)}</td>"
            f"<td>{_fmt(w.lost_at) if w.lost_at else 'online'}"
            f"{' (' + html.escape(w.lost_reason) + ')' if w.lost_reason else ''}</td>"
            f"<td>{w.tasks_done}</td><td>{online:.0f}s</td>"
            f"<td>{busy:.0f}s</td><td>{util}</td></tr>"
        )

    # ---- failures ------------------------------------------------------
    failure_rows = []
    for job_id, job in sorted(data.jobs.items()):
        for task_id, t in sorted(job.tasks.items()):
            if t.status == "failed":
                failure_rows.append(
                    f"<tr><td>{job_id}</td><td>{task_id}</td>"
                    f"<td>{html.escape(t.error[:120])}</td></tr>"
                )
    failures = (
        "<table><tr><th>job</th><th>task</th><th>error</th></tr>"
        + "".join(failure_rows[:200])
        + "</table>"
        if failure_rows
        else "<p>none</p>"
    )

    # ---- allocation-queue economics (reference report.rs tracks the
    # queued→running worker traces; here per queue: counts, manager-queue
    # latency, lifetime, and worker-seconds actually provisioned) ----------
    alloc_rows = []
    for qid, q in sorted(data.queues.items()):
        by_status = Counter(a.status for a in q.allocations.values())
        latencies = [
            a.started_at - a.queued_at
            for a in q.allocations.values()
            if a.started_at and a.queued_at
        ]
        lifetimes = [
            a.ended_at - a.started_at
            for a in q.allocations.values()
            if a.started_at and a.ended_at
        ]
        provisioned = sum(
            (a.ended_at - a.started_at) * a.worker_count
            for a in q.allocations.values()
            if a.started_at and a.ended_at
        )
        mean_latency = (
            f"{statistics.mean(latencies):.1f}s" if latencies else "-"
        )
        mean_lifetime = (
            f"{statistics.mean(lifetimes):.1f}s" if lifetimes else "-"
        )
        statuses = " ".join(
            f"{k}={v}" for k, v in sorted(by_status.items())
        ) or "-"
        alloc_rows.append(
            f"<tr><td>{qid}</td><td>{html.escape(q.manager)}</td>"
            f"<td>{q.state}</td><td>{statuses}</td>"
            f"<td>{mean_latency}</td><td>{mean_lifetime}</td>"
            f"<td>{provisioned:.0f}s</td></tr>"
        )

    # ---- charts --------------------------------------------------------
    worker_chart = _svg_line(
        [(t, float(n)) for t, n in data.worker_series], color="#383"
    )
    running_chart = _svg_line(running_trace)
    throughput_chart = _svg_line(
        [(m * 60.0, float(per_minute[m])) for m in sorted(per_minute)],
        color="#a44",
    )

    # running workers grouped by resource config (reference report.rs
    # running_workers traces keyed on ResCount)
    config_events: dict[str, list[tuple[float, int]]] = {}
    for w in data.workers.values():
        key = _config_key(w.resources)
        config_events.setdefault(key, []).append((w.connected_at, +1))
        if w.lost_at:
            config_events[key].append((w.lost_at, -1))
    config_sections = []
    for key in sorted(config_events):
        series, n = [], 0
        for t, delta in sorted(config_events[key]):
            n += delta
            series.append((t, float(n)))
        section = (
            f"<h3>workers [{html.escape(key)}]</h3>"
            + _svg_line(series, height=80, color="#383")
        )
        # normalized per-resource utilization on this config (reference
        # report.rs "<RESOURCE> alloc on <RESOURCES>" traces; 1.0 = full)
        for (cfg, name), trace in sorted(util_traces.items()):
            if cfg != key:
                continue
            section += (
                f"<h4>{html.escape(name)} utilization "
                f"(% of the config's pool)</h4>"
                + _svg_line([(t, v * 100.0) for t, v in trace],
                            height=70, color="#66a")
            )
        config_sections.append(section)

    # per-request-class duration boxes + counts + queue waits (reference
    # report.rs durationsChart/countsChart T1..Tn legend)
    class_names = {sig: f"T{i + 1}" for i, sig in enumerate(sorted(classes))}
    duration_boxes = _svg_boxes(
        [(f"{class_names[sig]} finished", cls["finished"])
         for sig, cls in sorted(classes.items())]
        + [(f"{class_names[sig]} failed", cls["failed"])
           for sig, cls in sorted(classes.items())]
    )
    wait_boxes = _svg_boxes(
        [(class_names[sig], cls["waits"])
         for sig, cls in sorted(classes.items())]
    )
    class_count_rows = "".join(
        f"<tr><td>{class_names[sig]}</td><td>{html.escape(sig)}</td>"
        f"<td>{len(cls['finished'])}</td><td>{len(cls['failed'])}</td>"
        f"<td>{_percentile(cls['waits'], 50)}</td>"
        f"<td>{_percentile(cls['waits'], 90)}</td>"
        f"<td>{_percentile(cls['waits'], 99)}</td></tr>"
        for sig, cls in sorted(classes.items())
    )

    task_totals = Counter()
    for job in data.jobs.values():
        for status, n in job.counters().items():
            task_totals[status] += n
    totals = " ".join(f"{k}={v}" for k, v in sorted(task_totals.items()) if v)
    window = ""
    if start_time is not None or end_time is not None:
        window = (
            f" window [{start_time if start_time is not None else 0:g}s, "
            f"{end_time if end_time is not None else span:g}s]"
        )

    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>HyperQueue-TPU report</title>
<style>
body {{ font-family: system-ui, sans-serif; margin: 2rem; max-width: 72rem; }}
table {{ border-collapse: collapse; margin: 1rem 0; font-size: 0.9rem; }}
td, th {{ border: 1px solid #ccc; padding: 4px 10px; text-align: left; }}
h2 {{ margin-top: 2rem; }}
</style></head><body>
<h1>HyperQueue-TPU journal report</h1>
<p>{len(data.jobs)} job(s), {len(data.workers)} worker(s), tasks: {totals}
over {span:.0f}s{window} &mdash; {html.escape(str(journal_path))}</p>
<h2>Connected workers over time</h2>{worker_chart}
<h2>Running workers by resource config</h2>{"".join(config_sections) or "<p>(no data)</p>"}
<h2>Running tasks over time</h2>{running_chart}
<h2>Throughput (finished tasks per minute)</h2>{throughput_chart}
<h2>Task classes</h2>
<table><tr><th>class</th><th>request</th><th>finished</th><th>failed</th>
<th>wait p50</th><th>wait p90</th><th>wait p99</th></tr>
{class_count_rows or "<tr><td colspan=7>none</td></tr>"}</table>
<h2>Task durations per class</h2>{duration_boxes}
<h2>Queue wait per class (submit &rarr; start)</h2>{wait_boxes}
<h2>Jobs</h2>
<table><tr><th>id</th><th>name</th><th>tasks</th><th>status</th>
<th>finished</th><th>failed</th><th>canceled</th><th>submitted</th>
<th>makespan</th><th>duration min/med/mean/max (s)</th></tr>
{"".join(job_rows)}</table>
<h2>Workers</h2>
<table><tr><th>id</th><th>hostname</th><th>group</th><th>connected</th>
<th>until</th><th>tasks done</th><th>online</th><th>busy</th><th>util</th></tr>
{"".join(worker_rows)}</table>
<h2>Failed tasks</h2>{failures}
<h2>Allocation queues</h2>
<table><tr><th>queue</th><th>manager</th><th>state</th><th>allocations</th>
<th>mean queue latency</th><th>mean lifetime</th><th>worker-seconds</th></tr>
{"".join(alloc_rows) or "<tr><td colspan=7>none</td></tr>"}</table>
</body></html>"""
