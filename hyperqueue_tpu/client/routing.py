"""Client-side job routing: ONE resolver for "which shard owns job X".

ISSUE 17 satellite: before the ownership map existed, every caller —
FederatedSession, serverdir helpers, the CLI — re-derived the modulo
``(job_id - 1) % shard_count`` inline, which is exactly the arithmetic
that goes stale the moment a job migrates. All routing now funnels
through :class:`Resolver`, which consults the federation root's
ownership log (utils/ownership.py) and falls back to the modulo only
when the log is absent or empty (a pre-migration federation — where the
modulo is still exact by construction).

The resolver CACHES its ownership-map read: clients route thousands of
requests and must not re-read a file per call. Staleness is handled by
the protocol, not by polling — a shard that no longer owns a job answers
``{"op": "error", "code": "wrong-shard", "owner": k}``, the caller
invokes :meth:`Resolver.refresh` and retries once toward the owner.
"""

from __future__ import annotations

from pathlib import Path

from hyperqueue_tpu.utils import serverdir


class Resolver:
    """Cached ownership-map routing for one federation root."""

    def __init__(self, root: Path, shard_count: int = 1):
        self.root = Path(root)
        # the descriptor count the caller booted with: the modulo
        # fallback when no ownership log exists yet
        self._fallback_count = max(int(shard_count), 1)
        self._map = None
        self._loaded = False

    def _load(self):
        if not self._loaded:
            from hyperqueue_tpu.utils.ownership import OwnershipStore

            try:
                self._map = OwnershipStore(self.root).load()
            except Exception:  # noqa: BLE001 - no log = modulo routing
                self._map = None
            self._loaded = True
        return self._map

    @property
    def shard_count(self) -> int:
        """Effective shard count — includes shards added online, which
        the boot-time descriptor snapshot a session cached may predate."""
        m = self._load()
        if m is not None:
            return max(m.shard_count, self._fallback_count)
        return self._fallback_count

    def shard_for_job(self, job_id: int) -> int:
        m = self._load()
        if m is not None:
            return m.shard_for_job(job_id)
        return serverdir.shard_for_job(job_id, self._fallback_count)

    def refresh(self) -> None:
        """Drop the cached map; the next route re-reads the log. Called
        on a wrong-shard redirect (the one signal the cache is stale)."""
        self._map = None
        self._loaded = False
