"""The `hq` command-line interface.

Reference: crates/hyperqueue/src/common/cli.rs:186-211 and bin/hq.rs:432-553 —
subcommand tree: server / worker / submit / job / task / output-log / alloc /
journal / dashboard. One binary drives everything; here it is
`python -m hyperqueue_tpu` (alias script `bin/hq`).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from pathlib import Path

from hyperqueue_tpu import __version__
from hyperqueue_tpu.client.connection import (
    ClientError,
    ClientSession,
    FederatedSession,
    open_session,
)
from hyperqueue_tpu.client.output import fail, make_output
from hyperqueue_tpu.resources.amount import amount_from_str
from hyperqueue_tpu.utils import serverdir
from hyperqueue_tpu.utils.placeholders import fill_placeholders
from hyperqueue_tpu.utils import clock


def _add_common(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--server-dir",
        default=None,
        help="server directory (default: ~/.hq-tpu-server or $HQ_SERVER_DIR)",
    )
    parser.add_argument(
        "--output-mode",
        choices=["cli", "json", "quiet"],
        default=os.environ.get("HQ_OUTPUT_MODE", "cli"),
    )


def _server_dir(args) -> Path:
    if args.server_dir:
        return Path(args.server_dir)
    return serverdir.default_server_dir()


def _session(args) -> ClientSession:
    # open_session routes through a FederatedSession when the server dir
    # is a federation root (per-shard routing + fan-out; ISSUE 11)
    try:
        return open_session(_server_dir(args))
    except FileNotFoundError as e:
        fail(str(e))


# ---------------------------------------------------------------- selectors
def parse_selector(text: str, last_id: int | None = None) -> list[int]:
    """Job/task selectors: "3", "1-5", "1,3-4", "last", "all" (reference
    transfer/messages.rs:255-285 IdSelector)."""
    if text == "all":
        return []
    if text == "last":
        if last_id is None:
            fail("no jobs submitted yet")
        return [last_id]
    ids: list[int] = []
    # underscore separators are readability sugar: 1-1000_000 == 1-1000000
    # (reference cli/shortcuts.md); steps via <start>-<end>:<step>.  Only
    # underscores BETWEEN digits are digit grouping — stripping them all
    # made typos like "_5" or "5_" silently parse
    cleaned = re.sub(r"(?<=\d)_(?=\d)", "", text)
    try:
        for part in cleaned.split(","):
            part = part.strip()
            if "-" in part:
                step = 1
                if ":" in part:
                    part, step_s = part.rsplit(":", 1)
                    step = int(step_s)
                    if step <= 0:
                        fail(f"selector step must be positive: {text!r}")
                lo, hi = part.split("-", 1)
                ids.extend(range(int(lo), int(hi) + 1, step))
            elif part:
                ids.append(int(part))
    except ValueError:
        fail(f"invalid selector: {text!r}")
    return ids


def _resolve_job_selector(session: ClientSession, text: str) -> list[int]:
    jobs = session.request({"op": "job_list"})["jobs"]
    if text == "all":
        return sorted(j["id"] for j in jobs)
    last = max((j["id"] for j in jobs), default=None)
    return parse_selector(text, last)


# ---------------------------------------------------------------- server cmds
def _setup_logging(args=None) -> None:
    """Server and worker processes log to stderr at $HQ_LOG level.

    --log-format json emits one JSON object per line with the correlation
    keys (tick/job/task/worker) the flight recorder and metrics use
    (utils/logfmt.py); plain stays the human-readable default."""
    from hyperqueue_tpu.utils.logfmt import setup_logging

    setup_logging(getattr(args, "log_format", None))


def cmd_server_start(args) -> None:
    import asyncio

    _setup_logging(args)

    # Enforce the scheduler's JAX platform: site preloads may hard-set the
    # platform (e.g. a TPU plugin overriding jax_platforms after reading
    # its own env), which both ignores JAX_PLATFORMS=cpu and makes every
    # test server contend for one real TPU chip.  jax itself is imported
    # lazily by the solver (ops/assign._load_jax) — when it has NOT been
    # preloaded, setting the env var suffices and the server start avoids
    # the multi-second jax import on the cpu path entirely.
    if args.scheduler == "tpu":
        pass  # keep the environment default (the TPU platform)
    elif (
        args.scheduler in ("cpu", "milp")
        or os.environ.get("JAX_PLATFORMS") == "cpu"
    ):
        os.environ["JAX_PLATFORMS"] = "cpu"
        if "jax" in sys.modules:
            import jax

            jax.config.update("jax_platforms", "cpu")

    from hyperqueue_tpu.server.bootstrap import Server

    profile_out = os.environ.get("HQ_PROFILE")

    # --- federation (ISSUE 11) -----------------------------------------
    shards = int(getattr(args, "shards", 0) or 0)
    standby = bool(getattr(args, "standby", False))
    if standby:
        _run_standby(args, shards)
        return
    federated = shards >= 1
    server_dir = _server_dir(args)
    federation_root = None
    journal = Path(args.journal) if args.journal else None
    shard_id = int(getattr(args, "shard_id", 0) or 0)
    if federated:
        if args.journal:
            fail(
                "--journal cannot be combined with --shards: federated "
                "shards always journal at <shard-dir>/journal.bin so a "
                "failover successor knows where to restore from"
            )
        from hyperqueue_tpu.server.federation import shard_journal_path

        federation_root = server_dir
        server_dir = serverdir.shard_path(federation_root, shard_id)
        journal = shard_journal_path(federation_root, shard_id)

    async def go():
        server = Server(
            server_dir=server_dir,
            host=args.host,
            client_port=args.client_port,
            worker_port=args.worker_port,
            disable_client_auth=args.disable_client_authentication,
            disable_worker_auth=args.disable_worker_authentication,
            scheduler=args.scheduler,
            journal_path=journal,
            idle_timeout=args.idle_timeout,
            journal_flush_period=args.journal_flush_period,
            access_file=Path(args.access_file) if args.access_file else None,
            paranoid_tick=args.paranoid_tick,
            journal_fsync=args.journal_fsync,
            journal_compact_interval=args.journal_compact_interval,
            journal_compact_threshold=args.journal_compact_threshold,
            journal_salvage=args.journal_salvage,
            heartbeat_timeout_factor=args.heartbeat_timeout_factor,
            reattach_timeout=args.reattach_timeout,
            solver_watchdog_timeout=args.solver_watchdog_timeout,
            solver_rearm_ticks=args.solver_rearm_ticks,
            metrics_port=args.metrics_port,
            metrics_host=args.metrics_host,
            flight_recorder_ticks=args.flight_recorder_ticks,
            tick_pipeline=args.tick_pipeline,
            policy_file=(
                Path(args.policy_file) if args.policy_file else None
            ),
            stall_budget=args.stall_budget,
            stall_dumps=args.stall_dumps,
            profile_hz=args.profile_hz,
            task_trace_capacity=args.task_trace_capacity,
            client_plane=args.client_plane,
            journal_plane=args.journal_plane,
            fanout_senders=args.fanout_senders,
            ingest_window=args.ingest_window,
            lazy_array_threshold=args.lazy_array_threshold,
            shard_id=shard_id,
            shard_count=shards if federated else 1,
            federation_root=federation_root,
            lease_timeout=args.lease_timeout,
            failover_watch=getattr(args, "failover_watch", False),
        )
        access = await server.start()
        if federated:
            print(
                f"| shard {shard_id}/{shards} of federation "
                f"{federation_root}",
                flush=True,
            )
        print(
            f"+-- HyperQueue TPU server [{access.server_uid}] --\n"
            f"| clients: {access.host}:{access.client_port}\n"
            f"| workers: {access.host_for_workers()}:{access.worker_port}\n"
            f"+--",
            flush=True,
        )
        await server.run_until_stopped()

    if profile_out:
        import cProfile

        cProfile.runctx("asyncio.run(go())", globals(), locals(),
                        filename=profile_out + ".server")
    else:
        asyncio.run(go())


def _run_standby(args, shards: int) -> None:
    """`hq server start --standby`: warm failover successor + federation
    coordinator. Holds no shard of its own; claims dead shards through
    the atomic lease and boots a full restored Server over each."""
    import asyncio

    from hyperqueue_tpu.server.federation import standby_main

    root = _server_dir(args)
    if shards >= 1:
        # allow the standby to come up FIRST in a deployment: it can
        # publish the federation descriptor the shards will join — and
        # GROW an existing one when restarted with a larger --shards
        # (online shard add; shrinking is rejected)
        existing = serverdir.load_federation(root)
        if existing is not None and shards != int(existing["shard_count"]):
            serverdir.grow_federation(root, shards)
        else:
            serverdir.write_federation(root, shards)
    # keep in lockstep with Server.federation_server_kwargs() — the
    # peer-promotion path clones the same subset from a live Server, and
    # a knob present in one list but not the other makes standby- and
    # peer-promoted successors behave differently for the same shard
    server_kwargs = dict(
        scheduler=args.scheduler,
        journal_fsync=args.journal_fsync,
        journal_flush_period=args.journal_flush_period,
        journal_compact_interval=args.journal_compact_interval,
        journal_compact_threshold=args.journal_compact_threshold,
        journal_salvage=args.journal_salvage,
        heartbeat_timeout_factor=args.heartbeat_timeout_factor,
        reattach_timeout=args.reattach_timeout,
        idle_timeout=args.idle_timeout,
        client_plane=args.client_plane,
        journal_plane=args.journal_plane,
        fanout_senders=args.fanout_senders,
        policy_file=(
            Path(args.policy_file) if args.policy_file else None
        ),
        lazy_array_threshold=args.lazy_array_threshold,
    )
    print(f"+-- HyperQueue TPU standby watching {root} --", flush=True)
    asyncio.run(standby_main(
        root,
        server_kwargs=server_kwargs,
        lease_timeout=args.lease_timeout,
        coordinate=not getattr(args, "no_coordinator", False),
        sample_interval=args.coordinator_interval,
        rebalance=getattr(args, "rebalance", False),
        # the standby's endpoint keeps hq_federation_shard_up and
        # failovers_total scrapeable through shard deaths (ISSUE 15)
        metrics_port=args.metrics_port,
        metrics_host=args.metrics_host,
    ))


def cmd_server_stop(args) -> None:
    with _session(args) as session:
        session.request({"op": "stop_server"})
    make_output(args.output_mode).message("server stopped")


def _print_federation_block(fed: dict | None) -> None:
    if not fed:
        return
    lease_age = fed.get("lease_age_seconds")
    print(
        f"federation: shard {fed.get('shard_id')}/{fed.get('shard_count')}"
        f" — partition {fed.get('partition')}"
        + (" [promoted successor]" if fed.get("promoted") else "")
        + (" [FENCED]" if fed.get("fenced") else "")
    )
    print(
        f"  lease: held by {fed.get('lease_owner')} "
        f"(epoch {fed.get('lease_epoch')}, renewed "
        + (f"{lease_age:.1f}s ago)" if lease_age is not None else "?)")
    )
    print(
        f"  workers: {fed.get('workers_lent', 0)} lent, "
        f"{fed.get('workers_borrowed', 0)} borrowed"
    )


def cmd_server_info(args) -> None:
    with _session(args) as session:
        info = session.request(
            {"op": "server_info", "shard": getattr(args, "shard", 0)}
        )
    info.pop("op", None)
    out = make_output(args.output_mode)
    if "shards" in info and args.output_mode == "cli":
        # --shard all: one record per shard
        for rec in info["shards"]:
            rec.pop("op", None)
            out.record(rec)
        return
    out.record(info)


def cmd_server_stats(args) -> None:
    """Per-phase tick latency breakdown + incremental-cache counters."""
    with _session(args) as session:
        stats = session.request(
            {"op": "server_stats", "shard": getattr(args, "shard", 0)}
        )
    stats.pop("op", None)
    if args.output_mode != "cli":
        make_output(args.output_mode).record(stats)
        return
    if "shards" in stats:
        # --shard all: the cross-shard summary (full per-shard telemetry
        # stays one `--shard k` away; latencies are never summed)
        for rec in stats["shards"]:
            if rec.get("error"):
                print(f"shard {rec.get('shard_id')}: DOWN ({rec['error']})")
                continue
            _print_federation_block(rec.get("federation"))
            tick = rec.get("tick") or {}
            print(f"  ticks: {tick.get('ticks', 0)}, scheduler "
                  f"{rec.get('scheduler')}")
        return
    _print_federation_block(stats.get("federation"))
    tick = stats.get("tick") or {}
    print(f"scheduler: {stats.get('scheduler')} "
          f"(backend {stats.get('solve_backend')})")
    pol = stats.get("policy")
    if pol:
        print(
            f"policy: {pol.get('source')} — "
            f"{pol.get('affinity_classes', 0)} affinity class(es), "
            f"fairness {'on' if (pol.get('fairness') or {}).get('enabled') else 'off'}, "
            f"prediction {'on' if (pol.get('prediction') or {}).get('enabled') else 'off'}, "
            f"boost range {pol.get('boost_range')}"
        )
        pred = pol.get("prediction") or {}
        if pred.get("enabled"):
            line = (
                f"  predictor: {pred.get('classes', 0)} class(es), "
                f"{pred.get('observations', 0)} observation(s), "
                f"hit rate {pred.get('hit_rate', 0.0):.2f}"
            )
            if pred.get("seeded_from"):
                line += (
                    f", seeded {pred.get('seeded_samples', 0)} sample(s) "
                    f"from {pred['seeded_from']}"
                )
            print(line)
        jain = pol.get("jain")
        if jain:
            print(
                f"  fairness jain: last {jain.get('last')}, "
                f"avg {jain.get('avg')} over {jain.get('ticks')} tick(s)"
            )
    print(f"ticks: {tick.get('ticks', 0)}")
    phase_rows = tick.get("phases") or {}
    if phase_rows:
        print(f"{'phase':<16}{'mean ms':>10}{'last ms':>10}{'max ms':>10}")
        for name, row in phase_rows.items():
            print(f"{name:<16}{row['mean_ms']:>10.3f}"
                  f"{row['last_ms']:>10.3f}{row['max_ms']:>10.3f}")
    cache = stats.get("tick_cache") or {}
    print(
        "tick cache: "
        f"{cache.get('workers', 0)} workers x "
        f"{cache.get('resources', 0)} resources, "
        f"{cache.get('full_rebuilds', 0)} full rebuilds, "
        f"{cache.get('incremental_syncs', 0)} incremental syncs "
        f"({cache.get('rows_rewritten_last', 0)} rows rewritten last tick)"
    )
    if stats.get("shape_allocations") is not None:
        print(f"solver shape allocations: {stats['shape_allocations']}")
    wd = stats.get("watchdog") or {}
    if wd:
        state = (
            "armed"
            if wd.get("armed")
            else f"DEGRADED (re-arm in {wd.get('bench_remaining', 0)} ticks)"
        )
        print(
            f"solver watchdog: {state} — "
            f"{wd.get('failures', 0)} failure(s), "
            f"{wd.get('timeouts', 0)} timeout(s), "
            f"{wd.get('degraded_ticks', 0)} degraded tick(s), "
            f"{wd.get('rearms', 0)} re-arm(s)"
        )
        if wd.get("last_error"):
            print(f"  last solver error: {wd['last_error']}")
    if stats.get("reattach_pending"):
        print(
            f"tasks awaiting worker reattach: {stats['reattach_pending']}"
        )
    jn = stats.get("journal")
    if jn:
        age = jn.get("snapshot_age_seconds")
        print(
            f"journal: {jn['journal_bytes']} bytes, "
            f"{jn['segments']} segment(s), snapshot "
            + (f"{jn['snapshot_bytes']} bytes (age {age:.0f}s)"
               if jn.get("snapshot_bytes") else "none")
        )
        lc = jn.get("last_compaction")
        if lc:
            print(
                f"  last compaction ({lc['reason']}): "
                f"kept {lc['kept_records']}, dropped "
                f"{lc['dropped_records']}, "
                f"{lc['journal_bytes_before']} -> "
                f"{lc['journal_bytes_after']} bytes "
                f"in {lc['duration_ms']} ms"
            )
        lr = jn.get("last_restore")
        if lr:
            print(
                f"  last restore: {lr['duration_s']}s via "
                + ("snapshot" if lr.get("snapshot") else "full replay")
                + f", {lr['tail_events']} tail events"
            )
    jp = stats.get("journal_plane") or {}
    if jp.get("mode") == "thread":
        print(
            f"journal plane: thread — {jp.get('commits', 0)} group "
            f"commit(s), mean batch {jp.get('mean_batch', 0)} "
            f"(max {jp.get('max_batch', 0)}), "
            f"{jp.get('depth', 0)} pending"
        )
    elif jp.get("mode"):
        print(f"journal plane: {jp['mode']} (inline group commit)")
    fo = stats.get("fanout") or {}
    if fo:
        print(
            f"fan-out plane: {fo.get('senders', 0)} sender(s), "
            f"wire backend {fo.get('wire_backend')}, "
            f"{fo.get('frames_total', 0)} frame(s) / "
            f"{fo.get('bytes_total', 0)} bytes, "
            f"{fo.get('send_stalls', 0)} send stall(s)"
        )
    lag = stats.get("lag") or {}
    if lag:
        print(f"{'loop lag':<16}{'mean ms':>10}{'last ms':>10}{'max ms':>10}")
        for plane, row in lag.items():
            print(f"{plane:<16}{row['mean_ms']:>10.3f}"
                  f"{row['last_ms']:>10.3f}{row['max_ms']:>10.3f}")
    prof = stats.get("profile") or {}
    if prof.get("enabled") and prof.get("planes"):
        print(
            f"{'cpu plane':<16}{'cpu%':>10}{'samples':>10}{'active':>10}"
            f"   ({prof.get('hz')} Hz sampler, "
            f"{prof.get('window_passes', 0)} passes windowed)"
        )
        planes = sorted(
            prof["planes"].items(), key=lambda kv: -kv[1].get("cpu", 0.0)
        )
        for plane, row in planes:
            print(
                f"{plane:<16}{row.get('cpu', 0.0) * 100:>9.1f}%"
                f"{row.get('samples', 0):>10}{row.get('active', 0):>10}"
            )
    stalls = stats.get("stalls") or {}
    if stalls.get("captured"):
        last = stalls.get("last") or {}
        print(
            f"reactor stalls: {stalls['captured']} over the "
            f"{stalls.get('budget_s')}s budget — last: "
            f"{last.get('plane')} plane held {last.get('duration_s')}s "
            f"at tick {last.get('tick')}"
            + (f" (dump: {last['dump']})" if last.get("dump") else "")
        )
    traces = stats.get("task_traces") or {}
    if traces.get("capacity"):
        print(
            f"task traces: {traces.get('tasks', 0)} of "
            f"{traces['capacity']} slots, {traces.get('spans', 0)} spans, "
            f"{traces.get('evictions', 0)} evicted"
        )
    if stats.get("subscribers"):
        print(f"event subscribers: {stats['subscribers']}")
    if stats.get("paranoid_tick"):
        print(f"paranoid-tick: every {stats['paranoid_tick']} ticks")


def cmd_server_flight_recorder(args) -> None:
    """Dump the server's flight recorder: last N per-tick DecisionRecords
    plus recent control-plane events (`hq server flight-recorder dump`)."""
    with _session(args) as session:
        dump = session.request({"op": "flight_recorder_dump"})
    dump.pop("op", None)
    if args.json or args.output_mode == "json":
        print(json.dumps(dump, default=str))
        return
    out = make_output(args.output_mode)
    ticks = dump.get("ticks") or []
    out.message(
        f"flight recorder: {len(ticks)} tick record(s) "
        f"(capacity {dump.get('capacity_ticks')}, "
        f"{dump.get('dropped_idle_ticks', 0)} idle ticks dropped)"
    )
    if ticks:
        out.table(
            ["tick", "solver", "assigned", "prefilled", "unplaced",
             "reasons"],
            [
                [
                    r["tick"],
                    (r.get("solver") or {}).get("status", "?"),
                    r["counts"].get("assigned", 0)
                    + r["counts"].get("gang_assigned", 0),
                    r["counts"].get("prefilled", 0),
                    r["counts"].get("unplaced", 0),
                    " ".join(sorted({
                        e["reason"] for e in r.get("unplaced") or ()
                    })) or "-",
                ]
                for r in ticks[-20:]
            ],
        )
    events = dump.get("events") or []
    if events:
        out.message("recent control-plane events:")
        for e in events[-15:]:
            t = time.strftime("%H:%M:%S", time.localtime(e.get("time", 0)))
            rest = {k: v for k, v in e.items() if k not in ("time", "event")}
            out.message(f"  {t} {e.get('event')} {rest}")


def cmd_server_trace_export(args) -> None:
    """Write the run's Chrome trace-event JSON (Perfetto-loadable): one
    scheduler row from the flight recorder, one row per worker with its
    task spans."""
    with _session(args) as session:
        result = session.request({"op": "trace_export"})
    events = result.get("traceEvents") or []
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(args.output, "w") as f:
        json.dump(trace, f)
    n_tasks = sum(1 for e in events if e.get("cat") == "task")
    n_ticks = sum(1 for e in events if e.get("cat") == "tick")
    make_output(args.output_mode).message(
        f"trace written to {args.output} ({n_ticks} tick slice(s), "
        f"{n_tasks} task span(s)); open it at https://ui.perfetto.dev"
    )


def cmd_job_pause(args) -> None:
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        result = session.request({"op": "job_pause", "job_ids": ids})
    paused = result["paused"]
    make_output(args.output_mode).message(
        f"paused {len(paused)} job(s): " + ", ".join(
            f"{p['job']} ({p['held']} held, "
            f"{p.get('retracted', 0)} recalled from workers)"
            for p in paused
        ) if paused else "no jobs paused"
    )


def cmd_job_resume(args) -> None:
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        result = session.request({"op": "job_resume", "job_ids": ids})
    resumed = result["resumed"]
    make_output(args.output_mode).message(
        f"resumed {len(resumed)} job(s): " + ", ".join(
            f"{r['job']} ({r['released']} task(s) released)" for r in resumed
        ) if resumed else "no paused jobs matched"
    )


def cmd_server_generate_access(args) -> None:
    client_host = args.client_host or args.host
    worker_host = args.worker_host or args.host
    if not client_host or not worker_host:
        fail("provide --host, or both --client-host and --worker-host")
    record = serverdir.generate_access(
        host=client_host,
        client_port=args.client_port,
        worker_port=args.worker_port,
        worker_host=worker_host if worker_host != client_host else None,
    )

    def write(path, role=None):
        with open(path, "w") as f:
            json.dump(record.to_json(role), f, indent=2)
        os.chmod(path, 0o600)

    write(args.access_file)
    written = [args.access_file]
    # split access: a client-only and/or worker-only record, each usable as
    # access.json by just that role (reference generate_access.rs splitting)
    if args.client_file:
        write(args.client_file, "client")
        written.append(args.client_file)
    if args.worker_file:
        write(args.worker_file, "worker")
        written.append(args.worker_file)
    make_output(args.output_mode).message(
        f"access file(s) written to {', '.join(written)}"
    )


# ---------------------------------------------------------------- worker cmds
def cmd_worker_start(args) -> None:
    import asyncio

    # without this the runtime's own reporting (reconnects, reattaches,
    # the bound --metrics-port endpoint) goes nowhere
    _setup_logging(args)

    from hyperqueue_tpu.server.worker import WorkerConfiguration
    from hyperqueue_tpu.worker.hwdetect import detect_resources
    from hyperqueue_tpu.worker.parser import parse_resource_definition
    from hyperqueue_tpu.worker.runtime import run_worker

    from hyperqueue_tpu.worker.manager import detect_manager

    # a federation root resolves to ONE shard's nested server dir: the
    # worker registers with that shard (and may later be lent to others
    # by the coordinator). --shard pins it; default spreads randomly.
    worker_dir = _server_dir(args)
    fed = serverdir.load_federation(worker_dir)
    if fed is not None:
        import random as _random

        shard = getattr(args, "shard", None)
        if shard is None:
            shard = _random.randrange(fed["shard_count"])
        if not (0 <= shard < fed["shard_count"]):
            fail(f"--shard {shard} outside 0..{fed['shard_count'] - 1}")
        worker_dir = serverdir.shard_path(worker_dir, shard)
    access = serverdir.load_access(worker_dir)
    manager_info = detect_manager(args.manager)
    descriptor = detect_resources(
        n_cpus=args.cpus,
        no_hyper_threading=args.no_hyper_threading,
    )
    if args.resource or args.coupling:
        from hyperqueue_tpu.resources.descriptor import ResourceDescriptor
        from hyperqueue_tpu.worker.parser import parse_resource_coupling

        items = {item.name: item for item in descriptor.items}
        for spec in args.resource or []:
            item = parse_resource_definition(spec)
            items[item.name] = item
        coupling = None
        if args.coupling:
            coupling = parse_resource_coupling(args.coupling)
        descriptor = ResourceDescriptor(
            items=tuple(items.values()), coupling=coupling
        )
    descriptor.validate()
    time_limit = args.time_limit or 0.0
    if not time_limit and manager_info.remaining_secs:
        time_limit = manager_info.remaining_secs
    # group defaults to the manager allocation id under PBS/Slurm so gang
    # members land on one allocation (reference worker.rs:440)
    group = args.group
    if group is None:
        group = (
            manager_info.job_id
            if manager_info.manager != "none" and manager_info.job_id
            else "default"
        )
    config = WorkerConfiguration(
        descriptor=descriptor,
        hostname=os.uname().nodename,
        group=group,
        heartbeat_secs=args.heartbeat,
        time_limit_secs=time_limit,
        # None = flag not given -> adopt the server default at registration;
        # an explicit --idle-timeout 0 means "never idle-stop"
        idle_timeout_secs=(
            args.idle_timeout if args.idle_timeout is not None else -1.0
        ),
        on_server_lost=args.on_server_lost,
        reconnect_timeout_secs=args.reconnect_timeout,
        overview_interval_secs=args.overview_interval,
        min_utilization=args.min_utilization,
        manager=manager_info.manager,
        manager_job_id=manager_info.job_id,
        alloc_id=os.environ.get("HQ_ALLOC_ID", ""),
        runner_pool=args.runner_pool,
        uplink_flush_secs=args.uplink_flush,
    )
    profile_out = os.environ.get("HQ_PROFILE")
    if not access.worker_port:
        fail("access record has no worker plane (client-only split file?)")
    coro_args = (
        access.host_for_workers(),
        access.worker_port,
        access.worker_key_bytes(),
        config,
    )
    worker_kwargs = {
        "zero_worker": args.zero_worker,
        # reconnect re-reads the access record from the server dir (a
        # restarted server publishes new ports/keys); under federation
        # this is the SHARD dir, so a failover successor's record is
        # picked up transparently
        "server_dir": worker_dir,
        "metrics_port": args.metrics_port,
        "metrics_host": args.metrics_host,
        "profile_hz": args.profile_hz,
    }
    if profile_out:
        import cProfile

        cProfile.runctx(
            "asyncio.run(run_worker(*coro_args, **worker_kwargs))",
            globals(), locals(), filename=profile_out + ".worker",
        )
    else:
        asyncio.run(run_worker(*coro_args, **worker_kwargs))


def cmd_worker_deploy_ssh(args) -> None:
    """Start a worker on each host via ssh (reference commands/worker.rs
    deploy-ssh). Requires passwordless ssh and a shared filesystem (or a
    pre-distributed access file via HQ_SERVER_DIR)."""
    import subprocess

    server_dir = str(_server_dir(args))
    with open(args.hostfile) as f:
        hosts = [line.strip() for line in f if line.strip()]
    if not hosts:
        fail("hostfile is empty")
    procs = []
    for host in hosts:
        remote_cmd = (
            f"{sys.executable} -m hyperqueue_tpu worker start "
            f"--server-dir {server_dir} --group {args.group}"
        )
        if args.cpus:
            remote_cmd += f" --cpus {args.cpus}"
        procs.append(
            subprocess.Popen(
                ["ssh", "-o", "BatchMode=yes", host, remote_cmd]
            )
        )
    out = make_output(args.output_mode)
    out.message(f"deploying workers to {len(hosts)} host(s); Ctrl-C to stop")
    try:
        for p in procs:
            p.wait()
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()


def cmd_worker_list(args) -> None:
    want_all = args.all or args.filter == "offline"
    with _session(args) as session:
        workers = session.request(
            {"op": "worker_list", "all": want_all}
        )["workers"]
    if args.filter:
        workers = [w for w in workers
                   if w.get("status", "running") == args.filter]
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(workers)
        return
    out.table(
        ["id", "hostname", "status", "group", "running", "resources"],
        [
            [
                w["id"],
                w["hostname"],
                w.get("status", "running"),
                w["group"],
                w["n_running"],
                " ".join(f"{k}={v / 10_000:g}"
                         for k, v in w["resources"].items()),
            ]
            for w in workers
        ],
    )


def cmd_worker_info(args) -> None:
    with _session(args) as session:
        worker = session.request(_worker_shard_msg(
            args, {"op": "worker_info", "worker_id": args.worker_id}
        ))["worker"]
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(worker)
        return
    if "free" in worker:  # absent on offline (past) workers
        worker["free"] = " ".join(
            f"{k}={v / 10_000:g}" for k, v in worker["free"].items() if v
        )
    if "running_tasks" in worker:
        worker["running_tasks"] = " ".join(worker["running_tasks"]) or "-"
    if "lost_at" in worker:
        worker["lost_at"] = _format_time(worker["lost_at"])
    worker.pop("descriptor", None)
    overview = worker.pop("overview", None) or {}
    if overview.get("hw"):
        worker["cpu_usage"] = f"{overview['hw'].get('cpu_usage_percent', 0)}%"
    out.record(worker)


def cmd_server_debug_dump(args) -> None:
    with _session(args) as session:
        dump = session.request({"op": "server_debug_dump"})
    dump.pop("op", None)
    print(json.dumps(dump, indent=2, default=str))


def cmd_task_notify(args) -> None:
    from hyperqueue_tpu.worker.localcomm import notify_from_task

    notify_from_task(args.payload or "")


def cmd_worker_address(args) -> None:
    with _session(args) as session:
        worker = session.request(_worker_shard_msg(
            args, {"op": "worker_info", "worker_id": args.worker_id}
        ))["worker"]
    make_output(args.output_mode).value(worker["hostname"])


def cmd_worker_wait(args) -> None:
    """Block until N workers are connected (reference `hq worker wait`)."""
    deadline = clock.now() + args.timeout
    with _session(args) as session:
        while True:
            workers = session.request({"op": "worker_list"})["workers"]
            if len(workers) >= args.count:
                make_output(args.output_mode).message(
                    f"{len(workers)} worker(s) connected"
                )
                return
            if clock.now() > deadline:
                fail(
                    f"timed out: {len(workers)}/{args.count} workers connected"
                )
            time.sleep(0.25)


def cmd_server_wait(args) -> None:
    """Block until a server is reachable in the server dir."""
    deadline = clock.now() + args.timeout
    while True:
        try:
            # retry_window=0: this loop IS the retry policy
            with open_session(_server_dir(args), retry_window=0) as session:
                session.request({"op": "server_info"})
            make_output(args.output_mode).message("server is running")
            return
        except (FileNotFoundError, ClientError, ConnectionError, OSError):
            if clock.now() > deadline:
                fail("timed out waiting for the server")
            time.sleep(0.25)


def _worker_shard_msg(args, msg: dict) -> dict:
    # worker ids are per shard under federation: thread --shard through
    # (FederatedSession requires it for worker-targeted ops)
    shard = getattr(args, "shard", None)
    if shard is not None:
        msg["shard"] = shard
    return msg


def cmd_worker_stop(args) -> None:
    with _session(args) as session:
        ids = parse_selector(args.selector)
        shards: list[int | None] = [getattr(args, "shard", None)]
        if (
            shards[0] is None
            and getattr(session, "shard_count", 0) > 1
            and not ids
        ):
            # federation `worker stop all` with no --shard: ids are per
            # shard (and collide across shards), so resolve AND stop
            # shard by shard
            shards = list(range(session.shard_count))
        stopped = []
        for shard in shards:
            msg: dict = {"op": "worker_list"}
            stop: dict = {"op": "worker_stop"}
            if getattr(args, "drain", False):
                # graceful: the server masks the worker out of the solve,
                # lets running tasks finish under the deadline, then stops
                stop["drain"] = True
                if getattr(args, "drain_timeout", None):
                    stop["timeout"] = args.drain_timeout
            if shard is not None:
                msg["shard"] = shard
                stop["shard"] = shard
            else:
                _worker_shard_msg(args, msg)
                _worker_shard_msg(args, stop)
            shard_ids = ids or [
                w["id"] for w in session.request(msg)["workers"]
            ]
            if not shard_ids:
                continue
            stop["worker_ids"] = shard_ids
            stopped.extend(session.request(stop)["stopped"])
    verb = "draining" if getattr(args, "drain", False) else "stopped"
    make_output(args.output_mode).message(f"{verb} workers: {stopped}")


# ---------------------------------------------------------------- submit
def _parse_env(pairs: list[str]) -> dict:
    env = {}
    for pair in pairs or []:
        key, sep, value = pair.partition("=")
        if not sep:
            fail(f"invalid --env {pair!r}, expected KEY=VALUE")
        env[key] = value
    return env


def _build_request(args) -> dict:
    entries = []
    if args.cpus:
        if str(args.cpus) == "all":
            entries.append({"name": "cpus", "amount": 0, "policy": "all"})
        else:
            entries.append(
                {"name": "cpus", "amount": amount_from_str(args.cpus),
                 "policy": "compact"}
            )
    for spec in args.resource_request or []:
        name, sep, amount = spec.partition("=")
        if not sep:
            fail(f"invalid --resource {spec!r}, expected name=amount")
        policy = "compact"
        if amount == "all":
            entries.append({"name": name, "amount": 0, "policy": "all"})
            continue
        entries.append(
            {"name": name, "amount": amount_from_str(amount), "policy": policy}
        )
    variant = {
        "n_nodes": args.nodes or 0,
        "min_time": args.time_request or 0.0,
        "entries": entries,
    }
    if getattr(args, "weight", None):
        variant["weight"] = args.weight
    return {"variants": [variant]}


def _parse_weight(text: str) -> float:
    value = float(text)
    if value <= 0:
        raise argparse.ArgumentTypeError(
            "resource weight has to be a positive number"
        )
    return value


def _parse_min_utilization(text: str) -> float:
    value = float(text)
    if not 0.0 <= value <= 1.0:
        raise argparse.ArgumentTypeError(
            "min utilization has to be in range 0.0-1.0"
        )
    return value


_DURATION_UNITS = {
    "ms": 0.001, "s": 1.0, "sec": 1.0, "secs": 1.0, "second": 1.0,
    "seconds": 1.0, "m": 60.0, "min": 60.0, "mins": 60.0, "minute": 60.0,
    "minutes": 60.0, "h": 3600.0, "hour": 3600.0, "hours": 3600.0,
    "hrs": 3600.0, "d": 86400.0, "day": 86400.0, "days": 86400.0,
}


def _parse_duration(text: str) -> float:
    """Seconds from `90`, `1.5h`, `10min`, `1h30m`, or `HH:MM:SS`
    (reference parse_hms_or_human_time, common/parser2.rs)."""
    text = text.strip()
    try:
        value = float(text)  # plain seconds
    except ValueError:
        pass
    else:
        if value < 0:
            raise argparse.ArgumentTypeError(
                f"duration must be non-negative, got {text!r}"
            )
        return value
    if ":" in text:  # [HH:]MM:SS
        parts = text.split(":")
        if len(parts) in (2, 3) and all(p.isdigit() for p in parts):
            secs = 0.0
            for p in parts:
                secs = secs * 60 + int(p)
            return secs
        raise argparse.ArgumentTypeError(f"invalid duration {text!r}")
    import re

    matches = re.findall(r"(\d+(?:\.\d+)?)\s*([a-zA-Z]+)", text)
    if not matches or "".join(n + u for n, u in matches) != text.replace(" ", ""):
        raise argparse.ArgumentTypeError(
            f"invalid duration {text!r} (expected e.g. 30, 10min, 1h30m, 01:30:00)"
        )
    secs = 0.0
    for number, unit in matches:
        scale = _DURATION_UNITS.get(unit.lower())
        if scale is None:
            raise argparse.ArgumentTypeError(
                f"unknown duration unit {unit!r} in {text!r}"
            )
        secs += float(number) * scale
    return secs


def _parse_crash_limit(text: str) -> int:
    """Positive integer, `never-restart` (-1 on the wire: fails on any
    worker loss while running, even clean stops — reference reactor.rs:166),
    or `unlimited` (0). Shared encoding: utils/parsing.py."""
    from hyperqueue_tpu.utils.parsing import parse_crash_limit

    return parse_crash_limit(text, exc_type=argparse.ArgumentTypeError)


class _NotifyRunner:
    """Streams task-notify events in a daemon thread and runs the
    `--on-notify` program serially for events of the submitted job
    (reference JobSubmitOpts::on_notify). Subscription is acknowledged by
    the server's `stream_live` frame BEFORE the submit happens on the other
    connection, so no notify of the submitted job can precede the listener.
    Records arriving before the job id is known are buffered and replayed
    via flush() once `set_job_id` runs."""

    def __init__(self, args):
        import threading

        self._args = args
        self._job_id = None
        self.stop = False
        self._buffered: list[dict] = []
        self._lock = threading.Lock()
        self._subscribed = threading.Event()
        threading.Thread(target=self._loop, daemon=True).start()
        if not self._subscribed.wait(timeout=10):
            print("--on-notify: event stream subscription timed out; "
                  "notifications disabled", file=sys.stderr)

    def set_job_id(self, job_id: int) -> None:
        with self._lock:
            self._job_id = job_id
            self._flush_locked()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        while self._buffered:
            self._run(self._buffered.pop(0))

    def _run(self, rec: dict) -> None:
        import subprocess

        if rec.get("job") != self._job_id:
            return
        try:
            subprocess.run([self._args.on_notify, json.dumps(rec)],
                           check=False)
        except OSError as e:
            print(f"--on-notify program failed: {e}", file=sys.stderr)

    def _loop(self):
        from hyperqueue_tpu.client.connection import stream_events

        try:
            for msg in stream_events(
                _server_dir(self._args), filters=("task-notify",)
            ):
                if self.stop:
                    break
                op = msg.get("op")
                if op == "stream_live":
                    self._subscribed.set()
                    continue
                if op != "event":
                    continue
                with self._lock:
                    if self._job_id is None:
                        self._buffered.append(msg["record"])
                    else:
                        self._flush_locked()
                        self._run(msg["record"])
        except Exception as e:
            if not self._subscribed.is_set():
                print(f"--on-notify: event stream unavailable ({e}); "
                      "notifications disabled", file=sys.stderr)
                self._subscribed.set()  # unblock the submit
            # post-subscription errors: stream teardown at process exit


_KNOWN_PLACEHOLDERS = {"JOB_ID", "TASK_ID", "INSTANCE_ID", "SUBMIT_DIR",
                       "SERVER_UID", "CWD"}
# a stream dir is shared by the whole job (the format multiplexes tasks),
# so only job-scope placeholders resolve there
_STREAM_PLACEHOLDERS = {"JOB_ID", "SUBMIT_DIR", "SERVER_UID"}


def _check_submit_placeholders(args, is_array: bool) -> None:
    """Submit-time placeholder validation (reference
    tests/test_placeholders.py): a recursive %{CWD} in --cwd is an error;
    unknown placeholders and an array job whose output paths lack
    %{TASK_ID} get loud warnings (the tasks would clobber one file).
    Warnings go to stderr so --output-mode quiet/json stdout stays
    machine-parseable.

    A TASK-scope placeholder (%{TASK_ID}, %{INSTANCE_ID}, %{CWD}) in a
    --stream path is a hard error: the stream dir is shared by the whole
    job, the worker only expands job-scope placeholders there, and the
    unexpanded text would become a literal directory name shared by every
    task (reference behavior; regression-pinned in
    tests/test_tick_cache.py)."""
    pattern = re.compile(r"%\{([^}]*)\}")
    if args.cwd and "%{CWD}" in args.cwd:
        fail("--cwd cannot contain the %{CWD} placeholder")
    if args.stream:
        task_scope = sorted(
            set(pattern.findall(args.stream))
            & (_KNOWN_PLACEHOLDERS - _STREAM_PLACEHOLDERS)
        )
        if task_scope:
            plural = "s" if len(task_scope) > 1 else ""
            fail(
                f"--stream path cannot contain task-scope placeholder"
                f"{plural} {', '.join('%{' + p + '}' for p in task_scope)}:"
                f" the stream directory is shared by the whole job"
            )
    for label, value, known in (
        ("stdout", args.stdout, _KNOWN_PLACEHOLDERS),
        ("stderr", args.stderr, _KNOWN_PLACEHOLDERS),
        ("working directory", args.cwd, _KNOWN_PLACEHOLDERS),
        ("stream log", args.stream, _STREAM_PLACEHOLDERS),
    ):
        if not value:
            continue
        unknown = sorted(set(pattern.findall(value)) - known)
        if unknown:
            plural = "s" if len(unknown) > 1 else ""
            print(f"WARNING: unknown placeholder{plural} "
                  f"{', '.join(unknown)} in {label} path", file=sys.stderr)
    if is_array:
        for channel in ("stdout", "stderr"):
            value = getattr(args, channel)
            if value is None:
                continue  # the default path carries %{TASK_ID}
            covered = "%{TASK_ID}" in value or (
                "%{CWD}" in value and args.cwd and "%{TASK_ID}" in args.cwd
            )
            if not covered:
                print(f"WARNING: array job, but the {channel} path has no "
                      f"%{{TASK_ID}} placeholder — tasks will overwrite "
                      f"each other's output. Consider adding %{{TASK_ID}} "
                      f"to --{channel}.", file=sys.stderr)


def _subset_array_entries(
    task_ids: list[int] | None, entry_values: list[str]
) -> tuple[list[int], list[str]]:
    """--array selects a SUBSET of --each-line/--from-json entries: task
    id = entry index (0-based).  Ids beyond the entry count are removed —
    loudly, and an empty intersection is an error (a typo'd selector must
    not submit zero tasks silently; reference docs/jobs/arrays.md
    "Combining --each-line/--from-json with --array").  `--array all`
    parses to [] = every id, i.e. every entry."""
    if not task_ids:
        return list(range(len(entry_values))), entry_values
    ids = [i for i in task_ids if 0 <= i < len(entry_values)]
    dropped = len(task_ids) - len(ids)
    if not ids:
        fail(
            f"--array selects no tasks: all {len(task_ids)} ids fall "
            f"outside the {len(entry_values)} provided entries "
            f"(valid ids: 0-{len(entry_values) - 1})"
        )
    if dropped:
        print(
            f"WARNING: {dropped} --array id(s) outside the "
            f"{len(entry_values)} provided entries were dropped",
            file=sys.stderr,
        )
    return ids, [entry_values[i] for i in ids]


def _iter_array_chunks(array: dict, chunk_size: int):
    """Split one wire array description into submit chunks; contiguous id
    runs travel as compact "id_range" [start, stop) — O(1) per chunk on
    the wire and in the server's lazy store."""
    ids = array["ids"]
    entries = array.get("entries")
    base = {k: v for k, v in array.items() if k not in ("ids", "entries")}
    for start in range(0, len(ids), chunk_size):
        part = ids[start:start + chunk_size]
        chunk = dict(base)
        if part[-1] - part[0] + 1 == len(part):
            chunk["id_range"] = [part[0], part[0] + len(part)]
        else:
            chunk["ids"] = part
        if entries is not None:
            chunk["entries"] = entries[start:start + chunk_size]
        yield chunk


def _iter_stdin_chunks(array_base: dict, chunk_size: int, lines=None):
    """`hq submit --from-stdin`: one task per stdin line (entry in
    HQ_ENTRY), yielded in chunks WITHOUT ever materializing the whole
    task list client-side — memory is bounded by chunk_size plus the
    in-flight window, no matter how many lines arrive."""
    source = lines if lines is not None else sys.stdin
    next_id = 0
    entries: list[str] = []
    for line in source:
        entries.append(line.rstrip("\n"))
        if len(entries) >= chunk_size:
            chunk = dict(array_base)
            chunk["id_range"] = [next_id, next_id + len(entries)]
            chunk["entries"] = entries
            next_id += len(entries)
            entries = []
            yield chunk
    if entries:
        chunk = dict(array_base)
        chunk["id_range"] = [next_id, next_id + len(entries)]
        chunk["entries"] = entries
        yield chunk


def cmd_submit(args) -> None:
    if not args.command:
        fail("no command given")
    if args.from_stdin and (
        args.array or args.each_line or args.from_json or args.stdin
    ):
        fail("--from-stdin cannot be combined with --array/--each-line/"
             "--from-json/--stdin")
    submit_dir = os.getcwd()
    body_base = {
        "cmd": list(args.command),
        "env": _parse_env(args.env),
        "cwd": args.cwd,
        "stdout": args.stdout,
        "stderr": args.stderr,
        "submit_dir": submit_dir,
    }
    if args.stream:
        body_base["stream"] = os.path.abspath(args.stream)
    if args.pin:
        body_base["pin"] = args.pin
    if args.task_dir:
        body_base["task_dir"] = True
    if args.time_limit:
        body_base["time_limit"] = args.time_limit
    if args.stdin:
        body_base["stdin"] = (
            getattr(args, "_stdin_data", None) or sys.stdin.buffer.read()
        )
    request = _build_request(args)

    task_ids: list[int] | None = None
    entry_values: list[str] | None = None
    if args.array:
        task_ids = parse_selector(args.array)
    _check_submit_placeholders(
        args,
        is_array=args.array is not None or args.each_line is not None
        or args.from_json is not None or args.from_stdin,
    )
    if args.each_line:
        with open(args.each_line) as f:
            entry_values = [line.rstrip("\n") for line in f]
    elif args.from_json:
        with open(args.from_json) as f:
            data = json.load(f)
        if not isinstance(data, list):
            fail("--from-json expects a JSON array")
        entry_values = [json.dumps(v) for v in data]

    # arrays go compressed: one shared body/request + ids (+ entries) — a
    # million-task array must not serialize a million bodies
    job_desc = {
        "name": args.name or Path(args.command[0]).name,
        "submit_dir": submit_dir,
        "max_fails": args.max_fails,
    }
    if entry_values is not None:
        ids, entry_values = _subset_array_entries(task_ids, entry_values)
        job_desc["array"] = {
            "ids": ids, "entries": entry_values, "body": body_base,
            "request": request, "priority": args.priority,
            "crash_limit": args.crash_limit,
        }
    elif task_ids is not None:
        job_desc["array"] = {
            "ids": task_ids, "body": body_base, "request": request,
            "priority": args.priority, "crash_limit": args.crash_limit,
        }
    else:
        job_desc["tasks"] = [
            {"id": 0, "body": body_base, "request": request,
             "priority": args.priority, "crash_limit": args.crash_limit}
        ]
    if args.job is not None:
        job_desc["job_id"] = args.job

    notify_runner = None
    if args.on_notify and (args.wait or args.progress):
        notify_runner = _NotifyRunner(args)
    # streaming chunked ingest (ISSUE 10): stdin feeds, and arrays larger
    # than --chunk-size, go through the pipelined submit_chunk plane
    chunks_iter = None
    chunk_size = max(args.chunk_size, 1) if args.chunk_size else 0
    if args.from_stdin:
        array_base = {
            "body": body_base, "request": request,
            "priority": args.priority, "crash_limit": args.crash_limit,
        }
        chunks_iter = _iter_stdin_chunks(array_base, chunk_size or 16384)
    elif (
        chunk_size
        and job_desc.get("array")
        and len(job_desc["array"].get("ids") or ()) > chunk_size
    ):
        chunks_iter = _iter_array_chunks(job_desc["array"], chunk_size)
    with _session(args) as session:
        # trace-context stamp: the client's send clock opens every task's
        # distributed trace (`hq task trace` client/submit span)
        from hyperqueue_tpu.transport.framing import attach_trace
        from hyperqueue_tpu.utils.trace import new_trace_id

        if chunks_iter is not None:
            from hyperqueue_tpu.client.connection import SubmitStream

            header = {
                "name": job_desc["name"], "submit_dir": submit_dir,
                "max_fails": args.max_fails,
            }
            if args.job is not None:
                header["job_id"] = args.job
            stream = SubmitStream(
                session, header, window=args.submit_window
            )
            for chunk in chunks_iter:
                stream.send_chunk(array=chunk)
            stream_job_id, stream_n = stream.finish()
            response = {"job_id": stream_job_id, "n_tasks": stream_n}
        else:
            response = session.request(attach_trace(
                {"op": "submit", "job": job_desc},
                new_trace_id(), sent_at=clock.now(),
            ))
        job_id = response["job_id"]
        if notify_runner is not None:
            notify_runner.set_job_id(job_id)
        out = make_output(args.output_mode)
        if args.output_mode == "quiet":
            out.value(job_id)
        else:
            out.message(
                f"Job submitted successfully, job ID: {job_id}"
                f" ({response['n_tasks']} tasks)"
            )
        try:
            if args.progress:
                jobs = _progress_loop(session, [job_id])
                job = jobs[0] if jobs else None
            elif args.wait:
                info = session.request({"op": "job_wait", "job_ids": [job_id]})
                job = info["jobs"][0] if info["jobs"] else None
            else:
                return
        finally:
            if notify_runner is not None:
                notify_runner.flush()  # buffered notifies of a fast job
                notify_runner.stop = True
        ok = job is not None and not (
            job["counters"]["failed"] or job["counters"]["canceled"]
        )
        if not args.progress:
            out.message(f"job {job_id} {job['status'] if job else 'unknown'}")
        if not ok:
            raise SystemExit(1)


# ---------------------------------------------------------------- job cmds
def cmd_job_list(args) -> None:
    with _session(args) as session:
        jobs = session.request({"op": "job_list"})["jobs"]
    # reference JobListOpts: open/running only by default; --all shows
    # everything; --filter selects explicit states
    if args.filter:
        wanted = set(args.filter.split(","))
        unknown = wanted - {"opened", "waiting", "running", "finished",
                            "failed", "canceled"}
        if unknown:
            fail(f"unknown job state(s) {sorted(unknown)}; valid: "
                 "opened, waiting, running, finished, failed, canceled")
        jobs = [j for j in jobs if j["status"] in wanted]
    elif not args.all:
        # reference hq.rs:95 default: waiting + running + opened
        jobs = [j for j in jobs if j["status"] in ("opened", "waiting",
                                                   "running")]
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(jobs)
        return
    headers = ["id", "name", "status", "tasks", "finished", "failed"]
    if args.verbose:
        headers.append("cancel reason")
    out.table(
        headers,
        [
            [
                j["id"],
                j["name"],
                j["status"],
                j["n_tasks"],
                j["counters"]["finished"],
                j["counters"]["failed"],
            ] + ([j.get("cancel_reason", "")] if args.verbose else [])
            for j in sorted(jobs, key=lambda j: j["id"])
        ],
    )


def cmd_job_summary(args) -> None:
    """Per-status job counts (reference cli.rs:514 print_job_summary,
    JOB_SUMMARY_STATUS_ORDER rows even when a count is zero)."""
    with _session(args) as session:
        jobs = session.request({"op": "job_list"})["jobs"]
    order = ["running", "waiting", "opened", "finished", "failed", "canceled"]
    counts = {status: 0 for status in order}
    for j in jobs:
        counts[j["status"]] = counts.get(j["status"], 0) + 1
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(counts)
        return
    out.table(["status", "count"], [[s, counts[s]] for s in counts])


def cmd_job_info(args) -> None:
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        jobs = session.request({"op": "job_info", "job_ids": ids})["jobs"]
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(jobs)
        return
    for job in jobs:
        record = {k: v for k, v in job.items() if k != "tasks"}
        record["counters"] = " ".join(
            f"{k}={v}" for k, v in record.pop("counters").items()
        )
        # "37 tasks waiting: 30 insufficient-capacity, 7 gang-incomplete"
        reasons = record.pop("pending_reasons", None)
        if reasons:
            from hyperqueue_tpu.scheduler.decision import (
                format_reason_counts,
            )

            total = sum(reasons.values())
            record["pending"] = (
                f"{total} task(s) waiting: {format_reason_counts(reasons)}"
            )
        out.record(record)


def cmd_job_wait(args) -> None:
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        t0 = clock.now()
        jobs = session.request({"op": "job_wait", "job_ids": ids})["jobs"]
    out = make_output(args.output_mode)
    bad = [
        j for j in jobs
        if j["counters"]["failed"] or j["counters"]["canceled"]
    ]
    out.message(
        f"waited {clock.now() - t0:.1f}s; "
        f"{len(jobs) - len(bad)} succeeded, {len(bad)} with failures"
    )
    if bad:
        raise SystemExit(1)


def cmd_job_timeline(args) -> None:
    """Task lifecycle timeline of selected jobs: per-phase
    (pending/queued/dispatch/run) percentiles plus a slowest-task
    drill-down, aggregated server-side from the same lifecycle stamps the
    event journal carries."""
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        results = []
        for job_id in ids:
            results.append(session.request(
                {"op": "job_timeline", "job_id": job_id,
                 "detail": bool(args.tasks)}
            ))
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        for r in results:
            r.pop("op", None)
        out.value(results)
        return
    for r in results:
        out.message(
            f"job {r['job']}: {r['n_finished']}/{r['n_tasks']} tasks "
            f"finished, makespan {r['makespan']:.3f}s"
        )
        out.table(
            ["phase", "count", "p50 (s)", "p95 (s)", "max (s)", "mean (s)",
             "total (s)"],
            [
                [
                    name,
                    row["count"],
                    f"{row['p50']:.4f}",
                    f"{row['p95']:.4f}",
                    f"{row['max']:.4f}",
                    f"{row['mean']:.4f}",
                    f"{row['total']:.3f}",
                ]
                for name, row in r["phases"].items()
            ],
        )
        if r.get("slowest"):
            out.message("slowest tasks:")
            out.table(
                ["task", "pending", "queued", "dispatch", "run",
                 "total (s)"],
                [
                    [
                        t["id"],
                        f"{t['phases']['pending']:.4f}",
                        f"{t['phases']['queued']:.4f}",
                        f"{t['phases']['dispatch']:.4f}",
                        f"{t['phases']['run']:.4f}",
                        f"{t['finished'] - t['submitted']:.3f}",
                    ]
                    for t in r["slowest"]
                ],
            )


def cmd_server_reset_metrics(args) -> None:
    """Zero the server's metrics plane (registry, tracer spans, tick-phase
    aggregates) so a benchmark can measure a steady-state window. Under a
    federation root, `--shard K|all` selects the shard(s) — `all` fans
    out so one reset opens a fleet-wide window (ISSUE 15)."""
    out = make_output(args.output_mode)
    shard = getattr(args, "shard", None)
    with _session(args) as session:
        if shard is not None and not isinstance(session, FederatedSession):
            # selector convention (cf. `hq top --shard`): a classic dir
            # must not silently ignore the flag — the user would believe
            # a shard-targeted window was opened when it was not
            fail(f"--shard needs a federation root; "
                 f"{_server_dir(args)} is a classic server dir")
        result = session.request({"op": "reset_metrics", "shard": shard})
    if "shards" in result:
        for k, rec in enumerate(result["shards"]):
            if rec.get("error"):
                out.message(f"shard {k}: DOWN ({rec['error']})")
            else:
                out.message(f"shard {k}: metrics reset")
        return
    out.message("metrics reset")


def cmd_server_profile(args) -> None:
    """Pull flamegraph-ready folded stacks from the server's sampling
    profiler (`hq server profile`). With --seconds N the server diffs its
    cumulative trie across an N-second window (so the output shows only
    that window); without it you get the whole-run aggregate. Pipe the
    folded output straight into flamegraph.pl / speedscope."""
    seconds = args.seconds or 0.0
    with _session(args) as session:
        result = session.request({
            "op": "profile",
            "seconds": seconds,
            "shard": getattr(args, "shard", None),
        })
    records = result.get("shards")
    if records is None:
        records = [result]
    if args.format == "json":
        print(json.dumps(result, default=str))
        return
    for rec in records:
        shard = rec.get("shard", rec.get("shard_id"))
        if rec.get("error"):
            print(f"# shard {shard}: DOWN ({rec['error']})",
                  file=sys.stderr)
            continue
        if len(records) > 1:
            print(f"# shard {shard}", file=sys.stderr)
        print(
            f"# mode={rec.get('mode')} hz={rec.get('hz')} "
            f"passes={rec.get('passes')} seconds={rec.get('seconds')}",
            file=sys.stderr,
        )
        folded = rec.get("folded") or ""
        if folded:
            print(folded, end="" if folded.endswith("\n") else "\n")


_ACCOUNTING_HEADER = [
    "job", "label", "task s", "cpu s", "gpu s", "wait s", "crash",
    "runs", "done", "fail", "run",
]


def cmd_job_accounting(args) -> None:
    """Per-job usage ledger rows (ISSUE 18): closed run-span charges
    folded from the journal — stable under restore/replay/migration."""
    out = make_output(args.output_mode)
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        result = session.request({"op": "accounting", "job_ids": ids})
    rows = result.get("jobs") or []
    if not rows:
        fail("no accounting rows for that selector")
    out.table(
        _ACCOUNTING_HEADER,
        [
            [
                r["job"], r["label"],
                f"{r['task_seconds']:.3f}",
                f"{r['cpu_seconds']:.3f}",
                f"{r['gpu_seconds']:.3f}",
                f"{r['wait_seconds']:.3f}",
                r["crash_retries"], r["runs"], r["finished"],
                r["failed"], r["running"],
            ]
            for r in rows
        ],
    )


def cmd_fleet_accounting(args) -> None:
    """Per-label usage rollup across every shard (`hq fleet accounting`;
    also answers on a classic dir as a single-shard rollup)."""
    out = make_output(args.output_mode)
    with _session(args) as session:
        if isinstance(session, FederatedSession):
            result = session.request({"op": "accounting", "shard": "all"})
            records = [
                rec for rec in result["shards"] if not rec.get("error")
            ]
        else:
            records = [session.request({"op": "accounting"})]
    header = ["shard", "label", "jobs", "task s", "cpu s", "gpu s",
              "wait s", "crash", "run"]
    rows = []
    for rec in records:
        rollup = rec.get("rollup") or {}
        shard = rec.get("shard", 0)
        for label, agg in (rollup.get("labels") or {}).items():
            rows.append([
                shard, label, agg["jobs"],
                f"{agg['task_seconds']:.3f}",
                f"{agg['cpu_seconds']:.3f}",
                f"{agg['gpu_seconds']:.3f}",
                f"{agg['wait_seconds']:.3f}",
                agg["crash_retries"], agg["running"],
            ])
        totals = rollup.get("totals")
        if totals and totals["jobs"]:
            rows.append([
                shard, "(total)", totals["jobs"],
                f"{totals['task_seconds']:.3f}",
                f"{totals['cpu_seconds']:.3f}",
                f"{totals['gpu_seconds']:.3f}",
                f"{totals['wait_seconds']:.3f}",
                totals["crash_retries"], totals["running"],
            ])
    if not rows:
        out.message("no usage recorded yet")
        return
    out.table(header, rows)


def cmd_alerts(args) -> None:
    """`hq alerts [--shard K|all]`: firing SLO burn-rate alerts + the
    most recent transitions, per shard."""
    out = make_output(args.output_mode)
    shard = getattr(args, "shard", None)
    with _session(args) as session:
        if isinstance(session, FederatedSession):
            result = session.request(
                {"op": "alerts", "shard": shard if shard is not None
                 else "all"}
            )
            records = result.get("shards") or [result]
        else:
            if shard is not None:
                fail(f"--shard needs a federation root; "
                     f"{_server_dir(args)} is a classic server dir")
            records = [session.request({"op": "alerts"})]
    rows = []
    for rec in records:
        if rec.get("error"):
            rows.append([rec.get("shard_id", "?"), "shard-availability",
                         "page", "DOWN", "-", "-"])
            continue
        for alert in rec.get("firing") or []:
            rows.append([
                rec.get("shard", 0), alert["slo"], alert["severity"],
                "firing",
                f"{alert['burn_rate']:.2f}x",
                "/".join(f"{w:g}s" for w in alert.get("window") or ()),
            ])
    if rows:
        out.table(
            ["shard", "slo", "severity", "state", "burn", "windows"],
            rows,
        )
    else:
        out.message("no alerts firing")
    recent = [
        t for rec in records if not rec.get("error")
        for t in rec.get("recent") or []
    ]
    if recent and args.output_mode == "cli":
        out.message("recent transitions:")
        for t in recent[-10:]:
            out.message(
                f"  {t['alert']}: {t['state']} "
                f"(burn {t['burn_rate']:.2f}x)"
            )


def cmd_job_cancel(args) -> None:
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        result = session.request({"op": "job_cancel", "job_ids": ids})["result"]
    make_output(args.output_mode).value(result)


def cmd_job_forget(args) -> None:
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        result = session.request({"op": "job_forget", "job_ids": ids})
    make_output(args.output_mode).message(
        f"forgot {result['forgotten']} job(s)"
    )


def cmd_job_cat(args) -> None:
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        jobs = session.request({"op": "job_info", "job_ids": ids})["jobs"]
    if not jobs:
        fail("job not found")
    stream = args.stream
    for job in jobs:
        detail = job
        task_filter = (
            set(parse_selector(args.tasks)) or None  # 'all' -> [] = all tasks
        ) if args.tasks else None
        for task in detail["tasks"]:
            if task_filter is not None and task["id"] not in task_filter:
                continue
            mapping = {
                "JOB_ID": str(job["id"]),
                "TASK_ID": str(task["id"]),
                "INSTANCE_ID": "0",
                "SUBMIT_DIR": job["submit_dir"],
            }
            path = fill_placeholders(
                f"%{{SUBMIT_DIR}}/job-%{{JOB_ID}}/%{{TASK_ID}}.{stream}", mapping
            )
            if os.path.exists(path):
                with open(path, "rb") as f:
                    sys.stdout.buffer.write(f.read())
    sys.stdout.flush()


def _progress_loop(session, ids: list[int]) -> list[dict]:
    """Poll + render a progress line until every job in `ids` is done;
    returns the final job infos."""
    while True:
        jobs = session.request({"op": "job_info", "job_ids": ids})["jobs"]
        parts = []
        all_done = True
        for j in jobs:
            c = j["counters"]
            done = c["finished"] + c["failed"] + c["canceled"]
            parts.append(
                f"job {j['id']}: {done}/{j['n_tasks']} "
                f"(run {c['running']}, fail {c['failed']})"
            )
            if done < j["n_tasks"] or j["status"] == "running":
                all_done = False
        print("\r" + " | ".join(parts) + " " * 8, end="", flush=True)
        if all_done:
            print()
            return jobs
        time.sleep(0.5)


def cmd_job_progress(args) -> None:
    """Live progress display while jobs run (reference `hq job progress`)."""
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        _progress_loop(session, ids)


def _format_id_ranges(ids: list[int]) -> str:
    """Compact `1-3,5,7-9` rendering of a sorted id list."""
    parts: list[str] = []
    i = 0
    ids = sorted(ids)
    while i < len(ids):
        j = i
        while j + 1 < len(ids) and ids[j + 1] == ids[j] + 1:
            j += 1
        parts.append(str(ids[i]) if i == j else f"{ids[i]}-{ids[j]}")
        i = j + 1
    return ",".join(parts)


def cmd_job_task_ids(args) -> None:
    """Print the task ids of selected jobs, optionally filtered by task
    status (reference JobCommand::TaskIds, commands/job.rs)."""
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        jobs = session.request({"op": "job_info", "job_ids": ids})["jobs"]
    statuses = set(args.filter.split(",")) if args.filter else None
    per_job = {
        j["id"]: [
            t["id"] for t in j["tasks"]
            if statuses is None or t["status"] in statuses
        ]
        for j in jobs
    }
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(per_job)
        return
    for job_id, task_ids in per_job.items():
        print(f"{job_id}: {_format_id_ranges(task_ids)}")


def cmd_doc(args) -> None:
    docs_root = Path(__file__).resolve().parent.parent.parent / "docs"
    topic = args.topic or "index"
    # `hq doc arrays` or `hq doc jobs/arrays` — search every docs subtree
    # (reference: cli/documentation.md, `hq doc` opens a topic index)
    candidates = [docs_root / f"{topic}.md", docs_root / topic / "README.md"]
    if "/" not in topic:
        # bare names search every subtree; explicit paths must match
        # exactly (a typo'd path should error, not print a random page)
        candidates += sorted(docs_root.rglob(f"{topic}.md"))
    for candidate in candidates:
        if candidate.exists():
            print(candidate.read_text())
            return
    available = sorted(
        str(p.relative_to(docs_root))[:-3] for p in docs_root.rglob("*.md")
    )
    fail(f"unknown topic {topic!r}; available: {', '.join(available)}")


def cmd_generate_completion(args) -> None:
    """Emit a completion script for the hq CLI (top-level commands, their
    subcommands, and per-command long options, walked from the real parser
    tree — reference uses clap_complete with a shell argument). zsh reuses
    the bash script through bashcompinit; fish gets native complete
    lines."""
    parser = build_parser()

    def sub_actions(p):
        return [a for a in p._actions
                if isinstance(a, argparse._SubParsersAction)]

    def long_opts(p):
        out = []
        for a in p._actions:
            out.extend(s for s in a.option_strings if s.startswith("--"))
        return out

    subs = sub_actions(parser)
    top_choices = subs[0].choices if subs else {}

    if args.shell == "fish":
        lines = [
            f'complete -c hq -f -n "__fish_use_subcommand" '
            f'-a "{" ".join(top_choices)}"'
        ]
        for name, sub_parser in top_choices.items():
            nested = sub_actions(sub_parser)
            if nested:
                nested_names = " ".join(nested[0].choices)
                # suggest verbs only until one is typed; afterwards fall
                # through to per-verb options + default file completion
                lines.append(
                    f'complete -c hq -f '
                    f'-n "__fish_seen_subcommand_from {name}; and not '
                    f'__fish_seen_subcommand_from {nested_names}" '
                    f'-a "{nested_names}"'
                )
                for nname, nparser in nested[0].choices.items():
                    for opt in sorted(set(long_opts(nparser))):
                        lines.append(
                            f'complete -c hq '
                            f'-n "__fish_seen_subcommand_from {name}; and '
                            f'__fish_seen_subcommand_from {nname}" '
                            f'-l {opt.lstrip("-")}'
                        )
            for opt in sorted(set(long_opts(sub_parser))):
                lines.append(
                    f'complete -c hq '
                    f'-n "__fish_seen_subcommand_from {name}" '
                    f'-l {opt.lstrip("-")}'
                )
        print("\n".join(lines))
        return

    lines = [
        "_hq_complete() {",
        '  local cur=${COMP_WORDS[COMP_CWORD]}',
        '  local cmd=${COMP_WORDS[1]:-}',
        '  local sub=${COMP_WORDS[2]:-}',
        "  if [ $COMP_CWORD -eq 1 ]; then",
        f'    COMPREPLY=( $(compgen -W "{" ".join(top_choices)}" -- "$cur") )',
        "    return",
        "  fi",
        '  case "$cmd" in',
    ]
    for name, sub_parser in top_choices.items():
        nested = sub_actions(sub_parser)
        own_opts = sorted(set(long_opts(sub_parser)))
        if nested:
            nested_choices = nested[0].choices
            second = " ".join([*nested_choices, *own_opts])
            lines.append(f"    {name})")
            lines.append("      if [ $COMP_CWORD -eq 2 ]; then")
            lines.append(
                f'        COMPREPLY=( $(compgen -W "{second}" -- "$cur") )'
            )
            lines.append("        return")
            lines.append("      fi")
            lines.append('      case "$sub" in')
            for nname, nparser in nested_choices.items():
                nwords = " ".join(sorted(set(long_opts(nparser))))
                # only complete flags when one is being typed; bare
                # positions fall through to bash's default (filenames)
                lines.append(
                    f'        {nname}) [[ "$cur" == -* ]] && '
                    f'COMPREPLY=( $(compgen -W "{nwords}" -- "$cur") );'
                    " return;;"
                )
            lines.append("      esac")
            lines.append("      ;;")
        else:
            opt_words = " ".join(own_opts)
            lines.append(
                f'    {name}) [[ "$cur" == -* ]] && '
                f'COMPREPLY=( $(compgen -W "{opt_words}" -- "$cur") );'
                " return;;"
            )
    lines += [
        "  esac",
        "}",
        "complete -o default -F _hq_complete hq",
        'complete -o default -F _hq_complete "python -m hyperqueue_tpu"'
        " 2>/dev/null || true",
    ]
    if args.shell == "zsh":
        # zsh consumes the bash script through its compatibility layer;
        # compinit must load first or bashcompinit's complete shim has no
        # compdef to call
        lines = [
            "autoload -U +X compinit && compinit",
            "autoload -U +X bashcompinit && bashcompinit",
        ] + lines
    print("\n".join(lines))


def cmd_job_open(args) -> None:
    with _session(args) as session:
        response = session.request(
            {"op": "open_job", "name": args.name or "job",
             "submit_dir": os.getcwd(), "max_fails": args.max_fails}
        )
    out = make_output(args.output_mode)
    if args.output_mode == "quiet":
        out.value(response["job_id"])
    else:
        out.message(f"opened job {response['job_id']}")


def cmd_job_close(args) -> None:
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        response = session.request({"op": "close_job", "job_ids": ids})
    make_output(args.output_mode).message(f"closed jobs: {response['closed']}")


# ---------------------------------------------------------------- alloc
def _alloc_params(args) -> dict:
    return {
        "manager": args.manager,
        "backlog": args.backlog,
        "workers_per_alloc": args.workers_per_alloc,
        "max_worker_count": args.max_worker_count or 0,
        "time_limit_secs": args.time_limit,
        "name": args.name or "",
        "worker_args": (args.worker_args or [])
        + (
            ["--min-utilization", str(args.min_utilization)]
            if args.min_utilization
            else []
        ),
        "additional_args": args.additional_args or [],
        "idle_timeout_secs": args.idle_timeout,
        "worker_start_cmd": args.worker_start_cmd or "",
        "worker_stop_cmd": args.worker_stop_cmd or "",
        "worker_wrap_cmd": args.worker_wrap_cmd or "",
        "worker_time_limit_secs": args.worker_time_limit or 0.0,
        "on_server_lost": args.on_server_lost,
    }


def cmd_alloc_add(args) -> None:
    with _session(args) as session:
        response = session.request(
            {"op": "alloc_add", "params": _alloc_params(args),
             "no_dry_run": args.no_dry_run}
        )
    out = make_output(args.output_mode)
    if args.output_mode == "quiet":
        out.value(response["queue_id"])
    else:
        out.message(f"allocation queue {response['queue_id']} created")


def cmd_alloc_list(args) -> None:
    with _session(args) as session:
        queues = session.request({"op": "alloc_list"})["queues"]
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(queues)
        return
    out.table(
        ["id", "manager", "state", "backlog", "workers/alloc", "allocations"],
        [
            [
                q["id"],
                q["params"]["manager"],
                q["state"],
                q["params"]["backlog"],
                q["params"]["workers_per_alloc"],
                len(q["allocations"]),
            ]
            for q in queues
        ],
    )


def cmd_alloc_info(args) -> None:
    with _session(args) as session:
        queues = session.request({"op": "alloc_list"})["queues"]
    queue = next((q for q in queues if q["id"] == args.queue_id), None)
    if queue is None:
        fail(f"allocation queue {args.queue_id} not found")
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(queue)
        return
    out.table(
        ["alloc", "status", "workers", "connected"],
        [
            [a["id"], a["status"], a["worker_count"], len(a["workers"])]
            for a in queue["allocations"]
        ],
    )


def cmd_alloc_log(args) -> None:
    """Print the manager-captured stdout/stderr of one allocation
    (reference commands/autoalloc.rs AutoAllocCommand::Log)."""
    with _session(args) as session:
        response = session.request(
            {"op": "alloc_log", "allocation_id": args.allocation_id}
        )
    alloc = response["allocation"]
    path = Path(alloc["workdir"]) / args.channel
    if not path.exists():
        fail(
            f"allocation {args.allocation_id} has no captured {args.channel} "
            f"(expected at {path}; the allocation may not have started yet)"
        )
    sys.stdout.write(path.read_text(errors="replace"))
    sys.stdout.flush()


def cmd_alloc_remove(args) -> None:
    with _session(args) as session:
        session.request({"op": "alloc_remove", "queue_id": args.queue_id})
    make_output(args.output_mode).message(
        f"allocation queue {args.queue_id} removed"
    )


def cmd_alloc_pause(args) -> None:
    with _session(args) as session:
        response = session.request(
            {"op": "alloc_pause", "queue_id": args.queue_id,
             "pause": args.alloc_cmd == "pause"}
        )
    make_output(args.output_mode).message(
        f"allocation queue {args.queue_id} is now {response['state']}"
    )


def cmd_alloc_dry_run(args) -> None:
    with _session(args) as session:
        response = session.request(
            {"op": "alloc_dry_run", "params": _alloc_params(args)}
        )
    out = make_output(args.output_mode)
    out.message(f"would submit via {response['submit_binary']}:")
    out.message(response["script"])


def cmd_alloc_events(args) -> None:
    """Scale decision records: why the elasticity controller did (or
    deliberately did not) scale each queue (ISSUE 13)."""
    with _session(args) as session:
        decisions = session.request({"op": "alloc_events"})["decisions"]
    if args.queue_id is not None:
        decisions = [d for d in decisions if d["queue"] == args.queue_id]
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(decisions)
        return
    out.table(
        ["time", "queue", "verdict", "reason", "ticks", "detail"],
        [
            [
                time.strftime("%H:%M:%S", time.localtime(d["time"])),
                d["queue"],
                d["verdict"],
                d["reason"],
                d["ticks"],
                d.get("detail", ""),
            ]
            for d in decisions
        ],
    )


# ---------------------------------------------------------------- journal
def cmd_journal_export(args) -> None:
    from hyperqueue_tpu.events.journal import Journal

    for record in Journal.read_all(
        Path(args.journal_file), salvage=getattr(args, "salvage", False)
    ):
        print(json.dumps(record, default=str))


def cmd_journal_flush(args) -> None:
    with _session(args) as session:
        session.request({"op": "journal_flush"})
    make_output(args.output_mode).message("journal flushed")


def cmd_journal_prune(args) -> None:
    with _session(args) as session:
        result = session.request({"op": "journal_prune"})
    make_output(args.output_mode).message(
        f"journal pruned: kept {result['kept_records']} records "
        f"for live jobs {result['live_jobs']}"
    )


def cmd_journal_compact(args) -> None:
    """Snapshot live server state + GC the superseded journal prefix."""
    with _session(args) as session:
        result = session.request({"op": "journal_compact"})
    out = make_output(args.output_mode)
    if args.output_mode != "cli":
        result.pop("op", None)
        out.record(result)
        return
    if result.get("skipped"):
        out.message(f"compaction skipped: {result['skipped']}")
        return
    out.message(
        f"journal compacted: {result['kept_records']} records kept, "
        f"{result['dropped_records']} dropped, "
        f"{result['journal_bytes_before']} -> "
        f"{result['journal_bytes_after']} bytes "
        f"(snapshot {result['snapshot_bytes']} bytes, "
        f"{result['duration_ms']} ms)"
    )


def cmd_journal_info(args) -> None:
    """Journal + snapshot sizes, lineage, and compaction/restore stats."""
    with _session(args) as session:
        info = session.request({"op": "journal_info"})
    if args.output_mode != "cli":
        info.pop("op", None)
        make_output(args.output_mode).record(info)
        return
    snap = info.get("snapshot") or {}
    print(f"journal: {info['path']} ({info['journal_bytes']} bytes, "
          f"{info['segments']} segment(s), fsync {info['fsync_policy']})")
    print(f"event seq: {info['event_seq']}  boots: {info['n_boots']}")
    if snap.get("bytes"):
        print(f"snapshot: {snap['path']} ({snap['bytes']} bytes, "
              f"age {snap['age_seconds']:.0f}s"
              + (f", prev {snap['prev_bytes']} bytes" if snap.get("prev_bytes")
                 else "") + ")")
    else:
        print("snapshot: none")
    lc = info.get("last_compaction")
    if lc:
        print(f"last compaction ({lc['reason']}): kept {lc['kept_records']}, "
              f"dropped {lc['dropped_records']}, "
              f"{lc['journal_bytes_before']} -> "
              f"{lc['journal_bytes_after']} bytes in {lc['duration_ms']} ms")
    lr = info.get("last_restore")
    if lr:
        print(f"last restore: {lr['duration_s']}s "
              f"({'snapshot ' + lr['snapshot'] if lr['snapshot'] else 'full replay'}, "
              f"{lr['tail_events']} tail events, "
              f"{lr['resubmitted']} resubmitted, "
              f"{lr['held_for_reattach']} held)")
    if info.get("compact_interval") or info.get("compact_threshold"):
        print(f"auto-compaction: every {info['compact_interval']}s"
              f" / over {info['compact_threshold']} bytes")


def cmd_journal_report(args) -> None:
    from hyperqueue_tpu.client.report import build_report

    html_text = build_report(
        args.journal_file,
        start_time=args.start_time,
        end_time=args.end_time,
    )
    output = args.output or "hq-report.html"
    with open(output, "w") as f:
        f.write(html_text)
    make_output(args.output_mode).message(f"report written to {output}")


def cmd_journal_replay(args) -> None:
    """Offline NDJSON replay (alias of export; reference `journal replay`
    streams through a server — the journal format is identical)."""
    cmd_journal_export(args)


def cmd_journal_stream(args) -> None:
    from hyperqueue_tpu.client.connection import stream_events

    try:
        for msg in stream_events(
            _server_dir(args),
            history=args.history,
            filters=args.filter or [],
        ):
            if msg.get("op") == "event":
                print(json.dumps(msg["record"], default=str), flush=True)
            elif msg.get("op") == "stream_live" and not args.follow:
                return
    except (ConnectionError, OSError, EOFError):
        pass


# ---------------------------------------------------------------- output-log
def cmd_output_log(args) -> None:
    from hyperqueue_tpu.events.outputlog import STDERR, STDOUT, OutputLog

    log = OutputLog(args.stream_dir)
    out = make_output(args.output_mode)
    if args.log_cmd == "summary":
        out.record(log.summary())
    elif args.log_cmd == "jobs":
        # reference outputlog.rs:349 — one job id per line
        if args.output_mode == "json":
            out.value(log.job_ids())
        else:
            for job_id in log.job_ids():
                print(job_id)
    elif args.log_cmd == "cat":
        from hyperqueue_tpu.ids import task_id_task

        channel = STDOUT if args.channel == "stdout" else STDERR
        # stream records carry packed (job, task) ids; --tasks selects by the
        # job-task part
        wanted = (
            set(parse_selector(args.tasks)) or None  # 'all' parses to [] = all tasks
        ) if args.tasks else None
        for task_id in log.task_ids():
            if wanted is None or task_id_task(task_id) in wanted:
                sys.stdout.buffer.write(log.cat(task_id, channel))
        sys.stdout.flush()
    elif args.log_cmd == "show":
        for rec in log.export():
            for line in rec["data"].splitlines():
                print(f"{rec['task']}:{rec['channel'][-3:]}> {line}")
    elif args.log_cmd == "export":
        for rec in log.export():
            print(json.dumps(rec))


def cmd_dashboard(args) -> None:
    from hyperqueue_tpu.client.dashboard import run_dashboard

    try:
        run_dashboard(
            _server_dir(args) if not args.replay else None,
            interval=args.interval,
            replay=args.replay,
        )
    except KeyboardInterrupt:
        pass


# ---------------------------------------------------------------- task cmds
def cmd_task_list(args) -> None:
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        jobs = session.request({"op": "job_info", "job_ids": ids})["jobs"]
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value([{"job": j["id"], "tasks": j["tasks"]} for j in jobs])
        return
    for job in jobs:
        out.table(
            ["job", "task", "status", "workers", "error"],
            [
                [job["id"], t["id"], t["status"],
                 ",".join(map(str, t["workers"])), t["error"][:60]]
                for t in job["tasks"]
            ],
        )


def cmd_task_info(args) -> None:
    """Detailed info for selected tasks of a job (reference
    TaskCommand::Info, client/task.rs)."""
    with _session(args) as session:
        ids = _resolve_job_selector(session, args.selector)
        jobs = session.request({"op": "job_info", "job_ids": ids})["jobs"]
    if not jobs:
        fail("job not found")
    wanted = (
        set(parse_selector(args.tasks)) or None  # 'all' parses to [] = all tasks
    ) if args.tasks else None
    rows = []
    for job in jobs:
        for t in job["tasks"]:
            if wanted is not None and t["id"] not in wanted:
                continue
            rows.append((job, t))
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value([
            {"job": job["id"], **t} for job, t in rows
        ])
        return
    for job, t in rows:
        runtime = ""
        if t["started_at"] and t["finished_at"]:
            runtime = f"{t['finished_at'] - t['started_at']:.3f}s"
        out.record({
            "job": job["id"],
            "task": t["id"],
            "status": t["status"],
            "workers": ",".join(map(str, t["workers"])),
            "started": _format_time(t["started_at"]),
            "finished": _format_time(t["finished_at"]),
            "runtime": runtime,
            "error": t["error"],
        })


def _format_time(ts: float) -> str:
    if not ts:
        return ""
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))


def cmd_worker_hwdetect(args) -> None:
    """Detect and print this node's resources without starting a worker
    (reference WorkerCommand::HwDetect)."""
    from hyperqueue_tpu.worker.hwdetect import detect_resources

    descriptor = detect_resources(
        n_cpus=None,
        no_hyper_threading=args.no_hyper_threading,
        with_memory=True,
    )
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(descriptor.to_dict())
        return
    for item in descriptor.items:
        groups = item.index_groups()
        if item.kind.value == "sum":
            print(f"{item.name}: sum({item.total_amount()})")
        elif len(groups) > 1:
            print(f"{item.name}: {len(groups)} groups "
                  f"{[len(g) for g in groups]} "
                  f"({sum(len(g) for g in groups)} total)")
        else:
            print(f"{item.name}: {len(groups[0]) if groups else 0}")
    if descriptor.coupling:
        print(f"coupling: {', '.join(descriptor.coupling.names)}")


# ---------------------------------------------------------------- parser
def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="hq", description="HyperQueue-TPU: task-graph execution framework"
    )
    parser.add_argument("--version", action="version", version=__version__)
    sub = parser.add_subparsers(dest="cmd", required=True)

    # server
    server = sub.add_parser("server", help="server management")
    ssub = server.add_subparsers(dest="server_cmd", required=True)
    p = ssub.add_parser("start")
    _add_common(p)
    p.add_argument("--host", default=None)
    p.add_argument("--client-port", type=int, default=0)
    p.add_argument("--worker-port", type=int, default=0)
    p.add_argument("--disable-client-authentication", action="store_true")
    p.add_argument("--disable-worker-authentication", action="store_true")
    p.add_argument("--scheduler",
                   choices=["auto", "cpu", "tpu", "milp", "multichip",
                            "greedy-numpy", "greedy-fused"],
                   default="auto",
                   help="auto/cpu/tpu pick the greedy cut-scan backend; "
                        "milp runs the exact host MILP (accuracy oracle); "
                        "multichip shards the cut-scan's worker axis over "
                        "all visible devices (identical semantics); "
                        "greedy-numpy pins the host numpy kernel; "
                        "greedy-fused additionally folds gang rows and "
                        "mask columns into the one dense solve "
                        "(docs/scheduler.md)")
    p.add_argument("--journal", default=None)
    p.add_argument("--journal-fsync", choices=["never", "periodic", "always"],
                   default="never",
                   help="fsync policy for the journal: never = fsync only "
                        "on clean close (flush-to-OS still per event), "
                        "periodic = fsync on the flush period, always = "
                        "fsync after every event (survives an OS crash)")
    p.add_argument("--heartbeat-timeout-factor", type=float, default=4.0,
                   metavar="X",
                   help="drop a worker after X missed heartbeat intervals "
                        "(timeout = heartbeat x X, floor 2s)")
    p.add_argument("--reattach-timeout", type=_parse_duration, default=15.0,
                   help="after a journal restore, hold maybe-running tasks "
                        "this long for their pre-crash worker to reconnect "
                        "and reclaim them before requeueing (0 = requeue "
                        "immediately)")
    p.add_argument("--solver-watchdog-timeout", type=_parse_duration,
                   default=5.0,
                   help="per-tick solve deadline before degrading to the "
                        "host greedy fallback (0 = exception guard only)")
    p.add_argument("--solver-rearm-ticks", type=int, default=20, metavar="N",
                   help="clean fallback ticks before re-trying a failed "
                        "primary solver")
    p.add_argument("--journal-flush-period", type=_parse_duration, default=0.0,
                   help="flush the journal on this period instead of after "
                        "every event (0 = per-event, the default)")
    p.add_argument("--journal-compact-interval", type=_parse_duration,
                   default=0.0,
                   help="snapshot live state and GC the superseded journal "
                        "prefix on this period (0 = no periodic compaction; "
                        "`hq journal compact` still works)")
    p.add_argument("--journal-compact-threshold", type=int, default=0,
                   metavar="BYTES",
                   help="also compact whenever the journal file exceeds "
                        "this many bytes (0 = no size trigger)")
    p.add_argument("--journal-salvage", action="store_true",
                   help="skip mid-file CRC-corrupt journal records (counted "
                        "in hq_journal_salvaged_records_total) instead of "
                        "refusing to start; torn tails are always handled")
    p.add_argument("--idle-timeout", type=_parse_duration, default=0.0,
                   help="default idle timeout adopted by workers that set "
                        "none of their own")
    p.add_argument("--access-file", default=None,
                   help="start with pre-shared keys/ports from generate-access")
    p.add_argument("--paranoid-tick", type=int, default=0, metavar="N",
                   help="debug: every N ticks, run the incremental and the "
                        "from-scratch tick assembly and assert they are "
                        "bit-identical (0 = off); on the device-resident "
                        "solve path the same cadence re-solves from a "
                        "fresh full upload and asserts identical counts, "
                        "and forces --tick-pipeline ticks synchronous")
    p.add_argument("--tick-pipeline", action="store_true",
                   help="two-stage async scheduling ticks: dispatch solve "
                        "N without blocking and map it at tick N+1, "
                        "overlapping device execution with inter-tick "
                        "host work (scheduler/pipeline.py); assignments "
                        "lag one tick")
    p.add_argument("--policy-file", default=None, metavar="TOML",
                   help="weighted scheduling objective (requires "
                        "--scheduler greedy-fused): TOML with [affinity] "
                        "per-(task-class, worker-group) weight rows "
                        "(0 = hard exclusion), [fairness] dominant-"
                        "resource-deficit priority boosts, and "
                        "[prediction] runtime-EWMA critical-path boosts "
                        "(docs/scheduler.md \"Scheduling policies\")")
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve Prometheus metrics on this port (0 = "
                        "ephemeral, see `hq server info`; off by default)")
    p.add_argument("--metrics-host", default="0.0.0.0", metavar="HOST",
                   help="bind address for the (unauthenticated) metrics "
                        "endpoint; use 127.0.0.1 behind a scraping sidecar")
    p.add_argument("--flight-recorder-ticks", type=int, default=512,
                   metavar="N",
                   help="keep the last N per-tick scheduling DecisionRecords"
                        " in memory for `hq server flight-recorder dump` / "
                        "`hq task explain` / `hq server trace export` "
                        "(0 = off)")
    p.add_argument("--log-format", choices=["plain", "json"],
                   default=os.environ.get("HQ_LOG_FORMAT", "plain"),
                   help="json: one JSON object per log line with "
                        "tick/job/task/worker correlation fields")
    p.add_argument("--stall-budget", type=_parse_duration, default=1.0,
                   help="reactor stall watchdog: when one work class "
                        "(rpc/journal/solve/fanout) or the loop itself "
                        "holds the event loop longer than this, auto-dump "
                        "flight recorder + trace + lag stats into the "
                        "instance dir (0 = record lag histograms only, "
                        "never capture)")
    p.add_argument("--stall-dumps", type=int, default=8, metavar="N",
                   help="keep at most N stall dump files")
    p.add_argument("--profile-hz", type=float, default=19.0, metavar="HZ",
                   help="always-on sampling profiler: walk every thread's "
                        "stack HZ times per second and fold the samples "
                        "into per-plane CPU-share gauges (hq_profile_*) "
                        "plus flamegraph data for `hq server profile` "
                        "(0 = off; the odd default avoids beating against "
                        "periodic work)")
    p.add_argument("--client-plane", choices=["thread", "reactor"],
                   default="thread",
                   help="where client connections are served: 'thread' "
                        "(default) runs accept/auth/framing/decode on a "
                        "dedicated connection-plane thread with a batched "
                        "handoff to the scheduler reactor; 'reactor' keeps "
                        "them on the reactor loop (escape hatch)")
    p.add_argument("--journal-plane", choices=["thread", "reactor"],
                   default="thread",
                   help="where the journal group commit + fsync runs: "
                        "'thread' (default) drains event batches onto a "
                        "dedicated commit thread and releases acks/"
                        "completions at the durability watermark; "
                        "'reactor' keeps the inline group-commit block "
                        "(escape hatch)")
    p.add_argument("--fanout-senders", type=int, default=2, metavar="N",
                   help="sender-pool threads running the downlink "
                        "msgpack-encode + AEAD-seal (worker compute "
                        "batches, client responses/streams, subscriber "
                        "fan-out); 0 keeps encodes inline on the owning "
                        "loop (escape hatch)")
    p.add_argument("--ingest-window", type=int, default=64, metavar="N",
                   help="per-client cap on handed-off, unanswered requests "
                        "before the connection plane pauses reading that "
                        "client (backpressure)")
    p.add_argument("--lazy-array-threshold", type=int, default=4096,
                   metavar="N",
                   help="array submits with at least N tasks are stored as "
                        "lazy chunks and materialized at dispatch "
                        "(0 disables lazy materialization)")
    p.add_argument("--task-trace-capacity", type=int, default=16384,
                   metavar="N",
                   help="bound the per-task distributed-trace store to N "
                        "tasks (`hq task trace`; 0 disables tracing "
                        "entirely, including trace headers on the wire)")
    p.add_argument("--shards", type=int, default=0, metavar="N",
                   help="run as part of an N-shard federation: the server "
                        "dir becomes the federation root with one nested "
                        "server dir (+ journal + lease) per shard, job ids "
                        "partition statically across shards, and clients "
                        "route by job id (docs/deployment/federation.md)")
    p.add_argument("--shard-id", type=int, default=0, metavar="K",
                   help="with --shards N: which shard (0..N-1) this "
                        "process owns")
    p.add_argument("--standby", action="store_true",
                   help="run a warm failover successor instead of a "
                        "shard: watch every shard's lease, claim stale "
                        "ones atomically, restore their journal and "
                        "absorb their workers/clients; also runs the "
                        "worker-lending coordinator")
    p.add_argument("--lease-timeout", type=_parse_duration, default=15.0,
                   help="shard lease staleness bound: a shard whose lease "
                        "went unrenewed this long is claimable by a "
                        "successor (renewal runs at a third of this)")
    p.add_argument("--failover-watch", action="store_true",
                   help="this shard also volunteers as a successor for "
                        "dead sibling shards while its own backlog is "
                        "empty (peer failover without a standby)")
    p.add_argument("--no-coordinator", action="store_true",
                   help="with --standby: watch leases only, never lend "
                        "workers across shards")
    p.add_argument("--coordinator-interval", type=_parse_duration,
                   default=1.0,
                   help="with --standby: subscribe-feed sample cadence "
                        "driving the lending decisions")
    p.add_argument("--rebalance", action="store_true",
                   help="with --standby: also drive live job migrations "
                        "from backlogged shards toward idle ones "
                        "(largest job first, hysteresis-bounded; every "
                        "verdict lands in the ownership log)")
    p.set_defaults(fn=cmd_server_start)
    p = ssub.add_parser("stop")
    _add_common(p)
    p.set_defaults(fn=cmd_server_stop)
    p = ssub.add_parser("info")
    _add_common(p)
    p.add_argument("--shard", default="0", metavar="K|all",
                   help="federation: which shard to query (default 0; "
                        "'all' fans out, one record per shard)")
    p.set_defaults(fn=cmd_server_info)
    p = ssub.add_parser(
        "stats", help="scheduler telemetry: per-phase tick latency "
                      "breakdown + snapshot-cache counters"
    )
    _add_common(p)
    p.add_argument("--shard", default="0", metavar="K|all",
                   help="federation: which shard to query (default 0; "
                        "'all' fans out, one record per shard)")
    p.set_defaults(fn=cmd_server_stats)
    p = ssub.add_parser("debug-dump", help="full server state as JSON")
    _add_common(p)
    p.set_defaults(fn=cmd_server_debug_dump)
    p = ssub.add_parser(
        "flight-recorder",
        help="scheduling flight recorder: per-tick DecisionRecords + "
             "recent control-plane events",
    )
    _add_common(p)
    p.add_argument("fr_cmd", choices=["dump"])
    p.add_argument("--json", action="store_true",
                   help="print the raw dump as JSON")
    p.set_defaults(fn=cmd_server_flight_recorder)
    p = ssub.add_parser(
        "trace",
        help="export the run as Chrome trace-event JSON (Perfetto)",
    )
    _add_common(p)
    p.add_argument("trace_cmd", choices=["export"])
    p.add_argument("output", help="output path (e.g. trace.json)")
    p.set_defaults(fn=cmd_server_trace_export)
    p = ssub.add_parser(
        "reset-metrics",
        help="zero the metrics plane (registry + tracer + tick aggregates) "
             "for steady-state benchmark windows",
    )
    _add_common(p)
    p.add_argument("--shard", default=None, metavar="K|all",
                   help="federation: which shard to reset (default 0; "
                        "'all' fans out for a fleet-wide window)")
    p.set_defaults(fn=cmd_server_reset_metrics)
    p = ssub.add_parser(
        "profile",
        help="flamegraph-ready folded stacks from the always-on sampling "
             "profiler (or a one-shot burst when --profile-hz 0)",
    )
    _add_common(p)
    p.add_argument("--seconds", type=float, default=0.0, metavar="N",
                   help="sample a fresh N-second window instead of the "
                        "whole-run aggregate (burst mode always samples "
                        "a window; default 2s there)")
    p.add_argument("--format", choices=["folded", "json"], default="folded",
                   help="folded: 'plane;frame;frame count' lines for "
                        "flamegraph.pl/speedscope; json: full snapshot")
    p.add_argument("--shard", default=None, metavar="K|all",
                   help="federation: which shard to profile (default 0; "
                        "'all' fans out, one block per shard)")
    p.set_defaults(fn=cmd_server_profile)
    p = ssub.add_parser("wait", help="wait until the server is reachable")
    _add_common(p)
    p.add_argument("--timeout", type=float, default=60.0)
    p.set_defaults(fn=cmd_server_wait)
    p = ssub.add_parser("generate-access")
    _add_common(p)
    p.add_argument("access_file")
    p.add_argument("--host", default=None,
                   help="hostname for both planes (or set per-role hosts)")
    p.add_argument("--client-host", default=None)
    p.add_argument("--worker-host", default=None)
    p.add_argument("--client-port", type=int, required=True)
    p.add_argument("--worker-port", type=int, required=True)
    p.add_argument("--client-file", default=None,
                   help="also write a client-only access file")
    p.add_argument("--worker-file", default=None,
                   help="also write a worker-only access file")
    p.set_defaults(fn=cmd_server_generate_access)

    # worker
    worker = sub.add_parser("worker", help="worker management")
    wsub = worker.add_subparsers(dest="worker_cmd", required=True)
    p = wsub.add_parser("start")
    _add_common(p)
    p.add_argument("--cpus", type=int, default=None)
    p.add_argument("--resource", action="append", default=None,
                   help='e.g. "gpus=[0,1]", "mem=sum(1024)", "x=range(1-5)"')
    p.add_argument("--coupling", default=None,
                   help='comma-separated group resources allocated together, '
                        'e.g. "cpus,gpus"')
    p.add_argument("--group", default=None,
                   help="multi-node gang group; defaults to the manager "
                        "allocation id under PBS/Slurm, else 'default'")
    p.add_argument("--no-hyper-threading", action="store_true")
    p.add_argument("--heartbeat", type=_parse_duration, default=8.0)
    p.add_argument("--time-limit", type=_parse_duration, default=None)
    p.add_argument("--idle-timeout", type=_parse_duration, default=None)
    p.add_argument("--on-server-lost",
                   choices=["stop", "finish-running", "reconnect"],
                   default="stop")
    p.add_argument("--reconnect-timeout", type=_parse_duration, default=60.0,
                   help="with --on-server-lost reconnect: give up after "
                        "this long without a successful re-registration "
                        "(0 = keep retrying forever)")
    p.add_argument("--manager", choices=["auto", "pbs", "slurm", "none"],
                   default="auto",
                   help="batch manager detection (time limit from walltime)")
    p.add_argument("--overview-interval", type=_parse_duration, default=0.0,
                   help="send hardware telemetry every N seconds")
    p.add_argument("--min-utilization", type=_parse_min_utilization,
                   default=0.0,
                   help="only accept tasks while at least this fraction of "
                        "the worker's cpus would be busy (0.0-1.0)")
    p.add_argument("--zero-worker", action="store_true",
                   help="benchmark mode: tasks succeed instantly, no spawn")
    p.add_argument("--runner-pool", type=int, default=-1, metavar="N",
                   help="warm runner processes for task spawn (-1 = "
                        "auto-size to CPU capacity, 0 = disable and spawn "
                        "in the worker's event loop)")
    p.add_argument("--uplink-flush", type=_parse_duration, default=0.002,
                   metavar="SECS",
                   help="coalesce task-state uplinks for up to this long "
                        "into one frame (0 = send each batch as ready)")
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve Prometheus metrics on this port (0 = "
                        "ephemeral; off by default — worker gauges still "
                        "piggyback on overview messages)")
    p.add_argument("--metrics-host", default="0.0.0.0", metavar="HOST",
                   help="bind address for the (unauthenticated) metrics "
                        "endpoint; use 127.0.0.1 behind a scraping sidecar")
    p.add_argument("--profile-hz", type=float, default=19.0, metavar="HZ",
                   help="always-on sampling profiler for the worker "
                        "process; per-plane shares piggyback on overview "
                        "messages for the fleet view (0 = off)")
    p.add_argument("--log-format", choices=["plain", "json"],
                   default=os.environ.get("HQ_LOG_FORMAT", "plain"),
                   help="json: one JSON object per log line with "
                        "task/worker correlation fields")
    p.add_argument("--shard", type=int, default=None, metavar="K",
                   help="federation: register with shard K instead of a "
                        "random one (the coordinator may lend the worker "
                        "to other shards later)")
    p.set_defaults(fn=cmd_worker_start)
    p = wsub.add_parser("hw-detect", help="print detected node resources")
    _add_common(p)
    p.add_argument("--no-hyper-threading", action="store_true")
    p.set_defaults(fn=cmd_worker_hwdetect)
    p = wsub.add_parser("list")
    _add_common(p)
    p.add_argument("--all", action="store_true",
                   help="include disconnected workers")
    p.add_argument("--filter", choices=["running", "offline"], default=None)
    p.set_defaults(fn=cmd_worker_list)
    p = wsub.add_parser("stop")
    _add_common(p)
    p.add_argument("selector")
    p.add_argument("--shard", type=int, default=None, metavar="K",
                   help="federation: worker ids are per shard — which "
                        "shard's workers to stop")
    p.add_argument("--drain", action="store_true",
                   help="graceful: stop scheduling new tasks onto the "
                        "worker, let running tasks finish, then stop it")
    p.add_argument("--drain-timeout", type=_parse_duration, default=None,
                   metavar="SECS",
                   help="with --drain: escalate to an immediate (clean) "
                        "stop after this long — running tasks requeue "
                        "without a crash charge (default 120s)")
    p.set_defaults(fn=cmd_worker_stop)
    p = wsub.add_parser("info")
    _add_common(p)
    p.add_argument("worker_id", type=int)
    p.add_argument("--shard", type=int, default=None, metavar="K",
                   help="federation: which shard owns this worker id")
    p.set_defaults(fn=cmd_worker_info)
    p = wsub.add_parser("address")
    _add_common(p)
    p.add_argument("worker_id", type=int)
    p.add_argument("--shard", type=int, default=None, metavar="K",
                   help="federation: which shard owns this worker id")
    p.set_defaults(fn=cmd_worker_address)
    p = wsub.add_parser("wait", help="wait until N workers are connected")
    _add_common(p)
    p.add_argument("count", type=int)
    p.add_argument("--timeout", type=float, default=60.0)
    p.set_defaults(fn=cmd_worker_wait)
    p = wsub.add_parser("deploy-ssh", help="start workers on hosts via ssh")
    _add_common(p)
    p.add_argument("hostfile", help="file with one hostname per line")
    p.add_argument("--cpus", type=int, default=None)
    p.add_argument("--group", default="default")
    p.set_defaults(fn=cmd_worker_deploy_ssh)

    # submit
    def _add_submit_args(p):
        _add_common(p)
        p.add_argument("--name", default=None)
        p.add_argument("--cpus", default=None)
        p.add_argument("--resource", dest="resource_request", action="append")
        p.add_argument("--nodes", type=int, default=None)
        p.add_argument("--time-request", type=_parse_duration, default=None,
                       help="minimal remaining worker lifetime needed to "
                            "start the task (e.g. 30, 10min, 01:30:00)")
        p.add_argument("--time-limit", type=_parse_duration, default=None,
                       help="kill a task after this long (e.g. 30, 10min)")
        p.add_argument("--priority", type=int, default=0)
        p.add_argument("--weight", type=_parse_weight, default=None,
                       help="scheduler objective weight: biases which same-"
                            "priority job wins contended workers (default 1.0)")
        p.add_argument("--max-fails", type=int, default=None)
        p.add_argument("--crash-limit", type=_parse_crash_limit, default=5,
                       help="positive integer, 'never-restart' or 'unlimited'")
        p.add_argument("--array", default=None)
        p.add_argument("--each-line", default=None)
        p.add_argument("--from-json", default=None)
        p.add_argument("--from-stdin", action="store_true",
                       help="one task per stdin line (entry in HQ_ENTRY), "
                            "streamed to the server in chunks — the task "
                            "list is never buffered whole on either side")
        p.add_argument("--chunk-size", type=int, default=16384,
                       help="tasks per streamed submit chunk; arrays "
                            "larger than this use the pipelined chunked "
                            "ingest plane (0 disables chunking)")
        p.add_argument("--submit-window", type=int, default=None,
                       help="max in-flight unacked chunks "
                            "(default HQ_SUBMIT_WINDOW or 8)")
        p.add_argument("--env", action="append")
        p.add_argument("--cwd", default=None)
        p.add_argument("--stdout", default=None)
        p.add_argument("--stderr", default=None)
        p.add_argument("--stream", default=None,
                       help="stream task output into this directory (.hqs files)")
        p.add_argument("--pin", choices=["taskset", "omp"], default=None,
                       help="pin tasks to their claimed cpu indices")
        p.add_argument("--task-dir", action="store_true",
                       help="create a private task directory (HQ_TASK_DIR)")
        p.add_argument("--stdin", action="store_true")
        p.add_argument("--wait", action="store_true")
        p.add_argument("--progress", action="store_true",
                       help="show a progress line until the job finishes")
        p.add_argument("--on-notify", default=None, metavar="PROGRAM",
                       help="with --wait/--progress: run PROGRAM (serially) "
                            "for each `hq task notify` event of this job, "
                            "event JSON as the first argument")
        p.add_argument("--job", type=int, default=None,
                       help="submit into an existing open job")
        p.add_argument("--directives", choices=["auto", "file", "stdin", "off"],
                       default="auto",
                       help="parse #HQ directive lines from the submitted "
                            "script (stdin: from the --stdin payload)")
        p.add_argument("command", nargs=argparse.REMAINDER)
        p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("submit", help="submit a job")
    _add_submit_args(p)

    # job
    job = sub.add_parser("job", help="job inspection")
    jsub = job.add_subparsers(dest="job_cmd", required=True)
    p = jsub.add_parser("list")
    _add_common(p)
    p.add_argument("--all", action="store_true",
                   help="include finished/failed/canceled jobs")
    p.add_argument("--filter", default=None,
                   help="comma-separated job states to show "
                        "(opened,waiting,running,finished,failed,canceled)")
    p.add_argument("--verbose", action="store_true",
                   help="additional columns (cancel reason)")
    p.set_defaults(fn=cmd_job_list)
    for name, fn, extra in [
        ("info", cmd_job_info, ()),
        ("wait", cmd_job_wait, ()),
        ("progress", cmd_job_progress, ()),
        ("cancel", cmd_job_cancel, ()),
        ("forget", cmd_job_forget, ()),
        ("close", cmd_job_close, ()),
        ("pause", cmd_job_pause, ()),
        ("resume", cmd_job_resume, ()),
    ]:
        p = jsub.add_parser(name)
        _add_common(p)
        p.add_argument("selector")
        p.set_defaults(fn=fn)
    p = jsub.add_parser("summary", help="job counts per status")
    _add_common(p)
    p.set_defaults(fn=cmd_job_summary)
    p = jsub.add_parser(
        "timeline",
        help="task lifecycle timeline: per-phase percentiles + slowest "
             "tasks (submit -> queued -> assigned -> spawned -> finished)",
    )
    _add_common(p)
    p.add_argument("selector")
    p.add_argument("--tasks", action="store_true",
                   help="include every task's timestamps (json mode)")
    p.set_defaults(fn=cmd_job_timeline)
    p = jsub.add_parser(
        "accounting",
        help="usage ledger: task/cpu/gpu/wait seconds and crash-charged "
             "retries per job, folded from the journal (survives "
             "restarts and live migration exactly-once)",
    )
    _add_common(p)
    p.add_argument("selector")
    p.set_defaults(fn=cmd_job_accounting)
    p = jsub.add_parser("submit", help="alias of top-level `hq submit`")
    _add_submit_args(p)
    p = jsub.add_parser("task-ids", help="print task ids of selected jobs")
    _add_common(p)
    p.add_argument("selector")
    p.add_argument("--filter", default=None,
                   help="comma-separated task statuses (e.g. failed,running)")
    p.set_defaults(fn=cmd_job_task_ids)
    p = jsub.add_parser("cat")
    _add_common(p)
    p.add_argument("selector")
    p.add_argument("stream", choices=["stdout", "stderr"])
    p.add_argument("--tasks", default=None)
    p.set_defaults(fn=cmd_job_cat)
    p = jsub.add_parser("open")
    _add_common(p)
    p.add_argument("--name", default=None)
    p.add_argument("--max-fails", type=int, default=None)
    p.set_defaults(fn=cmd_job_open)
    p = jsub.add_parser("submit-file", help="submit a TOML job definition")
    _add_common(p)
    p.add_argument("job_file")
    p.add_argument("--wait", action="store_true")
    p.add_argument("--chunk-size", type=int, default=16384,
                   help="stream jobfiles larger than this many tasks in "
                        "chunks over the pipelined ingest plane (0 = one "
                        "monolithic submit)")
    p.set_defaults(fn=cmd_job_submit_file)

    # alloc
    alloc = sub.add_parser("alloc", help="automatic allocation (PBS/Slurm)")
    asub = alloc.add_subparsers(dest="alloc_cmd", required=True)

    def add_alloc_params(p):
        # NOTE: manager must come after the options on the command line OR
        # options before the positional; argparse interleaves fine as long as
        # extra manager args are passed behind a literal "--"
        p.add_argument("--backlog", type=int, default=1)
        p.add_argument("--workers-per-alloc", type=int, default=1)
        p.add_argument("--max-worker-count", type=int, default=None)
        p.add_argument("--time-limit", type=_parse_duration, default=3600.0)
        p.add_argument("--idle-timeout", type=_parse_duration, default=300.0)
        p.add_argument("--name", default=None)
        p.add_argument("--worker-args", action="append")
        p.add_argument("--min-utilization", type=_parse_min_utilization,
                       default=0.0,
                       help="spawned workers only take tasks while at least "
                            "this fraction of their cpus stays busy")
        p.add_argument("--worker-start-cmd", default=None,
                       help="shell command run before each worker starts")
        p.add_argument("--worker-stop-cmd", default=None,
                       help="shell command run after the worker terminates "
                            "(best-effort)")
        p.add_argument("--worker-wrap-cmd", default=None,
                       help="command prepended to `hq worker start ...`")
        p.add_argument("--worker-time-limit", type=_parse_duration,
                       default=None,
                       help="stop workers this long after start (default: "
                            "the allocation time limit)")
        p.add_argument("--on-server-lost",
                       choices=["stop", "finish-running", "reconnect"],
                       default="finish-running")
        p.add_argument("--no-dry-run", action="store_true",
                       help="skip the probing allocation submit on `alloc add`")
        p.add_argument("manager", choices=["pbs", "slurm", "local"])
        p.add_argument("additional_args", nargs="*",
                       help="extra qsub/sbatch arguments after --")

    p = asub.add_parser("add")
    _add_common(p)
    add_alloc_params(p)
    p.set_defaults(fn=cmd_alloc_add)
    p = asub.add_parser("dry-run")
    _add_common(p)
    add_alloc_params(p)
    p.set_defaults(fn=cmd_alloc_dry_run)
    p = asub.add_parser("list")
    _add_common(p)
    p.set_defaults(fn=cmd_alloc_list)
    p = asub.add_parser("log", help="show an allocation's stdout/stderr")
    _add_common(p)
    p.add_argument("allocation_id")
    p.add_argument("channel", choices=["stdout", "stderr"])
    p.set_defaults(fn=cmd_alloc_log)
    p = asub.add_parser(
        "events", help="scale decision records (why did/didn't it scale)"
    )
    _add_common(p)
    p.add_argument("queue_id", type=int, nargs="?", default=None)
    p.set_defaults(fn=cmd_alloc_events)
    for name, fn in [("info", cmd_alloc_info), ("remove", cmd_alloc_remove),
                     ("pause", cmd_alloc_pause), ("resume", cmd_alloc_pause)]:
        p = asub.add_parser(name)
        _add_common(p)
        p.add_argument("queue_id", type=int)
        p.set_defaults(fn=fn)

    # journal
    journal = sub.add_parser("journal", help="event journal")
    josub = journal.add_subparsers(dest="journal_cmd", required=True)
    p = josub.add_parser("export", help="dump a journal file as NDJSON")
    _add_common(p)
    p.add_argument("journal_file")
    p.add_argument("--salvage", action="store_true",
                   help="skip mid-file CRC-corrupt records instead of "
                        "failing loudly")
    p.set_defaults(fn=cmd_journal_export)
    p = josub.add_parser("replay", help="replay a journal file as NDJSON")
    _add_common(p)
    p.add_argument("journal_file")
    p.add_argument("--salvage", action="store_true",
                   help="skip mid-file CRC-corrupt records instead of "
                        "failing loudly")
    p.set_defaults(fn=cmd_journal_replay)
    p = josub.add_parser("report", help="static HTML analytics report")
    _add_common(p)
    p.add_argument("journal_file")
    p.add_argument("--output", default=None)
    p.add_argument("--start-time", type=float, default=None,
                   help="window start, seconds from the first record")
    p.add_argument("--end-time", type=float, default=None,
                   help="window end, seconds from the first record")
    p.set_defaults(fn=cmd_journal_report)
    p = josub.add_parser("flush")
    _add_common(p)
    p.set_defaults(fn=cmd_journal_flush)
    p = josub.add_parser("prune")
    _add_common(p)
    p.set_defaults(fn=cmd_journal_prune)
    p = josub.add_parser(
        "compact",
        help="snapshot live state + GC the superseded journal prefix",
    )
    _add_common(p)
    p.set_defaults(fn=cmd_journal_compact)
    p = josub.add_parser(
        "info", help="journal/snapshot sizes and compaction stats"
    )
    _add_common(p)
    p.set_defaults(fn=cmd_journal_info)
    p = josub.add_parser("stream", help="stream live server events as NDJSON")
    _add_common(p)
    p.add_argument("--history", action="store_true",
                   help="replay journaled history first")
    p.add_argument("--follow", action="store_true",
                   help="keep streaming live events")
    p.add_argument("--filter", action="append",
                   help="event kind prefix filter (job/task/worker/alloc)")
    p.set_defaults(fn=cmd_journal_stream)

    # task
    task = sub.add_parser("task", help="task inspection")
    tsub = task.add_subparsers(dest="task_cmd", required=True)
    p = tsub.add_parser("list")
    _add_common(p)
    p.add_argument("selector")
    p.set_defaults(fn=cmd_task_list)
    p = tsub.add_parser("info", help="detailed task info")
    _add_common(p)
    p.add_argument("selector")
    p.add_argument("tasks", nargs="?", default=None,
                   help="task id selector (e.g. 1-3,7); all tasks if omitted")
    p.set_defaults(fn=cmd_task_info)
    p = tsub.add_parser("explain", help="why is this task (not) running")
    _add_common(p)
    p.add_argument("target",
                   help="<job> or <job>.<task> (task defaults to the "
                        "job's first pending task)")
    p.add_argument("task_id", type=int, nargs="?", default=None,
                   help="task id (legacy two-argument form)")
    p.set_defaults(fn=cmd_task_explain)
    p = tsub.add_parser(
        "trace",
        help="the task's distributed trace: client submit -> journal "
             "commit -> solve/dispatch -> worker spawn -> completion",
    )
    _add_common(p)
    p.add_argument("target",
                   help="<job> or <job>.<task> (task defaults to 0)")
    p.add_argument("task_id", type=int, nargs="?", default=None,
                   help="task id (two-argument form)")
    p.set_defaults(fn=cmd_task_trace)
    p = tsub.add_parser("notify",
                        help="send a notification from inside a task")
    _add_common(p)
    p.add_argument("payload", nargs="?", default="")
    p.set_defaults(fn=cmd_task_notify)

    # output-log
    olog = sub.add_parser("output-log", help="read streamed task output")
    osub = olog.add_subparsers(dest="log_cmd", required=True)
    for name in ("summary", "jobs", "cat", "show", "export"):
        p = osub.add_parser(name)
        _add_common(p)
        p.add_argument("stream_dir")
        if name == "cat":
            p.add_argument("channel", choices=["stdout", "stderr"])
            p.add_argument("--tasks", default=None)
        p.set_defaults(fn=cmd_output_log)

    # dashboard
    p = sub.add_parser("dashboard", help="live terminal overview")
    _add_common(p)
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--replay", default=None, metavar="JOURNAL",
                   help="replay a finished journal offline with time scrub")
    p.set_defaults(fn=cmd_dashboard)

    # top: push-fed live cluster view (subscribe RPC — no polling)
    p = sub.add_parser(
        "top", help="live cluster view streamed from the subscribe RPC; "
                    "against a federation root: the whole fleet"
    )
    _add_common(p)
    p.add_argument("--interval", type=float, default=1.0,
                   help="metric-sample refresh interval (seconds)")
    p.add_argument("--once", action="store_true",
                   help="print one sample and exit (scriptable)")
    p.add_argument("--shard", type=int, default=None, metavar="K",
                   help="federation: focus one shard with the classic "
                        "single-server view (default: fleet view)")
    p.set_defaults(fn=cmd_top)

    # fleet: cross-shard observability over a federation root (ISSUE 15)
    fleet = sub.add_parser(
        "fleet",
        help="fleet observability over a federation root: metrics "
             "federation + stitched trace export",
    )
    fsub = fleet.add_subparsers(dest="fleet_cmd", required=True)
    p = fsub.add_parser(
        "metrics-proxy",
        help="serve one /metrics endpoint re-exporting every shard's "
             "exposition under a shard label (dead shards appear as "
             "hq_federation_shard_up 0)",
    )
    _add_common(p)
    p.add_argument("--port", type=int, default=9090,
                   help="port to serve on (0 = ephemeral, printed)")
    p.add_argument("--host", default="0.0.0.0")
    p.set_defaults(fn=cmd_fleet_metrics_proxy)
    p = fsub.add_parser(
        "trace-export",
        help="one Perfetto timeline for the whole fleet: a row group "
             "per shard (ticks, boots/promotions, lease epochs, lending "
             "moves, elasticity verdicts)",
    )
    _add_common(p)
    p.add_argument("output", help="output path (e.g. fleet-trace.json)")
    p.set_defaults(fn=cmd_fleet_trace_export)
    p = fsub.add_parser(
        "status",
        help="ownership map: per-shard owned-job counts, in-flight "
             "migrations with their protocol phase, and the last "
             "rebalance verdict",
    )
    _add_common(p)
    p.set_defaults(fn=cmd_fleet_status)
    p = fsub.add_parser(
        "migrate",
        help="live-migrate one job to another shard: the source seals "
             "and drains it, the destination imports exactly-once, the "
             "ownership log journals the handoff (crash-safe at every "
             "phase; re-run with the same arguments to resume)",
    )
    _add_common(p)
    p.add_argument("job_id", type=int, nargs="?", default=None)
    p.add_argument("to_shard", type=int, nargs="?", default=None,
                   metavar="SHARD")
    p.add_argument("--recover", action="store_true",
                   help="re-drive every in-flight migration intent left "
                        "in the ownership log by a crashed driver, then "
                        "exit (no job/shard arguments needed)")
    p.set_defaults(fn=cmd_fleet_migrate)
    p = fsub.add_parser(
        "accounting",
        help="per-label usage rollup for every shard (task/cpu/gpu/wait "
             "seconds, crash retries) from each shard's ledger",
    )
    _add_common(p)
    p.set_defaults(fn=cmd_fleet_accounting)
    p = fsub.add_parser(
        "profile",
        help="folded profiler stacks from every shard in one stream "
             "(equivalent to `hq server profile --shard all`)",
    )
    _add_common(p)
    p.add_argument("--seconds", type=float, default=0.0, metavar="N",
                   help="sample a fresh N-second window on each shard")
    p.add_argument("--format", choices=["folded", "json"],
                   default="folded")
    p.set_defaults(fn=cmd_fleet_profile)

    # alerts: SLO burn-rate alert state (ISSUE 18)
    p = sub.add_parser(
        "alerts",
        help="firing SLO burn-rate alerts + recent transitions "
             "(tick latency, submit-ack, queue age, restore duration, "
             "shard availability)",
    )
    _add_common(p)
    p.add_argument("--shard", default=None, metavar="K|all",
                   help="federation: which shard to query (default all)")
    p.set_defaults(fn=cmd_alerts)

    # doc + completion
    p = sub.add_parser("doc", help="show documentation topics")
    _add_common(p)
    p.add_argument("topic", nargs="?", default=None)
    p.set_defaults(fn=cmd_doc)
    p = sub.add_parser("generate-completion",
                       help="shell completion script")
    _add_common(p)
    p.add_argument("shell", nargs="?", default="bash",
                   choices=["bash", "zsh", "fish"])
    p.set_defaults(fn=cmd_generate_completion)

    return parser


def _parse_explain_target(args) -> tuple[int, int | None]:
    """`hq task explain <job>[.<task>]` (or legacy `<job> <task>`)."""
    target = str(args.target)
    if args.task_id is not None:
        return int(target), args.task_id
    if "." in target:
        job_s, _, task_s = target.partition(".")
        try:
            return int(job_s), int(task_s)
        except ValueError:
            fail(f"invalid task selector {target!r} "
                 "(expected <job> or <job>.<task>)")
    try:
        return int(target), None
    except ValueError:
        fail(f"invalid job id {target!r}")


def cmd_task_explain(args) -> None:
    job_id, task_id = _parse_explain_target(args)
    with _session(args) as session:
        result = session.request(
            {"op": "task_explain", "job_id": job_id, "task_id": task_id}
        )
    result.pop("op", None)
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(result)
        return
    task_label = f"{result.get('job', job_id)}.{result.get('task', task_id)}"
    out.message(f"task {task_label}: {result['state']}")
    # the verdict line: reason code + human detail + deferral age
    reason = result.get("reason")
    if reason:
        line = f"verdict: {reason}"
        deferred = result.get("deferred_ticks") or 0
        if deferred:
            line += f" (deferred for {deferred} consecutive tick(s))"
        out.message(line)
        if result.get("reason_detail"):
            out.message(f"  {result['reason_detail']}")
    if result.get("solver_backend"):
        line = f"solver backend: {result['solver_backend']}"
        if result.get("solver_backend_reason"):
            line += f" ({result['solver_backend_reason']})"
        if result.get("solver_pipelined"):
            line += " [pipelined]"
        out.message(line)
    pol = result.get("policy")
    if pol:
        pred = pol.get("prediction") or {}
        line = (
            f"policy: {pol.get('source')} "
            f"({pol.get('affinity_classes', 0)} affinity class(es), "
            f"boost range {pol.get('boost_range')}"
        )
        if pred.get("enabled"):
            line += f", predictor hit rate {pred.get('hit_rate', 0.0):.2f}"
        line += ")"
        out.message(line)
    if result["n_waiting_deps"]:
        out.message(f"waiting for {result['n_waiting_deps']} dependencies")
    workers = result["workers"]
    runnable = [w for w in workers if w["runnable"]]
    out.message(
        f"workers considered: {len(workers)}, "
        f"could run it now: {len(runnable)}"
    )
    for w in workers:
        if w["runnable"]:
            out.message(f"worker {w['id']} ({w['hostname']}): can run")
        else:
            for v in w["variants"]:
                for blocked in v["blocked"]:
                    out.message(
                        f"worker {w['id']} ({w['hostname']}) "
                        f"variant {v['variant']}: {blocked}"
                    )


def cmd_task_trace(args) -> None:
    """The task's assembled distributed trace: every span from client
    submit through journal commit, solve dispatch, worker spawn, run and
    completion uplink (`hq task trace <job>.<task>`)."""
    job_id, task_id = _parse_explain_target(args)
    with _session(args) as session:
        result = session.request(
            {"op": "task_trace", "job_id": job_id, "task_id": task_id or 0}
        )
    result.pop("op", None)
    out = make_output(args.output_mode)
    if args.output_mode == "json":
        out.value(result)
        return
    spans = result.get("spans") or []
    out.message(
        f"task {result['job']}.{result['task']} trace "
        f"{result['trace_id']} — {len(spans)} span(s), "
        f"{'closed' if result.get('closed') else 'open'}, "
        f"wall {result.get('wall_s', 0.0) * 1e3:.2f} ms"
    )
    if result.get("missing_hops") and result.get("closed"):
        out.message(
            "  missing hops: " + ", ".join(result["missing_hops"])
        )
    for note in result.get("annotations") or ():
        kind = note.get("kind")
        if kind == "lend":
            out.message(
                f"  fleet: ran on worker {note.get('worker')} borrowed "
                f"from shard {note.get('home_shard')} "
                f"(host shard {note.get('host_shard')})"
            )
        elif kind == "failover":
            out.message(
                f"  fleet: survived failover of shard "
                f"{note.get('shard')} (lease epoch "
                f"{note.get('lease_epoch')})"
            )
        else:
            out.message(f"  fleet: {note}")
    if not spans:
        return
    t_base = min(s["t0"] for s in spans)
    out.message(
        f"{'offset ms':>10} {'dur ms':>10}  "
        f"{'span':<16} {'proc':<12} inst"
    )
    for s in spans:
        out.message(
            f"{(s['t0'] - t_base) * 1e3:>10.2f} "
            f"{(s['t1'] - s['t0']) * 1e3:>10.2f}  "
            f"{s['name']:<16} {s['proc']:<12} {s['instance']}"
        )


def cmd_top(args) -> None:
    """Live cluster view fed by the subscribe RPC (push, not polling);
    a federation root renders the fleet view unless --shard focuses."""
    from hyperqueue_tpu.client.top import run_top

    rc = run_top(
        _server_dir(args),
        interval=args.interval,
        once=args.once,
        output_mode=args.output_mode,
        shard=getattr(args, "shard", None),
    )
    if rc:
        raise SystemExit(rc)


def cmd_fleet_metrics_proxy(args) -> None:
    """`hq fleet metrics-proxy`: one scrape covers the fleet — every
    shard's exposition under a `shard` label, dead shards visible as
    hq_federation_shard_up 0 (ISSUE 15)."""
    from hyperqueue_tpu.client.fleet import run_metrics_proxy

    try:
        run_metrics_proxy(_server_dir(args), args.port, host=args.host)
    except ValueError as e:
        fail(str(e))
    except KeyboardInterrupt:
        pass


def cmd_fleet_trace_export(args) -> None:
    """`hq fleet trace-export <out.json>`: the whole fleet as one
    Perfetto timeline, a row group per shard."""
    from hyperqueue_tpu.client.fleet import export_fleet_trace

    try:
        trace = export_fleet_trace(_server_dir(args))
    except ValueError as e:
        fail(str(e))
    with open(args.output, "w") as f:
        json.dump(trace, f)
    meta = trace.get("metadata") or {}
    down = meta.get("down") or []
    make_output(args.output_mode).message(
        f"fleet trace written to {args.output} "
        f"({meta.get('shards', 0)} shard(s), "
        f"{len(trace.get('traceEvents') or ())} event(s)"
        + (f", DOWN: {down}" if down else "")
        + "); load at ui.perfetto.dev"
    )


def cmd_fleet_profile(args) -> None:
    """`hq fleet profile`: folded profiler stacks from every shard in one
    stream. On a classic server dir it degrades to a single-server
    profile (same convention as `hq fleet accounting`)."""
    fed = serverdir.load_federation(_server_dir(args))
    args.shard = "all" if fed is not None else None
    cmd_server_profile(args)


def cmd_fleet_status(args) -> None:
    """`hq fleet status`: the ownership map as operators read it —
    who owns what, what is mid-move, what the rebalancer last did."""
    from hyperqueue_tpu.client.connection import ClientSession
    from hyperqueue_tpu.client.fleet import shard_count_of
    from hyperqueue_tpu.utils.ownership import OwnershipStore

    root = _server_dir(args)
    try:
        n = shard_count_of(root)
    except ValueError as e:
        fail(str(e))
    omap = OwnershipStore(root).load()
    moved_in = omap.owned_counts()
    lines = [
        f"federation: {max(n, omap.shard_count)} shard(s) "
        f"(base {omap.base_shard_count}), "
        f"ownership epoch {omap.epoch}"
    ]
    for k in range(max(n, omap.shard_count)):
        shard_dir = serverdir.shard_path(root, k)
        try:
            with ClientSession(shard_dir, retry_window=2.0) as session:
                jobs = session.request({"op": "job_list"}).get("jobs", [])
            owned = len(jobs)
            live = sum(
                1 for j in jobs
                if j.get("status") in ("running", "waiting", "opened")
            )
            detail = f"{owned} job(s) owned, {live} active"
            if moved_in.get(k):
                detail += f", {moved_in[k]} migrated in"
        except (OSError, ClientError, FileNotFoundError) as e:
            detail = f"DOWN ({e})"
        lines.append(f"  shard {k}: {detail}")
    in_flight = omap.in_flight()
    if in_flight:
        lines.append("in-flight migrations:")
        for rec in in_flight:
            lines.append(
                f"  {rec['mig']}: job {rec['job']} shard {rec['from']} "
                f"-> {rec['to']} ({rec['phase']})"
            )
    else:
        lines.append("in-flight migrations: none")
    if omap.verdicts:
        v = omap.verdicts[-1]
        moved = v.get("moved")
        what = (f"moved job {moved}" if moved
                else "no move" + (f" (job {v['job']})" if v.get("job")
                                  else ""))
        lines.append(
            f"last rebalance: {what} shard {v.get('from')} -> "
            f"{v.get('to')} — {v.get('reason', '')}"
        )
    make_output(args.output_mode).message("\n".join(lines))


def cmd_fleet_migrate(args) -> None:
    """`hq fleet migrate <job> <shard>` (or `--recover`): drive the
    exactly-once live migration protocol from the CLI."""
    from hyperqueue_tpu.server.federation import (
        MigrationError,
        drive_migration,
        recover_migrations,
    )

    root = _server_dir(args)
    out = make_output(args.output_mode)
    if args.recover:
        moves = recover_migrations(root)
        if not moves:
            out.message("no in-flight migrations to recover")
        for move in moves:
            out.message(
                f"recovered {move['mig']}: job {move['job']} shard "
                f"{move['from']} -> {move['to']} ({move['seconds']}s)"
            )
        return
    if args.job_id is None or args.to_shard is None:
        fail("usage: hq fleet migrate <job_id> <to_shard> "
             "(or hq fleet migrate --recover)")
    try:
        move = drive_migration(root, args.job_id, args.to_shard)
    except MigrationError as e:
        fail(str(e))
    except Exception as e:  # noqa: BLE001 - MigrationClaimed and friends
        fail(str(e))
    out.message(
        f"migrated job {move['job']}: shard {move['from']} -> "
        f"{move['to']} ({move['mig']}, {move['seconds']}s)"
    )


def cmd_job_submit_file(args) -> None:
    from hyperqueue_tpu.client.jobfile import JobFileError, load_job_file

    try:
        job_desc = load_job_file(args.job_file, os.getcwd())
    except JobFileError as e:
        fail(str(e))
    with _session(args) as session:
        from hyperqueue_tpu.transport.framing import attach_trace
        from hyperqueue_tpu.utils.trace import new_trace_id

        tasks = job_desc.get("tasks") or []
        chunk_size = max(getattr(args, "chunk_size", 16384) or 0, 0)
        if chunk_size and len(tasks) > chunk_size:
            # big jobfile: stream the task graph in chunks (deps always
            # reference tasks defined ABOVE, so in-order chunking keeps
            # every dependency in an earlier-or-same chunk)
            from hyperqueue_tpu.client.connection import SubmitStream

            stream = SubmitStream(session, {
                "name": job_desc["name"],
                "submit_dir": job_desc["submit_dir"],
                "max_fails": job_desc.get("max_fails"),
            })
            for start in range(0, len(tasks), chunk_size):
                stream.send_chunk(tasks=tasks[start:start + chunk_size])
            job_id, n_tasks = stream.finish()
            response = {"job_id": job_id, "n_tasks": n_tasks}
        else:
            response = session.request(attach_trace(
                {"op": "submit", "job": job_desc},
                new_trace_id(), sent_at=clock.now(),
            ))
        job_id = response["job_id"]
        out = make_output(args.output_mode)
        if args.output_mode == "quiet":
            out.value(job_id)
        else:
            out.message(
                f"Job submitted successfully, job ID: {job_id}"
                f" ({response['n_tasks']} tasks)"
            )
        if args.wait:
            info = session.request({"op": "job_wait", "job_ids": [job_id]})
            job = info["jobs"][0] if info["jobs"] else None
            if job is None or job["counters"]["failed"] or job["counters"]["canceled"]:
                raise SystemExit(1)


def main(argv: list[str] | None = None) -> None:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    args = build_parser().parse_args(argv)
    if getattr(args, "fn", None) is cmd_submit:  # `submit` or `job submit`
        if args.command and args.command[0] == "--":
            args.command = args.command[1:]
        # #HQ directives from the submitted script; explicit CLI args win
        # because they come later in the re-parsed argv
        from hyperqueue_tpu.client.directives import (
            parse_directives,
            parse_directives_text,
            should_parse,
        )

        stdin_data = None
        tokens: list[str] = []
        if args.directives == "stdin":
            # the script arrives on stdin (used with --stdin); directives are
            # parsed from it rather than from the command path
            if not args.stdin:
                fail("--directives=stdin requires --stdin (the script is "
                     "read from standard input and passed to the task)")
            stdin_data = sys.stdin.buffer.read()
            tokens = parse_directives_text(stdin_data.decode(errors="replace"))
        elif args.command and should_parse(args.command[0], args.directives):
            tokens = parse_directives(args.command[0])
        if tokens:
            idx = argv.index("submit")
            args = build_parser().parse_args(
                argv[: idx + 1] + tokens + argv[idx + 1 :]
            )
            if args.command and args.command[0] == "--":
                args.command = args.command[1:]
        if stdin_data is not None:
            args._stdin_data = stdin_data
    try:
        args.fn(args)
    except (ClientError, ValueError) as e:
        # user-input errors (bad amounts, selectors, resource defs) must be
        # one clean line, not a traceback
        fail(str(e))
    except FileNotFoundError as e:
        fail(str(e))
    except BrokenPipeError:
        # `hq ... | head` closed the pipe: exit quietly like other CLIs.
        # Point stdout at devnull so interpreter shutdown's implicit flush
        # does not raise a second time.
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        raise SystemExit(141)
    except KeyboardInterrupt:
        raise SystemExit(130)


if __name__ == "__main__":
    main()
