"""#HQ directive parsing from submitted shell scripts.

Reference: crates/hyperqueue/src/client/commands/submit/directives.rs +
docs/jobs/directives.md — lines starting with `#HQ ` in the leading comment
block of a submitted script contribute submit arguments; explicit CLI
arguments take precedence.
"""

from __future__ import annotations

import shlex
from pathlib import Path

DIRECTIVE_PREFIX = "#HQ "
MAX_SCAN_BYTES = 32 * 1024


def parse_directives(path: str | Path) -> list[str]:
    """Extract tokens from #HQ lines in the leading comment block."""
    try:
        with open(path, "r", errors="replace") as f:
            text = f.read(MAX_SCAN_BYTES)
    except OSError:
        return []
    return parse_directives_text(text)


def parse_directives_text(text: str) -> list[str]:
    """#HQ tokens from script text (used for `--directives stdin`, where the
    script arrives on standard input — reference DirectivesMode::Stdin)."""
    tokens: list[str] = []
    for i, line in enumerate(text[:MAX_SCAN_BYTES].splitlines()):
        stripped = line.strip()
        if i == 0 and stripped.startswith("#!"):
            continue
        if not stripped:
            continue
        if not stripped.startswith("#"):
            break  # directives live only in the leading comment block
        if stripped.startswith(DIRECTIVE_PREFIX.rstrip()) and (
            stripped.startswith(DIRECTIVE_PREFIX) or stripped == "#HQ"
        ):
            tokens.extend(shlex.split(stripped[len(DIRECTIVE_PREFIX):]))
    return tokens


def should_parse(path: str, mode: str) -> bool:
    if mode == "off":
        return False
    if mode == "file":
        return True
    # auto: only .sh files that exist
    return path.endswith(".sh") and Path(path).exists()
