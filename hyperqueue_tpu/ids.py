"""Core identifier types.

Reference: crates/tako/src/internal/common/ids.rs:5-60 — TaskId is a packed
(JobId u32, JobTaskId u32) pair; WorkerId / InstanceId / ResourceId are u32
newtypes. We keep them as plain ints (Python) packed the same way so a task id
is a single int64-compatible scalar — which is exactly what the dense scheduler
snapshot wants.
"""

from __future__ import annotations

# A TaskId packs (job_id << 32) | job_task_id into one int.
TASK_ID_BITS = 32
TASK_ID_MASK = (1 << TASK_ID_BITS) - 1


def make_task_id(job_id: int, job_task_id: int) -> int:
    if not (0 <= job_task_id <= TASK_ID_MASK and 0 <= job_id <= TASK_ID_MASK):
        raise ValueError(f"task id out of range: {job_id}@{job_task_id}")
    return (job_id << TASK_ID_BITS) | job_task_id


def task_id_job(task_id: int) -> int:
    return task_id >> TASK_ID_BITS


def task_id_task(task_id: int) -> int:
    return task_id & TASK_ID_MASK


def format_task_id(task_id: int) -> str:
    return f"{task_id_job(task_id)}@{task_id_task(task_id)}"


def parse_task_id(text: str) -> int:
    job, _, task = text.partition("@")
    return make_task_id(int(job), int(task))


class IdCounter:
    """Monotonic id allocator (1-based, 0 reserved as 'none').

    With ``stride > 1`` the counter allocates only ids congruent to
    ``start`` modulo ``stride`` — the static job-id partition of a
    federated server shard (shard k of N allocates k+1, k+1+N, ...), so
    N shards can allocate concurrently without coordination and a job id
    alone names its owning shard.
    """

    __slots__ = ("_next", "_stride")

    def __init__(self, start: int = 1, stride: int = 1):
        self._next = start
        self._stride = max(int(stride), 1)

    def next(self) -> int:
        value = self._next
        self._next += self._stride
        return value

    def peek(self) -> int:
        return self._next

    def ensure_above(self, used: int) -> None:
        # advance past `used` while keeping the congruence class: a
        # restored shard replays jobs from its own partition, but the
        # snapshot's next_job_id watermark may land mid-class
        if used >= self._next:
            steps = (used - self._next) // self._stride + 1
            self._next += steps * self._stride
