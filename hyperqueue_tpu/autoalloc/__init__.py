"""Automatic allocation: elastic workers via PBS/Slurm.

Reference: crates/hyperqueue/src/server/autoalloc/ — allocation queues with
backlog, workers-per-alloc and limits; a periodic process refreshes allocation
statuses via qstat/sacct, plans submissions against the scheduler's
fake-worker query, submits qsub/sbatch scripts that start workers, and backs
off (eventually pausing the queue) on repeated failures.
"""
