"""Autoalloc state: queues and allocations.

Reference: crates/hyperqueue/src/server/autoalloc/state.rs:22-399 —
AllocationQueue descriptors and the Allocation lifecycle
Queued -> Running -> Finished/Failed, plus the rate limiter with exponential
backoff that pauses repeatedly-failing queues (process.rs:881,1209).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from hyperqueue_tpu.ids import IdCounter

MAX_SUBMIT_FAILS_BEFORE_PAUSE = 3
BACKOFF_BASE_SECS = 2.0
BACKOFF_MAX_SECS = 300.0


@dataclass
class QueueParams:
    manager: str  # "pbs" | "slurm"
    backlog: int = 1              # allocations kept in the batch queue
    workers_per_alloc: int = 1
    max_worker_count: int = 0     # 0 = unlimited
    time_limit_secs: float = 3600.0
    name: str = ""
    worker_args: list[str] = field(default_factory=list)  # extra hq args
    additional_args: list[str] = field(default_factory=list)  # qsub/sbatch args
    idle_timeout_secs: float = 300.0
    # reference SharedQueueOpts (commands/autoalloc.rs:96-180)
    worker_start_cmd: str = ""    # shell line run before each worker starts
    worker_stop_cmd: str = ""     # shell line run after the worker terminates
    worker_wrap_cmd: str = ""     # prefix for the `hq worker start` command
    worker_time_limit_secs: float = 0.0  # 0 = allocation time limit
    on_server_lost: str = "finish-running"

    def to_wire(self) -> dict:
        return self.__dict__.copy()

    @classmethod
    def from_wire(cls, data: dict) -> "QueueParams":
        return cls(**{k: v for k, v in data.items() if k in cls.__dataclass_fields__})


@dataclass
class Allocation:
    allocation_id: str          # manager job id (qsub/sbatch output)
    queue_id: int
    worker_count: int
    status: str = "queued"      # queued | running | finished | failed
    queued_at: float = field(default_factory=time.time)
    started_at: float = 0.0
    ended_at: float = 0.0
    connected_workers: set[int] = field(default_factory=set)
    workdir: str = ""           # holds hq-submit.sh + manager stdout/stderr

    @property
    def is_active(self) -> bool:
        return self.status in ("queued", "running")

    def to_wire(self) -> dict:
        return {
            "id": self.allocation_id,
            "queue": self.queue_id,
            "worker_count": self.worker_count,
            "status": self.status,
            "queued_at": self.queued_at,
            "started_at": self.started_at,
            "ended_at": self.ended_at,
            "workers": sorted(self.connected_workers),
            "workdir": self.workdir,
        }


@dataclass
class AllocationQueue:
    queue_id: int
    params: QueueParams
    state: str = "running"  # running | paused
    allocations: dict[str, Allocation] = field(default_factory=dict)
    consecutive_failures: int = 0
    next_submit_at: float = 0.0

    def active_allocations(self) -> list[Allocation]:
        return [a for a in self.allocations.values() if a.is_active]

    def queued_allocations(self) -> list[Allocation]:
        return [a for a in self.allocations.values() if a.status == "queued"]

    def active_worker_count(self) -> int:
        return sum(a.worker_count for a in self.active_allocations())

    def on_submit_ok(self) -> None:
        self.consecutive_failures = 0
        self.next_submit_at = 0.0

    def on_submit_fail(self) -> bool:
        """Returns True if the queue should be paused."""
        self.consecutive_failures += 1
        backoff = min(
            BACKOFF_BASE_SECS * (2 ** (self.consecutive_failures - 1)),
            BACKOFF_MAX_SECS,
        )
        self.next_submit_at = time.time() + backoff
        return self.consecutive_failures >= MAX_SUBMIT_FAILS_BEFORE_PAUSE

    def can_submit_now(self) -> bool:
        return self.state == "running" and time.time() >= self.next_submit_at

    def to_wire(self) -> dict:
        return {
            "id": self.queue_id,
            "state": self.state,
            "params": self.params.to_wire(),
            "allocations": [a.to_wire() for a in self.allocations.values()],
            "consecutive_failures": self.consecutive_failures,
        }


class AutoAllocState:
    def __init__(self):
        self.queues: dict[int, AllocationQueue] = {}
        self.queue_id_counter = IdCounter()

    def add_queue(self, params: QueueParams) -> AllocationQueue:
        queue = AllocationQueue(self.queue_id_counter.next(), params)
        self.queues[queue.queue_id] = queue
        return queue

    def find_allocation(self, allocation_id: str):
        for queue in self.queues.values():
            alloc = queue.allocations.get(allocation_id)
            if alloc is not None:
                return queue, alloc
        return None, None
