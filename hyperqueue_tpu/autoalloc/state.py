"""Autoalloc state: queues and allocations.

Reference: crates/hyperqueue/src/server/autoalloc/state.rs:22-399 —
AllocationQueue descriptors and the Allocation lifecycle
Queued -> Running -> Finished/Failed, plus the rate limiter with exponential
backoff that pauses repeatedly-failing queues (process.rs:881,1209).

ISSUE 13 additions: a crash-loop quarantine (a queue whose workers keep
dying right after registration is benched with geometric backoff — the
containment sibling of the submit-failure pause), an explicit `cancelled`
terminal status (drain scale-down, zombie reap, queue removal), and full
wire round-trips (`from_wire`) so the allocation table can ride the journal
and snapshots like every other durable table.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from hyperqueue_tpu.ids import IdCounter
from hyperqueue_tpu.utils import clock

MAX_SUBMIT_FAILS_BEFORE_PAUSE = 3
BACKOFF_BASE_SECS = 2.0
BACKOFF_MAX_SECS = 300.0

# crash-loop quarantine policy (env-overridable so chaos tests can run the
# whole loop in seconds): a worker death within WINDOW seconds of its
# registration is a "fast" death; K consecutive fast deaths quarantine the
# queue for BASE * 2^(n_quarantines-1) seconds, capped at MAX.
CRASH_LOOP_K = int(os.environ.get("HQ_AUTOALLOC_CRASH_LOOP_K", "3"))
CRASH_LOOP_WINDOW_SECS = float(
    os.environ.get("HQ_AUTOALLOC_CRASH_LOOP_WINDOW", "10.0")
)
QUARANTINE_BASE_SECS = float(
    os.environ.get("HQ_AUTOALLOC_QUARANTINE_BASE", "30.0")
)
QUARANTINE_MAX_SECS = float(
    os.environ.get("HQ_AUTOALLOC_QUARANTINE_MAX", "3600.0")
)


@dataclass
class QueueParams:
    manager: str  # "pbs" | "slurm" | "local"
    backlog: int = 1              # allocations kept in the batch queue
    workers_per_alloc: int = 1
    max_worker_count: int = 0     # 0 = unlimited
    time_limit_secs: float = 3600.0
    name: str = ""
    worker_args: list[str] = field(default_factory=list)  # extra hq args
    additional_args: list[str] = field(default_factory=list)  # qsub/sbatch args
    idle_timeout_secs: float = 300.0
    # reference SharedQueueOpts (commands/autoalloc.rs:96-180)
    worker_start_cmd: str = ""    # shell line run before each worker starts
    worker_stop_cmd: str = ""     # shell line run after the worker terminates
    worker_wrap_cmd: str = ""     # prefix for the `hq worker start` command
    worker_time_limit_secs: float = 0.0  # 0 = allocation time limit
    on_server_lost: str = "finish-running"

    def to_wire(self) -> dict:
        return self.__dict__.copy()

    @classmethod
    def from_wire(cls, data: dict) -> "QueueParams":
        return cls(**{k: v for k, v in data.items() if k in cls.__dataclass_fields__})


@dataclass
class Allocation:
    allocation_id: str          # manager job id (qsub/sbatch output)
    queue_id: int
    worker_count: int
    status: str = "queued"      # queued | running | finished | failed | cancelled
    queued_at: float = field(default_factory=clock.now)
    started_at: float = 0.0
    ended_at: float = 0.0
    connected_workers: set[int] = field(default_factory=set)
    workdir: str = ""           # holds hq-submit.sh + manager stdout/stderr
    # did ANY worker ever register from this allocation?  The zombie
    # reaper only cancels running allocations that never produced one —
    # survives restore so a restart never resets the zombie clock's basis
    ever_bound: bool = False
    # why a cancelled/failed allocation ended ("scale-down", "zombie",
    # "queue-removed", ...)
    reason: str = ""

    @property
    def is_active(self) -> bool:
        return self.status in ("queued", "running")

    def to_wire(self) -> dict:
        return {
            "id": self.allocation_id,
            "queue": self.queue_id,
            "worker_count": self.worker_count,
            "status": self.status,
            "queued_at": self.queued_at,
            "started_at": self.started_at,
            "ended_at": self.ended_at,
            "workers": sorted(self.connected_workers),
            "workdir": self.workdir,
            "ever_bound": self.ever_bound,
            "reason": self.reason,
        }

    @classmethod
    def from_wire(cls, data: dict) -> "Allocation":
        return cls(
            allocation_id=data["id"],
            queue_id=data.get("queue", 0),
            worker_count=data.get("worker_count", 1),
            status=data.get("status", "queued"),
            queued_at=data.get("queued_at", 0.0),
            started_at=data.get("started_at", 0.0),
            ended_at=data.get("ended_at", 0.0),
            connected_workers=set(data.get("workers") or ()),
            workdir=data.get("workdir", ""),
            ever_bound=bool(
                data.get("ever_bound") or data.get("workers")
            ),
            reason=data.get("reason", ""),
        )


@dataclass
class AllocationQueue:
    queue_id: int
    params: QueueParams
    state: str = "running"  # running | paused | quarantined
    allocations: dict[str, Allocation] = field(default_factory=dict)
    consecutive_failures: int = 0
    next_submit_at: float = 0.0
    # crash-loop quarantine (ISSUE 13)
    crash_streak: int = 0       # consecutive fast worker deaths
    quarantines: int = 0        # times quarantined (geometric backoff base)
    quarantine_until: float = 0.0  # wall clock; 0 = not quarantined

    def active_allocations(self) -> list[Allocation]:
        return [a for a in self.allocations.values() if a.is_active]

    def queued_allocations(self) -> list[Allocation]:
        return [a for a in self.allocations.values() if a.status == "queued"]

    def active_worker_count(self) -> int:
        return sum(a.worker_count for a in self.active_allocations())

    def on_submit_ok(self) -> None:
        self.consecutive_failures = 0
        self.next_submit_at = 0.0

    def on_submit_fail(self) -> bool:
        """Returns True if the queue should be paused."""
        self.consecutive_failures += 1
        backoff = min(
            BACKOFF_BASE_SECS * (2 ** (self.consecutive_failures - 1)),
            BACKOFF_MAX_SECS,
        )
        self.next_submit_at = clock.now() + backoff
        return self.consecutive_failures >= MAX_SUBMIT_FAILS_BEFORE_PAUSE

    # --- crash-loop quarantine ------------------------------------------
    def on_worker_death(self, fast: bool) -> bool:
        """Record one allocation-worker death. `fast` = the worker died
        (uncleanly) within CRASH_LOOP_WINDOW_SECS of registering. Returns
        True when this death tips the queue into quarantine."""
        if not fast:
            self.crash_streak = 0
            return False
        self.crash_streak += 1
        if self.crash_streak < CRASH_LOOP_K or self.state == "quarantined":
            return False
        self.quarantine()
        return True

    def quarantine(self) -> float:
        """Bench the queue with geometric backoff; returns the backoff."""
        self.quarantines += 1
        backoff = min(
            QUARANTINE_BASE_SECS * (2 ** (self.quarantines - 1)),
            QUARANTINE_MAX_SECS,
        )
        self.quarantine_until = clock.now() + backoff
        self.state = "quarantined"
        self.crash_streak = 0
        return backoff

    def maybe_release_quarantine(self) -> bool:
        """Release an expired quarantine (keeps `quarantines` so a repeat
        offender backs off twice as long next time)."""
        if self.state == "quarantined" and clock.now() >= self.quarantine_until:
            self.state = "running"
            self.quarantine_until = 0.0
            return True
        return False

    def clear_quarantine(self) -> None:
        """Operator override (`hq alloc resume`): forget the history."""
        self.quarantines = 0
        self.quarantine_until = 0.0
        self.crash_streak = 0

    def can_submit_now(self) -> bool:
        return self.state == "running" and clock.now() >= self.next_submit_at

    def to_wire(self) -> dict:
        return {
            "id": self.queue_id,
            "state": self.state,
            "params": self.params.to_wire(),
            "allocations": [a.to_wire() for a in self.allocations.values()],
            "consecutive_failures": self.consecutive_failures,
            "quarantines": self.quarantines,
            "quarantine_until": self.quarantine_until,
        }

    @classmethod
    def from_wire(cls, data: dict) -> "AllocationQueue":
        queue = cls(
            queue_id=data["id"],
            params=QueueParams.from_wire(data.get("params") or {}),
            state=data.get("state", "running"),
            consecutive_failures=data.get("consecutive_failures", 0),
            quarantines=data.get("quarantines", 0),
            quarantine_until=data.get("quarantine_until", 0.0),
        )
        for a in data.get("allocations") or ():
            alloc = Allocation.from_wire(a)
            queue.allocations[alloc.allocation_id] = alloc
        return queue


class AutoAllocState:
    def __init__(self):
        self.queues: dict[int, AllocationQueue] = {}
        self.queue_id_counter = IdCounter()

    def add_queue(self, params: QueueParams) -> AllocationQueue:
        queue = AllocationQueue(self.queue_id_counter.next(), params)
        self.queues[queue.queue_id] = queue
        return queue

    def find_allocation(self, allocation_id: str):
        for queue in self.queues.values():
            alloc = queue.allocations.get(allocation_id)
            if alloc is not None:
                return queue, alloc
        return None, None

    # --- durability (ISSUE 13) ------------------------------------------
    def capture(self) -> dict:
        """Snapshot-table form: everything `restore` needs to rebuild the
        allocation table exactly (events/snapshot.py carries this)."""
        return {
            "queues": [q.to_wire() for q in self.queues.values()],
            "next_queue_id": self.queue_id_counter.peek(),
        }

    def restore(self, data: dict) -> None:
        for qd in data.get("queues") or ():
            queue = AllocationQueue.from_wire(qd)
            self.queues[queue.queue_id] = queue
        self.queue_id_counter.ensure_above(
            data.get("next_queue_id", 1) - 1
        )
