"""Worker-type queries: "how many NEW workers of each shape would get load?"

Reference: crates/tako/src/internal/scheduler/query.rs
compute_new_worker_query — build `max_sn_workers` fake workers per query,
rerun the production batches+solver over (real + fake) workers, and count
the fake workers that received at least one task, per query.  All queries
are solved JOINTLY: an earlier query's fake workers absorb demand so a
later query only sees the leftovers (test_query.rs sn_leftovers/partial
cases).  `partial` queries pad every resource the query did not declare to
an effectively unlimited amount (query.rs:35-47 ResourceAmount::MAX) —
"we know nothing about this worker type beyond what the CLI args say, so
assume the best".  Padding covers exactly the names registered in the
resource map: amounts are never invented for resources no task or worker
ever named (test_query.rs:730 unknown_do_not_add_extra).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from hyperqueue_tpu.ops.assign import INF_TIME
from hyperqueue_tpu.resources.worker_resources import (
    TASK_MAX_COUNT_CAP,
    WorkerResources,
)
from hyperqueue_tpu.scheduler.tick import (
    WorkerRow,
    assemble_solve_inputs,
    create_batches,
)

# Stand-in for "unlimited" on padded partial resources.  Must stay BELOW
# the kernel's float32-exact bound (scheduler/tick.MAX_SAFE_AMOUNT = 2**23
# fractions): a larger pad would trigger _range_compress's column shift and
# destroy the real workers' fit precision (a 4-cpu worker would round down
# to 3 tasks).  2**23-1 fractions ≈ 838 units — far above any plausible
# single-task request, never above the compression threshold.
PARTIAL_MAX_FRACTIONS = 2**23 - 1
# Concurrency bound for a padded fake worker (WorkerResources would derive
# it from real pool sizes, which padding distorts).  Equal to the bound
# every REAL worker gets (worker_resources.TASK_MAX_COUNT_CAP), so a
# partial fake worker is never more constrained than the worker the
# allocation would actually spawn; demand beyond it spills into the next
# fake worker of the same query (max_sn_workers permitting).
PARTIAL_TASK_CAP = TASK_MAX_COUNT_CAP


@dataclass
class WorkerTypeQuery:
    """One worker shape the autoalloc planner may spawn.

    Mirrors reference control.rs WorkerTypeQuery (descriptor, partial,
    time_limit, max_sn_workers, max_workers_per_allocation,
    min_utilization)."""

    resources: WorkerResources
    partial: bool = False
    time_limit_secs: float | None = None
    max_sn_workers: int = 1
    max_workers_per_allocation: int = 1
    min_utilization: float = 0.0
    # resource ids the query's descriptor explicitly declares (partial
    # padding skips these — an explicit 0 means "this worker type has
    # none", not "unknown")
    declared_ids: frozenset[int] = field(default_factory=frozenset)


@dataclass
class MultiNodeAllocation:
    """Reference gateway.rs MultiNodeAllocationResponse."""

    worker_type: int          # index into the queries list
    workers_per_allocation: int
    max_allocations: int


@dataclass
class WorkerQueryResponse:
    single_node_workers_per_query: list[int]
    multi_node_allocations: list[MultiNodeAllocation]


def _fake_rows(
    queries: list[WorkerTypeQuery],
    n_r: int,
    pad_floor: list[int] | None = None,
) -> list[WorkerRow]:
    rows: list[WorkerRow] = []
    fake_id = 0
    for query in queries:
        amounts = list(query.resources.amounts)
        amounts += [0] * (n_r - len(amounts))
        if query.partial:
            for rid in range(n_r):
                if rid not in query.declared_ids:
                    # a task requesting MORE than the stand-in "unlimited"
                    # pad must still register demand (reference uses
                    # ResourceAmount::MAX): raise the pad to the peak
                    # pending need and let _range_compress shift that
                    # column (sound: needs ceil, free floor)
                    amounts[rid] = max(
                        PARTIAL_MAX_FRACTIONS,
                        pad_floor[rid] if pad_floor else 0,
                    )
            nt = PARTIAL_TASK_CAP
        else:
            nt = query.resources.task_max_count()
        lifetime = (
            min(int(query.time_limit_secs), int(INF_TIME))
            if query.time_limit_secs is not None
            else int(INF_TIME)
        )
        for _ in range(query.max_sn_workers):
            fake_id -= 1
            rows.append(
                WorkerRow(
                    worker_id=fake_id,
                    free=amounts[:],
                    nt_free=nt,
                    lifetime_secs=lifetime,
                    total=amounts[:],
                )
            )
    return rows


def compute_new_worker_query(
    core, model, queries: list[WorkerTypeQuery]
) -> WorkerQueryResponse:
    """Non-destructive joint solve; see module docstring."""
    n_r = len(core.resource_map)
    # Real min-utilization workers are carved out of the production solve
    # and may leave ANY load unserved (all-or-nothing floors,
    # scheduler/tick.py run_tick) — counting their capacity here would
    # absorb demand that production won't serve and starve the queues, so
    # the demand estimate drops them (conservative: may spawn a worker a
    # mu-host would in fact have taken).
    real_rows = [r for r in core.worker_rows() if r.cpu_floor <= 0]
    first_fake = len(real_rows)
    batches = create_batches(core.queues)
    pad_floor = [0] * n_r
    for batch in batches:
        for variant in core.rq_map.get_variants(batch.rq_id).variants:
            for entry in variant.entries:
                if entry.amount > pad_floor[entry.resource_id]:
                    pad_floor[entry.resource_id] = entry.amount
    rows = real_rows + _fake_rows(queries, n_r, pad_floor)

    sn_counts = np.zeros(max(sum(q.max_sn_workers for q in queries), 1))
    if batches and len(rows) > first_fake:
        # the EXACT production assembly (dense rows, scarcity batch order,
        # range compression for float32-exactness, weights) — the fake
        # workers simply ride along as extra rows
        kwargs = assemble_solve_inputs(
            rows, batches, core.rq_map, core.resource_map
        )
        counts = np.asarray(model.solve(**kwargs))
        fake_counts = counts[:, :, first_fake:]
        sn_counts = fake_counts.sum(axis=(0, 1))

        # per-query min-utilization filter: a projected worker only counts
        # if the work it would attract keeps it above its utilization
        # floor (reference query.rs min_utilization,
        # test_query.rs:273-442).  Judged on cpus (resource 0), like the
        # production floor.  needs/free here are the (identically
        # compressed) solve inputs, so the ratio is consistent.
        needs = kwargs["needs"]
        all_mask = kwargs.get("all_mask")
        offset = 0
        for query in queries:
            k = query.max_sn_workers
            # an undeclared (padded) cpu pool has no meaningful utilization
            # floor — reference test_query.rs:420 min_utilization_vs_partial2
            # expects demand at mu=1.0 from an empty partial descriptor
            cpus_padded = query.partial and 0 not in query.declared_ids
            if query.min_utilization > 0.001 and k and not cpus_padded:
                span = slice(offset, offset + k)
                cpu_fr = np.einsum(
                    "bvw,bv->w", fake_counts[:, :, span], needs[:, :, 0]
                ).astype(np.float64)
                pool = float(kwargs["free"][first_fake + offset, 0])
                if all_mask is not None:
                    # an ALL-policy cpu task occupies the whole pool (its
                    # needs row is zero; the amount lives in the mask)
                    cpu_fr += np.einsum(
                        "bvw,bv->w", fake_counts[:, :, span],
                        all_mask[:, :, 0],
                    ) * pool
                floor = query.min_utilization * pool
                sn_counts[span] = np.where(cpu_fr >= floor, cpu_fr, 0.0)
            offset += k

    per_query: list[int] = []
    offset = 0
    for query in queries:
        k = query.max_sn_workers
        per_query.append(int((sn_counts[offset : offset + k] > 0).sum()))
        offset += k

    # mn allocations: each pending gang class maps to the FIRST query able
    # to host a whole gang in one allocation (reference query.rs:97-125)
    mn: list[MultiNodeAllocation] = []
    gang_classes: dict[int, int] = {}
    for task_id in core.mn_queue:
        task = core.tasks.get(task_id)
        if task is None or task.is_done:
            continue
        gang_classes[task.rq_id] = gang_classes.get(task.rq_id, 0) + 1
    for rq_id, n_pending in gang_classes.items():
        req = core.rq_map.get_variants(rq_id).variants[0]
        for i, query in enumerate(queries):
            if (
                query.time_limit_secs is not None
                and req.min_time_secs > query.time_limit_secs
            ):
                continue
            if query.max_workers_per_allocation >= req.n_nodes:
                mn.append(
                    MultiNodeAllocation(
                        worker_type=i,
                        workers_per_allocation=req.n_nodes,
                        max_allocations=n_pending,
                    )
                )
                break
    mn.sort(key=lambda x: (x.worker_type, x.workers_per_allocation))
    return WorkerQueryResponse(
        single_node_workers_per_query=per_query,
        multi_node_allocations=mn,
    )
