"""Autoalloc service: the periodic planning/submission loop.

Reference: crates/hyperqueue/src/server/autoalloc/process.rs —
autoalloc_process (:41): interval tick doing refresh_queue_allocations (:800)
via the queue handler, then perform_submits (:367): a fake-worker query
against the scheduler (:416 -> tako query.rs:12) decides how many allocations
each queue should have in flight, bounded by compute_submission_permit (:500).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from pathlib import Path

from hyperqueue_tpu.autoalloc.controller import (
    ALLOCATIONS_TOTAL,
    QUARANTINES_TOTAL,
    SCALE_UP_SECONDS,
    SUBMIT_FAILURES_TOTAL,
    ZOMBIE_TIMEOUT_SECS,
    ElasticityController,
)
from hyperqueue_tpu.autoalloc.handlers import SubmitError, make_handler
from hyperqueue_tpu.autoalloc.query import (
    WorkerTypeQuery,
    compute_new_worker_query,
)
from hyperqueue_tpu.autoalloc.state import (
    CRASH_LOOP_WINDOW_SECS,
    Allocation,
    AutoAllocState,
    QueueParams,
)
from hyperqueue_tpu.resources.worker_resources import WorkerResources
from hyperqueue_tpu.utils import chaos
from hyperqueue_tpu.worker.hwdetect import detect_resources
from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.autoalloc")

REFRESH_INTERVAL = float(os.environ.get("HQ_AUTOALLOC_INTERVAL", "2.0"))


class AutoAllocService:
    def __init__(self, server, work_dir: Path):
        self.server = server
        self.state = AutoAllocState()
        self.work_dir = Path(work_dir)
        self._handlers: dict[int, object] = {}
        # queue params are immutable after `alloc add`; the parsed worker
        # descriptor (which probes host hardware as its base) is cached
        self._queue_descriptors: dict[int, object] = {}
        # exact resources of a worker that connected from this queue's
        # allocation — once known, demand queries use them verbatim
        # (partial=False; reference queue.get_worker_resources())
        self._queue_known_resources: dict[int, WorkerResources] = {}
        self._task: asyncio.Task | None = None
        self.controller = ElasticityController(self)
        # wid -> (queue_id, alloc_id, registered_at monotonic): the
        # crash-loop detector's registration clock + scale-down linkage
        self._worker_alloc: dict[int, tuple[int, str, float]] = {}
        # submits in flight between their alloc-submit-attempt record and
        # the alloc-queued/alloc-submit-failed outcome; snapshots carry
        # them so a crash mid-submit stays adoptable after compaction
        self._pending_attempts: list[dict] = []
        # strong refs to fire-and-forget cancel tasks: the loop keeps only
        # weak refs, so an unreferenced task can be GC'd before it runs —
        # and a collected scancel is a leaked cluster job
        self._bg_tasks: set[asyncio.Task] = set()
        # allocation-exact restore (ISSUE 13): the journal/snapshot replay
        # left the reconstructed table on the server; adopt it, then let
        # the first refresh reconcile the live set against the manager
        restored = getattr(server, "restored_autoalloc", None)
        if restored:
            self.state.restore(restored)
            n_active = sum(
                len(q.active_allocations())
                for q in self.state.queues.values()
            )
            if self.state.queues:
                logger.info(
                    "restored %d allocation queue(s) with %d active "
                    "allocation(s); reconciling against the manager",
                    len(self.state.queues), n_active,
                )
            self._adopt_orphans(restored.get("attempts") or ())

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    def emit(self, kind: str, payload: dict) -> None:
        emit = getattr(self.server, "emit_event", None)
        if emit is not None:
            emit(kind, payload)

    def capture(self) -> dict:
        """Snapshot table: the allocation state plus submits in flight
        (events/snapshot.py capture_state carries this)."""
        return {
            **self.state.capture(),
            "attempts": [dict(a) for a in self._pending_attempts],
        }

    def _adopt_orphans(self, attempts) -> None:
        """A crash BETWEEN a submit and its journal record leaves a live
        allocation the journal does not know. Every submit script writes
        its pid to <alloc workdir>/pid, and the journaled submit-attempt
        names the queue's workdir tree — scanning it for live pids the
        restored table does not know finds the orphan. Local allocations
        are adopted exactly (their allocation id IS ``local-<pid>``);
        external managers get a loud event for the operator (their manager
        job id is not recoverable from a pid)."""
        known_dirs = {
            a.workdir
            for q in self.state.queues.values()
            for a in q.allocations.values()
        }
        for attempt in attempts:
            queue = self.state.queues.get(attempt.get("queue_id"))
            if queue is None:
                continue
            if queue.params.manager != "local":
                # the manager may have accepted the submit (the job can
                # even still be sitting in ITS queue, script never run),
                # and a compute-node pid is meaningless on this host —
                # nothing can be verified locally, so any unresolved
                # attempt is surfaced loudly for the operator to check
                # against qstat/squeue
                logger.error(
                    "a %s allocation submit for queue %d has no journaled "
                    "outcome (server died mid-submit); check the manager "
                    "for an orphan job and cancel it manually (workdir "
                    "tree: %s)",
                    queue.params.manager, queue.queue_id,
                    attempt.get("workdir"),
                )
                self.emit("alloc-orphan-detected", {
                    "queue_id": queue.queue_id,
                    "workdir": attempt.get("workdir") or "",
                })
                continue
            root = Path(attempt.get("workdir") or "")
            if not root.is_dir():
                continue
            for pid_file in sorted(root.rglob("pid")):
                workdir = str(pid_file.parent)
                if workdir in known_dirs:
                    continue
                try:
                    pid = int(pid_file.read_text().strip())
                    os.kill(pid, 0)
                except (ValueError, OSError):
                    continue  # never started or already gone: no leak
                # pid-recycling guard: the live process must actually be
                # this workdir's submit script, not an innocent bystander
                # that inherited the pid
                try:
                    cmdline = Path(
                        f"/proc/{pid}/cmdline"
                    ).read_bytes().replace(b"\0", b" ").decode(
                        errors="replace"
                    )
                    if workdir not in cmdline:
                        continue
                except OSError:
                    pass  # no /proc: fall back to the liveness check alone
                allocation_id = f"local-{pid}"
                if allocation_id in queue.allocations:
                    continue
                known_dirs.add(workdir)
                queue.allocations[allocation_id] = Allocation(
                    allocation_id=allocation_id,
                    queue_id=queue.queue_id,
                    worker_count=queue.params.workers_per_alloc,
                    status="running",
                    started_at=clock.now(),
                    workdir=workdir,
                )
                logger.warning(
                    "adopted orphan local allocation %s (submit raced "
                    "the crash; journal never saw it)", allocation_id,
                )
                self.emit("alloc-queued", {
                    "queue_id": queue.queue_id, "alloc": allocation_id,
                    "worker_count": queue.params.workers_per_alloc,
                    "workdir": workdir, "adopted": True,
                })

    def forget_queue(self, queue_id: int) -> None:
        """Drop per-queue caches after `alloc remove`."""
        self._handlers.pop(queue_id, None)
        self._queue_descriptors.pop(queue_id, None)
        self._queue_known_resources.pop(queue_id, None)

    def handler_for(self, queue):
        handler = self._handlers.get(queue.queue_id)
        if handler is None:
            handler = make_handler(
                queue.params.manager,
                str(self.server.server_dir),
                self.work_dir / f"queue-{queue.queue_id}",
            )
            self._handlers[queue.queue_id] = handler
        return handler

    # ------------------------------------------------------------------
    async def _loop(self) -> None:
        logger.info("autoalloc service started")
        while True:
            try:
                await self.refresh_allocations()
                # ONE signal sample per tick, shared by the submit
                # decisions and the controller policy (a second sample
                # would double the O(workers) walk and skew the
                # backlog-slope window)
                signals = self.controller.sample_signals()
                await self.perform_submits(signals)
                # elasticity policy: quarantine release, scale-down
                # drains, allocation release, zombie reap
                self.controller.tick(signals)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - autoalloc must not die
                logger.exception("autoalloc tick failed")
            await asyncio.sleep(REFRESH_INTERVAL)

    async def refresh_allocations(self) -> None:
        for queue in self.state.queues.values():
            active = [a.allocation_id for a in queue.active_allocations()
                      if not a.allocation_id.startswith("dry-run:")]
            if not active:
                continue
            handler = self.handler_for(queue)
            try:
                statuses = await handler.refresh_statuses(active)
            except Exception as e:  # noqa: BLE001
                logger.warning("status refresh failed for queue %d: %s",
                               queue.queue_id, e)
                continue
            order = {"queued": 0, "running": 1, "finished": 2, "failed": 2,
                     "cancelled": 2}
            for allocation_id, status in statuses.items():
                alloc = queue.allocations.get(allocation_id)
                if alloc is None or alloc.status == status:
                    continue
                # never move backwards: a worker connecting marks the
                # allocation running even while the manager still reports it
                # queued (status propagation lag)
                if order[status] < order[alloc.status]:
                    continue
                self._transition(queue, alloc, status)

    def _transition(self, queue, alloc: Allocation, status: str) -> None:
        alloc.status = status
        now = clock.now()
        if status == "running" and not alloc.started_at:
            alloc.started_at = now
            self.emit(
                "alloc-started",
                {"queue_id": queue.queue_id, "alloc": alloc.allocation_id},
            )
        elif status in ("finished", "failed", "cancelled"):
            alloc.ended_at = now
            self.emit(
                f"alloc-{status}",
                {"queue_id": queue.queue_id, "alloc": alloc.allocation_id,
                 **({"reason": alloc.reason} if alloc.reason else {})},
            )

    def cancel_allocation(
        self, queue, alloc: Allocation, reason: str, failed: bool = False
    ) -> "asyncio.Task":
        """Cancel an allocation's backing manager job (scale-down drain
        completed, zombie reap, queue removal). The table transition is
        synchronous — decisions and restore see it immediately — while
        the manager call runs in the background; the returned task lets
        callers that must not outrun the cancel (alloc remove, shutdown)
        await it."""
        alloc.reason = reason
        self._transition(queue, alloc, "failed" if failed else "cancelled")
        handler = self.handler_for(queue)

        async def _remove() -> None:
            try:
                await handler.remove_allocation(alloc.allocation_id)
            except Exception as e:  # noqa: BLE001 - best-effort cancel
                logger.warning(
                    "failed to cancel allocation %s: %s",
                    alloc.allocation_id, e,
                )

        task = asyncio.ensure_future(_remove())
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    async def drain_background(self, timeout: float = 10.0) -> None:
        """Let in-flight manager cancellations finish (server shutdown):
        a scancel lost to process exit would leak a live cluster job
        that the journal already believes cancelled."""
        if self._bg_tasks:
            await asyncio.wait(set(self._bg_tasks), timeout=timeout)

    # ------------------------------------------------------------------
    @staticmethod
    def _parse_worker_args(queue):
        """(cpus, {name: item}) declared by the queue's worker args —
        the same --cpus / --resource parsing `hq worker start` applies."""
        from hyperqueue_tpu.worker.parser import parse_resource_definition

        args = list(queue.params.worker_args or [])
        cpus = None
        overrides = {}
        i = 0
        while i < len(args):
            arg = args[i]
            value = None
            for flag in ("--cpus", "--resource"):
                if arg == flag and i + 1 < len(args):
                    value = args[i + 1]
                    i += 1
                    break
                if arg.startswith(flag + "="):
                    value = arg.split("=", 1)[1]
                    break
            if value is not None:
                if arg.startswith("--cpus") or arg == "--cpus":
                    try:
                        cpus = int(value)
                    except ValueError:
                        pass
                else:
                    try:
                        item = parse_resource_definition(value)
                        overrides[item.name] = item
                    except ValueError:
                        pass
            i += 1
        return cpus, overrides

    def _queue_worker_descriptor(self, queue):
        """Resource descriptor of the workers this queue would spawn.

        Parsed from the queue's worker args (--cpus / --resource overrides
        applied over host detection, exactly as `hq worker start` would
        apply them) — the reference stores the same thing as the queue's
        cli_resource_descriptor (autoalloc/queue/mod.rs:32). Falls back to
        plain host detection when the queue declares nothing. Used for the
        mn gang-hosting check; the sn demand query uses _build_query."""
        cached = self._queue_descriptors.get(queue.queue_id)
        if cached is not None:
            return cached
        cpus, overrides = self._parse_worker_args(queue)
        base = detect_resources(n_cpus=cpus)
        if overrides:
            from hyperqueue_tpu.resources.descriptor import ResourceDescriptor

            items = {item.name: item for item in base.items}
            items.update(overrides)
            base = ResourceDescriptor(items=tuple(items.values()))
        self._queue_descriptors[queue.queue_id] = base
        return base

    def _build_query(self, queue) -> WorkerTypeQuery:
        """Reference process.rs:416 create_queue_worker_query — exact
        resources from a previously connected worker of this queue when
        known (partial=False); else the CLI-declared items with everything
        undeclared padded as unknown-best (partial=True); else an empty
        fully-partial descriptor."""
        core = self.server.core
        wpa = max(queue.params.workers_per_alloc, 1)
        known = self._queue_known_resources.get(queue.queue_id)
        if known is not None:
            resources, partial, declared = known, False, frozenset()
        else:
            from hyperqueue_tpu.resources.descriptor import (
                ResourceDescriptor,
                ResourceDescriptorItem,
            )

            cpus, overrides = self._parse_worker_args(queue)
            items = []
            if "cpus" in overrides:
                # an explicit `--resource cpus=...` declaration wins, like
                # `hq worker start` resource overrides
                items.append(overrides["cpus"])
            elif cpus is not None:
                items.append(
                    ResourceDescriptorItem.range("cpus", 0, cpus - 1)
                )
            items.extend(
                item for item in overrides.values() if item.name != "cpus"
            )
            resources = WorkerResources.from_descriptor(
                ResourceDescriptor(items=tuple(items)), core.resource_map
            )
            partial = True
            declared = frozenset(
                core.resource_map.get_or_create(item.name) for item in items
            )
        return WorkerTypeQuery(
            resources=resources,
            partial=partial,
            time_limit_secs=queue.params.time_limit_secs,
            max_sn_workers=queue.params.backlog * wpa,
            max_workers_per_allocation=wpa,
            min_utilization=self._queue_min_utilization(queue),
            declared_ids=declared,
        )

    @staticmethod
    def _queue_min_utilization(queue) -> float:
        """min_utilization the queue's spawned workers will carry (parsed
        from worker args like the descriptor; reference WorkerTypeQuery
        carries it explicitly, query.rs + test_query.rs:273-342)."""
        args = list(queue.params.worker_args or [])
        for i, arg in enumerate(args):
            if arg == "--min-utilization" and i + 1 < len(args):
                try:
                    return float(args[i + 1])
                except ValueError:
                    return 0.0
            if arg.startswith("--min-utilization="):
                try:
                    return float(arg.split("=", 1)[1])
                except ValueError:
                    return 0.0
        return 0.0

    def _fake_worker_demand(self, queue) -> int:
        """How many NEW single-node workers of this queue's shape would
        receive load right now?  Single-queue convenience wrapper over the
        joint compute_new_worker_query (autoalloc/query.py — reference
        scheduler/query.rs:12-80)."""
        if queue.params.backlog * queue.params.workers_per_alloc <= 0:
            return 0
        response = compute_new_worker_query(
            self.server.core, self.server.model, [self._build_query(queue)]
        )
        return response.single_node_workers_per_query[0]

    def _mn_demand_joint(self, queues) -> dict[int, list[int]]:
        """n_nodes of each pending multi-node task, assigned to the FIRST
        eligible queue (first-query-wins dedup, reference query.rs:97-125):
        two queues that could both host a pending gang must not each
        provision an allocation for it.

        Reference process.rs:500 (compute_submission_permit) counts mn
        allocations separately from sn workers: a pending gang that no
        current worker group can host needs a whole fresh allocation of at
        least n_nodes workers with enough lifetime."""
        from hyperqueue_tpu.server.reactor import _mn_member_eligible

        core = self.server.core
        out: dict[int, list[int]] = {q.queue_id: [] for q in queues}
        shapes = {
            q.queue_id: (
                max(q.params.workers_per_alloc, 1),
                WorkerResources.from_descriptor(
                    self._queue_worker_descriptor(q), core.resource_map
                ),
            )
            for q in queues
        }
        for task_id in core.mn_queue:
            task = core.tasks.get(task_id)
            if task is None or task.is_done:
                continue
            req = core.rq_map.get_variants(task.rq_id).variants[0]
            groups: dict[str, int] = {}
            for w in core.workers.values():
                if w.mn_task or w.draining or not _mn_member_eligible(w, req):
                    continue
                groups[w.group] = groups.get(w.group, 0) + 1
            if any(n >= req.n_nodes for n in groups.values()):
                continue  # an existing worker group can already host it
            for queue in queues:
                wpa, queue_worker = shapes[queue.queue_id]
                if req.n_nodes > wpa:
                    continue  # one allocation of this queue can't host it
                if req.min_time_secs > queue.params.time_limit_secs:
                    continue
                if any(
                    queue_worker.amount(e.resource_id) < e.amount
                    for e in req.entries
                ):
                    continue  # this queue's workers can't be members
                out[queue.queue_id].append(req.n_nodes)
                break
        return out

    async def perform_submits(self, signals: dict | None = None) -> None:
        # all eligible queues are planned in ONE joint query: an earlier
        # queue's projected workers absorb demand so a later queue only
        # provisions for the leftovers (reference process.rs:380-407 —
        # queries built per queue and solved together in query.rs)
        for queue in self.state.queues.values():
            if queue.can_submit_now():
                continue
            # blocked queues get a decision record too: "why didn't it
            # scale" is half the controller's observability contract
            if queue.state in ("paused", "quarantined"):
                self.controller.record(
                    queue.queue_id, "hold", queue.state,
                    "submits disabled while the queue is "
                    f"{queue.state}",
                )
            elif queue.next_submit_at > clock.now():
                self.controller.record(
                    queue.queue_id, "hold", "submit-backoff",
                    f"{queue.consecutive_failures} consecutive submit "
                    "failure(s); backing off",
                )
        eligible = [
            q for q in self.state.queues.values() if q.can_submit_now()
        ]
        if not eligible:
            return
        # SLO gate (ISSUE 18): while a page-severity burn-rate alert is
        # firing, the control plane is already failing its objectives —
        # buying MORE workers would pile registration/dispatch load onto
        # a struggling server (and spend allocation budget on capacity
        # it cannot drive). Hold scale-up, with a verdict per queue so
        # `hq alloc events` explains the pause; ticket-severity alerts
        # do not gate (slow burn leaves time for capacity to help).
        slo = getattr(self.server, "slo", None)
        paging = slo.paging_alerts() if slo is not None else []
        if paging:
            names = ",".join(sorted(a["alert"] for a in paging))
            for queue in eligible:
                self.controller.record(
                    queue.queue_id, "hold", "slo-page",
                    f"scale-up held: page alert(s) firing ({names})",
                )
            return
        response = compute_new_worker_query(
            self.server.core,
            self.server.model,
            [self._build_query(q) for q in eligible],
        )
        mn_by_queue = self._mn_demand_joint(eligible)
        for queue, sn_workers in zip(
            eligible, response.single_node_workers_per_query
        ):
            wpa = max(queue.params.workers_per_alloc, 1)
            mn_nodes = mn_by_queue[queue.queue_id]
            # in-flight capacity first satisfies mn demand (a whole alloc
            # per gang), the rest counts against sn demand (reference
            # process.rs:500 step 1). In-flight = workers an active
            # allocation has NOT yet connected: queued allocations
            # entirely (a batch job may legitimately sit queued for
            # hours), plus a bounded boot/reconnect window of running
            # ones — a restored `running` allocation whose workers are
            # still re-registering must absorb demand or a restart would
            # double-submit (allocation-exact restore, ISSUE 13). The
            # window is bounded by the zombie timeout: past it, a
            # running allocation's missing workers are presumed dead and
            # must not suppress scale-up for the allocation's lifetime.
            workers = self.server.core.workers
            now = clock.now()
            queued = queue.queued_allocations()
            for alloc in queue.active_allocations():
                if alloc.status == "running" and (
                    now - (alloc.started_at or alloc.queued_at)
                    > ZOMBIE_TIMEOUT_SECS
                ):
                    continue
                live = sum(
                    1 for wid in alloc.connected_workers if wid in workers
                )
                inflight = max(alloc.worker_count - live, 0)
                if inflight <= 0:
                    continue
                if mn_nodes and inflight >= mn_nodes[0]:
                    inflight -= mn_nodes.pop(0)
                sn_workers = max(0, sn_workers - inflight)
            allocs_needed = len(mn_nodes) + -(-sn_workers // wpa)
            logger.debug(
                "queue %d sn_demand=%d mn_demand=%d allocs_needed=%d",
                queue.queue_id, sn_workers, len(mn_nodes), allocs_needed,
            )
            if allocs_needed <= 0:
                self.controller.record(
                    queue.queue_id, "hold", "no-demand",
                    "fake-worker query: no new worker of this shape "
                    "would receive load",
                )
                continue
            # permit: stay within backlog and max worker count
            permit = queue.params.backlog - len(queued)
            if queue.params.max_worker_count:
                headroom = (
                    queue.params.max_worker_count - queue.active_worker_count()
                )
                permit = min(permit, headroom // wpa)
            n_submit = max(0, min(allocs_needed, permit))
            if signals is None:
                # standalone callers (tests) without a shared tick sample
                signals = self.controller.sample_signals()
            if n_submit <= 0:
                self.controller.record(
                    queue.queue_id, "hold", "backlog-full",
                    f"demand {allocs_needed} allocation(s) but "
                    f"{len(queued)} already queued (backlog "
                    f"{queue.params.backlog}, max workers "
                    f"{queue.params.max_worker_count or 'unlimited'})",
                )
                continue
            self.controller.record(
                queue.queue_id, "scale-up", "insufficient-capacity",
                f"submitting {n_submit} allocation(s): demand "
                f"{sn_workers} sn worker(s) + {len(mn_nodes)} gang(s), "
                f"backlog {signals['ready']} ready "
                f"(slope {signals['slope']:+.1f}/s, "
                f"{signals['insufficient_capacity']} marked "
                "insufficient-capacity last tick)",
            )
            for _ in range(n_submit):
                await self._submit_one(queue)

    async def _submit_one(self, queue) -> None:
        handler = self.handler_for(queue)
        # write-ahead intent: a kill -9 BETWEEN the submit and its
        # alloc-queued record would otherwise leak the allocation — the
        # attempt names the workdir, whose pidfile makes the orphan
        # findable at restore (see _adopt_orphans)
        # the handler's next allocation dir is deterministic enough for
        # adoption: the pidfile scan walks every numbered dir under it
        workdir_hint = str(
            self.work_dir / f"queue-{queue.queue_id}"
        )
        attempt = {"queue_id": queue.queue_id, "workdir": workdir_hint}
        self._pending_attempts.append(attempt)
        self.emit("alloc-submit-attempt", dict(attempt))
        try:
            allocation_id, workdir = await handler.submit_allocation(
                queue.queue_id, queue.params
            )
        except Exception as e:  # noqa: BLE001 - ANY failure must clear
            # the attempt, or it would ride every future snapshot and
            # trigger a spurious orphan scan on each restore
            self._pending_attempts.remove(attempt)
            logger.warning("allocation submit failed: %s", e)
            SUBMIT_FAILURES_TOTAL.inc()
            self.emit(
                "alloc-submit-failed",
                {"queue_id": queue.queue_id, "error": str(e)},
            )
            self.controller.record(
                queue.queue_id, "scale-up-failed", "submit-error", str(e)
            )
            if queue.on_submit_fail():
                queue.state = "paused"
                self.emit(
                    "alloc-queue-paused", {"queue_id": queue.queue_id}
                )
            return
        try:
            if chaos.ACTIVE:
                # the adoption window: the allocation exists at the
                # manager but alloc-queued has not hit the journal yet —
                # kill here proves the pidfile scan finds the orphan
                chaos.fire("autoalloc.post-spawn", op=queue.params.manager)
        finally:
            # a non-kill chaos action (raise) must not leave a LIVE
            # allocation untracked: the bookkeeping always completes
            # (SIGKILL bypasses finally, which is the point of the site)
            self._pending_attempts.remove(attempt)
            queue.on_submit_ok()
            ALLOCATIONS_TOTAL.labels(queue.params.manager).inc()
            queue.allocations[allocation_id] = Allocation(
                allocation_id=allocation_id,
                queue_id=queue.queue_id,
                worker_count=queue.params.workers_per_alloc,
                workdir=workdir,
            )
            self.emit(
                "alloc-queued",
                {"queue_id": queue.queue_id, "alloc": allocation_id,
                 "worker_count": queue.params.workers_per_alloc,
                 "workdir": workdir},
            )

    # ------------------------------------------------------------------
    def on_worker_connected(self, worker_id: int, alloc_id: str) -> None:
        queue, alloc = self.state.find_allocation(alloc_id)
        if alloc is None:
            return
        alloc.connected_workers.add(worker_id)
        self._worker_alloc[worker_id] = (
            queue.queue_id, alloc_id, clock.monotonic()
        )
        if not alloc.ever_bound:
            alloc.ever_bound = True
            # scale-up latency: submit accepted -> first usable capacity
            if alloc.queued_at:
                SCALE_UP_SECONDS.observe(
                    max(clock.now() - alloc.queued_at, 0.0)
                )
            self.emit(
                "alloc-worker-bound",
                {"queue_id": queue.queue_id, "alloc": alloc_id,
                 "worker": worker_id},
            )
        worker = self.server.core.workers.get(worker_id)
        if worker is not None:
            self._queue_known_resources[queue.queue_id] = (
                worker.resources
            )
        if alloc.status == "queued":
            self._transition(queue, alloc, "running")

    def on_worker_lost(self, worker_id: int, reason: str) -> None:
        """Crash-loop containment: an allocation worker that died
        (uncleanly) within CRASH_LOOP_WINDOW_SECS of registering counts
        toward the queue's crash streak; the K-th tips it into
        quarantine with geometric backoff (state.py)."""
        linked = self._worker_alloc.pop(worker_id, None)
        if linked is None:
            return
        queue_id, alloc_id, registered_at = linked
        queue = self.state.queues.get(queue_id)
        if queue is None:
            return
        alloc = queue.allocations.get(alloc_id)
        if alloc is not None:
            alloc.connected_workers.discard(worker_id)
        lifetime = clock.monotonic() - registered_at
        clean = reason == "stopped" or reason.startswith("lent")
        fast = not clean and lifetime < CRASH_LOOP_WINDOW_SECS
        if queue.on_worker_death(fast):
            QUARANTINES_TOTAL.inc()
            backoff = queue.quarantine_until - clock.now()
            logger.warning(
                "queue %d quarantined: workers keep dying within %.0fs of "
                "registration (%.0fs backoff, offense #%d)",
                queue_id, CRASH_LOOP_WINDOW_SECS, backoff,
                queue.quarantines,
            )
            self.emit(
                "alloc-queue-quarantined",
                {"queue_id": queue_id,
                 "backoff": round(backoff, 1),
                 "until": queue.quarantine_until,
                 "quarantines": queue.quarantines},
            )
            self.controller.record(
                queue_id, "quarantined", "crash-loop",
                f"worker {worker_id} of allocation {alloc_id} died "
                f"{lifetime:.1f}s after registering ({reason}); "
                f"backing off {backoff:.0f}s",
            )

    async def dry_run(self, params: QueueParams) -> dict:
        handler = make_handler(
            params.manager, str(self.server.server_dir), self.work_dir / "dryrun"
        )
        script = handler.build_script(0, params)
        return {"script": script, "submit_binary": handler.submit_binary}

    async def probe_submit(self, params: QueueParams) -> str | None:
        """Submit a probing allocation and immediately cancel it — `alloc add`
        verifies queue parameters this way unless --no-dry-run (reference
        commands/autoalloc.rs no_dry_run, process.rs dry-run submit).
        Returns an error message, or None if the probe succeeded."""
        handler = make_handler(
            params.manager, str(self.server.server_dir), self.work_dir / "dryrun"
        )
        try:
            allocation_id, _workdir = await handler.submit_allocation(0, params)
        except (SubmitError, OSError) as e:
            return str(e)
        try:
            await handler.remove_allocation(allocation_id)
        except Exception:  # noqa: BLE001 — cancel is best-effort
            logger.warning("failed to cancel probe allocation %s", allocation_id)
        return None
