"""Autoalloc service: the periodic planning/submission loop.

Reference: crates/hyperqueue/src/server/autoalloc/process.rs —
autoalloc_process (:41): interval tick doing refresh_queue_allocations (:800)
via the queue handler, then perform_submits (:367): a fake-worker query
against the scheduler (:416 -> tako query.rs:12) decides how many allocations
each queue should have in flight, bounded by compute_submission_permit (:500).
"""

from __future__ import annotations

import asyncio
import logging
import time
from pathlib import Path

from hyperqueue_tpu.autoalloc.handlers import SubmitError, make_handler
from hyperqueue_tpu.autoalloc.query import (
    WorkerTypeQuery,
    compute_new_worker_query,
)
from hyperqueue_tpu.autoalloc.state import (
    Allocation,
    AutoAllocState,
    QueueParams,
)
from hyperqueue_tpu.resources.worker_resources import WorkerResources
from hyperqueue_tpu.worker.hwdetect import detect_resources

logger = logging.getLogger("hq.autoalloc")

REFRESH_INTERVAL = 2.0


class AutoAllocService:
    def __init__(self, server, work_dir: Path):
        self.server = server
        self.state = AutoAllocState()
        self.work_dir = Path(work_dir)
        self._handlers: dict[int, object] = {}
        # queue params are immutable after `alloc add`; the parsed worker
        # descriptor (which probes host hardware as its base) is cached
        self._queue_descriptors: dict[int, object] = {}
        # exact resources of a worker that connected from this queue's
        # allocation — once known, demand queries use them verbatim
        # (partial=False; reference queue.get_worker_resources())
        self._queue_known_resources: dict[int, WorkerResources] = {}
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    def forget_queue(self, queue_id: int) -> None:
        """Drop per-queue caches after `alloc remove`."""
        self._handlers.pop(queue_id, None)
        self._queue_descriptors.pop(queue_id, None)
        self._queue_known_resources.pop(queue_id, None)

    def handler_for(self, queue):
        handler = self._handlers.get(queue.queue_id)
        if handler is None:
            handler = make_handler(
                queue.params.manager,
                str(self.server.server_dir),
                self.work_dir / f"queue-{queue.queue_id}",
            )
            self._handlers[queue.queue_id] = handler
        return handler

    # ------------------------------------------------------------------
    async def _loop(self) -> None:
        logger.info("autoalloc service started")
        while True:
            try:
                await self.refresh_allocations()
                await self.perform_submits()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - autoalloc must not die
                logger.exception("autoalloc tick failed")
            await asyncio.sleep(REFRESH_INTERVAL)

    async def refresh_allocations(self) -> None:
        for queue in self.state.queues.values():
            active = [a.allocation_id for a in queue.active_allocations()
                      if not a.allocation_id.startswith("dry-run:")]
            if not active:
                continue
            handler = self.handler_for(queue)
            try:
                statuses = await handler.refresh_statuses(active)
            except Exception as e:  # noqa: BLE001
                logger.warning("status refresh failed for queue %d: %s",
                               queue.queue_id, e)
                continue
            order = {"queued": 0, "running": 1, "finished": 2, "failed": 2}
            for allocation_id, status in statuses.items():
                alloc = queue.allocations.get(allocation_id)
                if alloc is None or alloc.status == status:
                    continue
                # never move backwards: a worker connecting marks the
                # allocation running even while the manager still reports it
                # queued (status propagation lag)
                if order[status] < order[alloc.status]:
                    continue
                self._transition(queue, alloc, status)

    def _transition(self, queue, alloc: Allocation, status: str) -> None:
        alloc.status = status
        now = time.time()
        if status == "running" and not alloc.started_at:
            alloc.started_at = now
            self.server.emit_event(
                "alloc-started",
                {"queue_id": queue.queue_id, "alloc": alloc.allocation_id},
            )
        elif status in ("finished", "failed"):
            alloc.ended_at = now
            self.server.emit_event(
                f"alloc-{status}",
                {"queue_id": queue.queue_id, "alloc": alloc.allocation_id},
            )

    # ------------------------------------------------------------------
    @staticmethod
    def _parse_worker_args(queue):
        """(cpus, {name: item}) declared by the queue's worker args —
        the same --cpus / --resource parsing `hq worker start` applies."""
        from hyperqueue_tpu.worker.parser import parse_resource_definition

        args = list(queue.params.worker_args or [])
        cpus = None
        overrides = {}
        i = 0
        while i < len(args):
            arg = args[i]
            value = None
            for flag in ("--cpus", "--resource"):
                if arg == flag and i + 1 < len(args):
                    value = args[i + 1]
                    i += 1
                    break
                if arg.startswith(flag + "="):
                    value = arg.split("=", 1)[1]
                    break
            if value is not None:
                if arg.startswith("--cpus") or arg == "--cpus":
                    try:
                        cpus = int(value)
                    except ValueError:
                        pass
                else:
                    try:
                        item = parse_resource_definition(value)
                        overrides[item.name] = item
                    except ValueError:
                        pass
            i += 1
        return cpus, overrides

    def _queue_worker_descriptor(self, queue):
        """Resource descriptor of the workers this queue would spawn.

        Parsed from the queue's worker args (--cpus / --resource overrides
        applied over host detection, exactly as `hq worker start` would
        apply them) — the reference stores the same thing as the queue's
        cli_resource_descriptor (autoalloc/queue/mod.rs:32). Falls back to
        plain host detection when the queue declares nothing. Used for the
        mn gang-hosting check; the sn demand query uses _build_query."""
        cached = self._queue_descriptors.get(queue.queue_id)
        if cached is not None:
            return cached
        cpus, overrides = self._parse_worker_args(queue)
        base = detect_resources(n_cpus=cpus)
        if overrides:
            from hyperqueue_tpu.resources.descriptor import ResourceDescriptor

            items = {item.name: item for item in base.items}
            items.update(overrides)
            base = ResourceDescriptor(items=tuple(items.values()))
        self._queue_descriptors[queue.queue_id] = base
        return base

    def _build_query(self, queue) -> WorkerTypeQuery:
        """Reference process.rs:416 create_queue_worker_query — exact
        resources from a previously connected worker of this queue when
        known (partial=False); else the CLI-declared items with everything
        undeclared padded as unknown-best (partial=True); else an empty
        fully-partial descriptor."""
        core = self.server.core
        wpa = max(queue.params.workers_per_alloc, 1)
        known = self._queue_known_resources.get(queue.queue_id)
        if known is not None:
            resources, partial, declared = known, False, frozenset()
        else:
            from hyperqueue_tpu.resources.descriptor import (
                ResourceDescriptor,
                ResourceDescriptorItem,
            )

            cpus, overrides = self._parse_worker_args(queue)
            items = []
            if "cpus" in overrides:
                # an explicit `--resource cpus=...` declaration wins, like
                # `hq worker start` resource overrides
                items.append(overrides["cpus"])
            elif cpus is not None:
                items.append(
                    ResourceDescriptorItem.range("cpus", 0, cpus - 1)
                )
            items.extend(
                item for item in overrides.values() if item.name != "cpus"
            )
            resources = WorkerResources.from_descriptor(
                ResourceDescriptor(items=tuple(items)), core.resource_map
            )
            partial = True
            declared = frozenset(
                core.resource_map.get_or_create(item.name) for item in items
            )
        return WorkerTypeQuery(
            resources=resources,
            partial=partial,
            time_limit_secs=queue.params.time_limit_secs,
            max_sn_workers=queue.params.backlog * wpa,
            max_workers_per_allocation=wpa,
            min_utilization=self._queue_min_utilization(queue),
            declared_ids=declared,
        )

    @staticmethod
    def _queue_min_utilization(queue) -> float:
        """min_utilization the queue's spawned workers will carry (parsed
        from worker args like the descriptor; reference WorkerTypeQuery
        carries it explicitly, query.rs + test_query.rs:273-342)."""
        args = list(queue.params.worker_args or [])
        for i, arg in enumerate(args):
            if arg == "--min-utilization" and i + 1 < len(args):
                try:
                    return float(args[i + 1])
                except ValueError:
                    return 0.0
            if arg.startswith("--min-utilization="):
                try:
                    return float(arg.split("=", 1)[1])
                except ValueError:
                    return 0.0
        return 0.0

    def _fake_worker_demand(self, queue) -> int:
        """How many NEW single-node workers of this queue's shape would
        receive load right now?  Single-queue convenience wrapper over the
        joint compute_new_worker_query (autoalloc/query.py — reference
        scheduler/query.rs:12-80)."""
        if queue.params.backlog * queue.params.workers_per_alloc <= 0:
            return 0
        response = compute_new_worker_query(
            self.server.core, self.server.model, [self._build_query(queue)]
        )
        return response.single_node_workers_per_query[0]

    def _mn_demand_joint(self, queues) -> dict[int, list[int]]:
        """n_nodes of each pending multi-node task, assigned to the FIRST
        eligible queue (first-query-wins dedup, reference query.rs:97-125):
        two queues that could both host a pending gang must not each
        provision an allocation for it.

        Reference process.rs:500 (compute_submission_permit) counts mn
        allocations separately from sn workers: a pending gang that no
        current worker group can host needs a whole fresh allocation of at
        least n_nodes workers with enough lifetime."""
        from hyperqueue_tpu.server.reactor import _mn_member_eligible

        core = self.server.core
        out: dict[int, list[int]] = {q.queue_id: [] for q in queues}
        shapes = {
            q.queue_id: (
                max(q.params.workers_per_alloc, 1),
                WorkerResources.from_descriptor(
                    self._queue_worker_descriptor(q), core.resource_map
                ),
            )
            for q in queues
        }
        for task_id in core.mn_queue:
            task = core.tasks.get(task_id)
            if task is None or task.is_done:
                continue
            req = core.rq_map.get_variants(task.rq_id).variants[0]
            groups: dict[str, int] = {}
            for w in core.workers.values():
                if w.mn_task or not _mn_member_eligible(w, req):
                    continue
                groups[w.group] = groups.get(w.group, 0) + 1
            if any(n >= req.n_nodes for n in groups.values()):
                continue  # an existing worker group can already host it
            for queue in queues:
                wpa, queue_worker = shapes[queue.queue_id]
                if req.n_nodes > wpa:
                    continue  # one allocation of this queue can't host it
                if req.min_time_secs > queue.params.time_limit_secs:
                    continue
                if any(
                    queue_worker.amount(e.resource_id) < e.amount
                    for e in req.entries
                ):
                    continue  # this queue's workers can't be members
                out[queue.queue_id].append(req.n_nodes)
                break
        return out

    async def perform_submits(self) -> None:
        # all eligible queues are planned in ONE joint query: an earlier
        # queue's projected workers absorb demand so a later queue only
        # provisions for the leftovers (reference process.rs:380-407 —
        # queries built per queue and solved together in query.rs)
        eligible = [
            q for q in self.state.queues.values() if q.can_submit_now()
        ]
        if not eligible:
            return
        response = compute_new_worker_query(
            self.server.core,
            self.server.model,
            [self._build_query(q) for q in eligible],
        )
        mn_by_queue = self._mn_demand_joint(eligible)
        for queue, sn_workers in zip(
            eligible, response.single_node_workers_per_query
        ):
            wpa = max(queue.params.workers_per_alloc, 1)
            mn_nodes = mn_by_queue[queue.queue_id]
            # queued allocations first satisfy mn demand (a whole alloc per
            # gang), their remaining workers count against sn demand
            # (reference process.rs:500 step 1)
            queued = queue.queued_allocations()
            for alloc in queued:
                worker_count = alloc.worker_count
                if mn_nodes and worker_count >= mn_nodes[0]:
                    worker_count -= mn_nodes.pop(0)
                sn_workers = max(0, sn_workers - worker_count)
            allocs_needed = len(mn_nodes) + -(-sn_workers // wpa)
            logger.debug(
                "queue %d sn_demand=%d mn_demand=%d allocs_needed=%d",
                queue.queue_id, sn_workers, len(mn_nodes), allocs_needed,
            )
            if allocs_needed <= 0:
                continue
            # permit: stay within backlog and max worker count
            permit = queue.params.backlog - len(queued)
            if queue.params.max_worker_count:
                headroom = (
                    queue.params.max_worker_count - queue.active_worker_count()
                )
                permit = min(permit, headroom // wpa)
            for _ in range(max(0, min(allocs_needed, permit))):
                await self._submit_one(queue)

    async def _submit_one(self, queue) -> None:
        handler = self.handler_for(queue)
        try:
            allocation_id, workdir = await handler.submit_allocation(
                queue.queue_id, queue.params
            )
        except (SubmitError, OSError) as e:
            logger.warning("allocation submit failed: %s", e)
            self.server.emit_event(
                "alloc-submit-failed",
                {"queue_id": queue.queue_id, "error": str(e)},
            )
            if queue.on_submit_fail():
                queue.state = "paused"
                self.server.emit_event(
                    "alloc-queue-paused", {"queue_id": queue.queue_id}
                )
            return
        queue.on_submit_ok()
        queue.allocations[allocation_id] = Allocation(
            allocation_id=allocation_id,
            queue_id=queue.queue_id,
            worker_count=queue.params.workers_per_alloc,
            workdir=workdir,
        )
        self.server.emit_event(
            "alloc-queued",
            {"queue_id": queue.queue_id, "alloc": allocation_id,
             "worker_count": queue.params.workers_per_alloc},
        )

    # ------------------------------------------------------------------
    def on_worker_connected(self, worker_id: int, alloc_id: str) -> None:
        queue, alloc = self.state.find_allocation(alloc_id)
        if alloc is not None:
            alloc.connected_workers.add(worker_id)
            worker = self.server.core.workers.get(worker_id)
            if worker is not None:
                self._queue_known_resources[queue.queue_id] = (
                    worker.resources
                )
            if alloc.status == "queued":
                self._transition(queue, alloc, "running")

    async def dry_run(self, params: QueueParams) -> dict:
        handler = make_handler(
            params.manager, str(self.server.server_dir), self.work_dir / "dryrun"
        )
        script = handler.build_script(0, params)
        return {"script": script, "submit_binary": handler.submit_binary}

    async def probe_submit(self, params: QueueParams) -> str | None:
        """Submit a probing allocation and immediately cancel it — `alloc add`
        verifies queue parameters this way unless --no-dry-run (reference
        commands/autoalloc.rs no_dry_run, process.rs dry-run submit).
        Returns an error message, or None if the probe succeeded."""
        handler = make_handler(
            params.manager, str(self.server.server_dir), self.work_dir / "dryrun"
        )
        try:
            allocation_id, _workdir = await handler.submit_allocation(0, params)
        except (SubmitError, OSError) as e:
            return str(e)
        try:
            await handler.remove_allocation(allocation_id)
        except Exception:  # noqa: BLE001 — cancel is best-effort
            logger.warning("failed to cancel probe allocation %s", allocation_id)
        return None
