"""Elasticity controller: the consumer of the autoscaler signal feed.

Closes the loop ROADMAP item 1 left open: the subscribe plane (ISSUE 8)
exports queue depths, ``pending_reasons`` (``insufficient-capacity``
counts from the per-tick DecisionRecords) and per-worker idle samples —
this controller consumes the same server-side signals every tick of the
autoalloc service and drives:

- **scale-up** corroboration + decision records: the fake-worker demand
  query stays authoritative (it answers "would a new worker of this shape
  receive load?"), and every verdict — scaled, held, blocked — is recorded
  with the backlog/pending-reason evidence so ``hq alloc events`` can
  answer "why did/didn't it scale";
- **scale-down**: a worker that has idled for the queue's idle timeout is
  gracefully DRAINED (masked from the solve by ``Worker.draining``, so no
  assignment can race its departure — the membership-mask move PR 11's
  lend exclusion introduced); once an allocation's last worker is gone its
  backing manager job is cancelled — capacity leaves, task state never;
- **failure containment**: crash-loop quarantine release (geometric
  backoff lives in state.py), and a zombie reaper for allocations that
  reach ``running`` but never produce a registered worker.

Pure policy: the controller never touches sockets or subprocesses itself;
it calls ``server.start_drain`` and the queue handlers the service owns.
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque

from hyperqueue_tpu.utils.metrics import REGISTRY
from hyperqueue_tpu.utils import clock

logger = logging.getLogger("hq.autoalloc")

# an allocation that reached `running` but never produced a registered
# worker within this window is a zombie: its manager job is cancelled
ZOMBIE_TIMEOUT_SECS = float(
    os.environ.get("HQ_AUTOALLOC_ZOMBIE_TIMEOUT", "120.0")
)

# how many (time, backlog) samples feed the backlog-slope estimate
_BACKLOG_WINDOW = 16

ALLOCATIONS_TOTAL = REGISTRY.counter(
    "hq_autoalloc_allocations_total",
    "allocations successfully submitted to a queue manager",
    labels=("manager",),
)
SUBMIT_FAILURES_TOTAL = REGISTRY.counter(
    "hq_autoalloc_submit_failures_total",
    "allocation submits that failed (manager error, timeout, chaos)",
)
QUARANTINES_TOTAL = REGISTRY.counter(
    "hq_autoalloc_quarantines_total",
    "allocation queues quarantined by the crash-loop detector",
)
ZOMBIES_REAPED_TOTAL = REGISTRY.counter(
    "hq_autoalloc_zombies_reaped_total",
    "running allocations cancelled because no worker ever registered "
    "within the zombie timeout",
)
SCALE_UP_SECONDS = REGISTRY.histogram(
    "hq_autoalloc_scale_up_seconds",
    "allocation submit to its first registered worker",
    buckets=(0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 300.0, 1800.0),
)


class ElasticityController:
    """Per-server scale policy + decision journal (see module docstring)."""

    def __init__(self, service):
        self.service = service
        self.server = service.server
        # decision records, newest last; consecutive identical verdicts
        # for a queue collapse into one record with a tick count
        self.decisions: deque[dict] = deque(maxlen=512)
        # wid -> monotonic stamp of when the worker was last seen busy
        # (absent = not yet observed); sustained idle = now - stamp
        self._idle_since: dict[int, float] = {}
        # (time, total_ready) ring for the backlog-slope signal
        self._backlog: deque[tuple[float, int]] = deque(maxlen=_BACKLOG_WINDOW)
        # allocation ids the scale-down path drained: when their last
        # live worker departs, the backing manager job is cancelled
        self._draining_allocs: set[str] = set()

    # --- decision journal ------------------------------------------------
    def record(self, queue_id: int, verdict: str, reason: str,
               detail: str = "") -> None:
        """Append one scale verdict; consecutive repeats collapse."""
        now = clock.now()
        if self.decisions:
            last = self.decisions[-1]
            if (
                last["queue"] == queue_id
                and last["verdict"] == verdict
                and last["reason"] == reason
            ):
                last["ticks"] += 1
                last["last_time"] = now
                return
        self.decisions.append({
            "time": now, "last_time": now, "ticks": 1,
            "queue": queue_id, "verdict": verdict,
            "reason": reason, "detail": detail,
        })

    # --- signal sampling -------------------------------------------------
    def sample_signals(self) -> dict:
        """One tick's worth of the same signals the subscribe plane
        streams: backlog, its slope, and insufficient-capacity counts."""
        core = self.server.core
        now = clock.monotonic()
        ready = core.queues.total_ready() + len(core.mn_queue)
        self._backlog.append((now, ready))
        slope = 0.0
        if len(self._backlog) >= 2:
            t0, r0 = self._backlog[0]
            t1, r1 = self._backlog[-1]
            if t1 > t0:
                slope = (r1 - r0) / (t1 - t0)
        pending = {}
        latest = core.flight.latest() or {}
        for entry in latest.get("unplaced") or ():
            reason = entry.get("reason")
            if reason:
                pending[reason] = pending.get(reason, 0) + entry.get("count", 0)
        # per-worker idle tracking (all workers; scale-down below only
        # ever acts on allocation-bound ones)
        live = set()
        for w in core.workers.values():
            live.add(w.worker_id)
            busy = (
                w.assigned_tasks or w.prefilled_tasks or w.mn_task
                or w.mn_reserved
            )
            if busy:
                self._idle_since[w.worker_id] = now
            else:
                self._idle_since.setdefault(w.worker_id, now)
        for wid in [w for w in self._idle_since if w not in live]:
            del self._idle_since[wid]
        return {
            "ready": ready,
            "slope": slope,
            "insufficient_capacity": pending.get("insufficient-capacity", 0),
            "pending_reasons": pending,
        }

    def idle_for(self, worker_id: int) -> float:
        stamp = self._idle_since.get(worker_id)
        return 0.0 if stamp is None else clock.monotonic() - stamp

    # --- per-tick policy -------------------------------------------------
    def tick(self, signals: dict) -> None:
        service = self.service
        # a drained allocation usually ends on its own (the stopped worker
        # exits the batch script); drop tracking for anything no longer
        # active so the set cannot grow unboundedly
        if self._draining_allocs:
            active_ids = {
                a.allocation_id
                for q in service.state.queues.values()
                for a in q.active_allocations()
            }
            self._draining_allocs.intersection_update(active_ids)
        for queue in list(service.state.queues.values()):
            if queue.maybe_release_quarantine():
                service.emit("alloc-queue-resumed", {
                    "queue_id": queue.queue_id, "from": "quarantine",
                    "quarantines": queue.quarantines,
                })
                self.record(
                    queue.queue_id, "quarantine-released",
                    "backoff-expired",
                    f"quarantine #{queue.quarantines} expired; submits "
                    "re-enabled (next offense backs off twice as long)",
                )
            self._scale_down(queue, signals)
            self._reap_zombies(queue)

    def _scale_down(self, queue, signals: dict) -> None:
        """Drain sustained-idle allocation workers; cancel allocations
        whose last worker left."""
        threshold = max(queue.params.idle_timeout_secs, 0.1)
        core = self.server.core
        # drain idle workers bound to this queue's active allocations
        for alloc in queue.active_allocations():
            live = [
                wid for wid in alloc.connected_workers
                if wid in core.workers
            ]
            for wid in live:
                worker = core.workers[wid]
                if worker.draining:
                    continue
                idle_s = self.idle_for(wid)
                if idle_s < threshold:
                    continue
                started = self.server.start_drain(
                    [wid], timeout=max(threshold, 30.0),
                    source="scale-down",
                )
                if started:
                    self._draining_allocs.add(alloc.allocation_id)
                    self.record(
                        queue.queue_id, "scale-down", "sustained-idle",
                        f"worker {wid} idle {idle_s:.1f}s >= "
                        f"{threshold:.1f}s; draining (allocation "
                        f"{alloc.allocation_id})",
                    )
            if (
                alloc.allocation_id in self._draining_allocs
                and not live
            ):
                # the last drained worker is gone: release the backing
                # manager job — the allocation's capacity has left the pool
                self._draining_allocs.discard(alloc.allocation_id)
                self.service.cancel_allocation(
                    queue, alloc, reason="scale-down"
                )
                self.record(
                    queue.queue_id, "scale-down", "allocation-released",
                    f"allocation {alloc.allocation_id} drained to empty; "
                    "manager job cancelled",
                )

    def _reap_zombies(self, queue) -> None:
        now = clock.now()
        for alloc in queue.active_allocations():
            if (
                alloc.status == "running"
                and alloc.started_at
                and not alloc.ever_bound
                and now - alloc.started_at >= ZOMBIE_TIMEOUT_SECS
            ):
                ZOMBIES_REAPED_TOTAL.inc()
                logger.warning(
                    "allocation %s has been running %.0fs without a "
                    "registered worker; reaping as zombie",
                    alloc.allocation_id, now - alloc.started_at,
                )
                self.service.cancel_allocation(
                    queue, alloc, reason="zombie", failed=True
                )
                self.service.emit("alloc-zombie-reaped", {
                    "queue_id": queue.queue_id,
                    "alloc": alloc.allocation_id,
                    "ran_for": round(now - alloc.started_at, 1),
                })
                self.record(
                    queue.queue_id, "zombie-reaped", "never-registered",
                    f"allocation {alloc.allocation_id} ran "
                    f"{now - alloc.started_at:.0f}s with no worker",
                )

    def to_wire(self) -> list[dict]:
        return [dict(d) for d in self.decisions]
