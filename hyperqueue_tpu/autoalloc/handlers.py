"""Queue handlers: build and submit PBS/Slurm/local allocations.

Reference: crates/hyperqueue/src/server/autoalloc/queue/{pbs,slurm,common}.rs —
a QueueHandler trait with qsub/sbatch script builders and qstat/sacct status
refresh. External binaries are resolved via PATH, which is also how the test
mock takes over (reference tests/autoalloc/mock; ours: fake executables on
PATH writing their argv to files).

ISSUE 13 additions:

- every external queue-manager subprocess is bounded by a hard timeout +
  kill (`HQ_AUTOALLOC_MANAGER_TIMEOUT`, default 30 s): a hung
  `sbatch`/`qstat` is a submit/refresh FAILURE, never a wedged autoalloc
  tick loop (counted in ``hq_autoalloc_manager_timeouts_total``);
- a ``local`` handler that spawns real worker processes on the server's
  host — the whole autoscaling loop runs in CI without a batch scheduler,
  and doubles as the FaultPlan chaos surface (submit fails, allocation
  stuck queued, worker boots then dies, worker never registers);
- submit scripts write their pid to ``<workdir>/pid`` so a crash between
  the submit and its journal record leaves an adoptable trail instead of a
  leaked allocation (events/restore.py + service reconciliation).
"""

from __future__ import annotations

import asyncio
import json
import os
import shlex
import signal
import sys
from pathlib import Path

from hyperqueue_tpu.autoalloc.state import QueueParams
from hyperqueue_tpu.utils import chaos
from hyperqueue_tpu.utils.metrics import REGISTRY

# hard ceiling on any single qsub/sbatch/qstat/sacct/qdel/scancel call
MANAGER_TIMEOUT_SECS = float(
    os.environ.get("HQ_AUTOALLOC_MANAGER_TIMEOUT", "30.0")
)

_MANAGER_TIMEOUTS = REGISTRY.counter(
    "hq_autoalloc_manager_timeouts_total",
    "external queue-manager calls (qsub/sbatch/qstat/sacct/...) killed "
    "after the hard timeout; counted as submit/refresh failures",
)


class SubmitError(Exception):
    pass


class ManagerTimeout(SubmitError):
    """An external manager binary exceeded the hard call timeout."""


def _format_walltime(secs: float) -> str:
    secs = int(secs)
    return f"{secs // 3600:02d}:{(secs % 3600) // 60:02d}:{secs % 60:02d}"


def _worker_command(server_dir: str, queue_id: int, params: QueueParams) -> str:
    # the elasticity controller owns scale-down: it DRAINS a worker once it
    # has idled for the queue's idle timeout (masked from the solve, so no
    # assignment can race its departure). The worker's own idle timeout is
    # kept as a 4x fallback for when the server is unreachable and cannot
    # drive the drain.
    args = [
        sys.executable,
        "-m",
        "hyperqueue_tpu",
        "worker",
        "start",
        "--server-dir",
        server_dir,
        "--idle-timeout",
        str(params.idle_timeout_secs * 4),
        "--time-limit",
        str(params.worker_time_limit_secs or params.time_limit_secs),
        "--on-server-lost",
        params.on_server_lost or "finish-running",
        *params.worker_args,
    ]
    cmd = " ".join(shlex.quote(a) for a in args)
    if params.worker_wrap_cmd:
        # reference worker_wrap_cmd: `<wrap> hq worker start ...`
        cmd = f"{params.worker_wrap_cmd} {cmd}"
    return cmd


def _node_command(params: QueueParams, worker_cmd: str) -> str:
    """Per-node shell line: start hook, (wrapped) worker, stop hook.
    The stop hook runs regardless of the worker's exit status
    (reference worker_start_cmd/worker_stop_cmd, best-effort)."""
    parts = []
    if params.worker_start_cmd:
        parts.append(params.worker_start_cmd)
    parts.append(worker_cmd)
    if params.worker_stop_cmd:
        parts.append(params.worker_stop_cmd)
    return " ; ".join(parts)


class QueueHandler:
    """Common machinery; subclasses define submit/status binaries + script."""

    manager = "none"
    submit_binary = "true"

    def __init__(self, server_dir: str, work_dir: Path):
        self.server_dir = server_dir
        self.work_dir = Path(work_dir)
        self.work_dir.mkdir(parents=True, exist_ok=True)

    def build_script(
        self, queue_id: int, params: QueueParams, workdir: Path | None = None
    ) -> str:
        raise NotImplementedError

    def parse_submit_output(self, stdout: str) -> str:
        raise NotImplementedError

    def _create_allocation_dir(self, queue_id: int, params: QueueParams) -> Path:
        """Per-allocation working directory holding the submit script and the
        manager-captured stdout/stderr (reference queue/common.rs
        create_allocation_dir: <server_dir>/autoalloc/<id>[-name]/<n>)."""
        name = str(queue_id) + (f"-{params.name}" if params.name else "")
        parent = self.work_dir / name
        parent.mkdir(parents=True, exist_ok=True)
        n = len(list(parent.iterdir()))
        while True:
            n += 1
            workdir = parent / f"{n:03d}"
            try:
                workdir.mkdir()
                return workdir
            except FileExistsError:
                continue

    async def submit_allocation(
        self, queue_id: int, params: QueueParams, dry_run: bool = False
    ) -> tuple[str, str]:
        """Run qsub/sbatch on a generated script; returns
        (allocation id, allocation working directory)."""
        workdir = self._create_allocation_dir(queue_id, params)
        script = self.build_script(queue_id, params, workdir)
        path = workdir / "hq-submit.sh"
        path.write_text(script)
        os.chmod(path, 0o755)
        cmd = [self.submit_binary, *params.additional_args, str(path)]
        if dry_run:
            return f"dry-run:{path}", str(workdir)
        if chaos.ACTIVE and chaos.decide(
            "autoalloc.submit", op=self.manager
        ) == "raise":
            raise SubmitError("chaos: injected submit failure")
        process = await asyncio.create_subprocess_exec(
            *cmd,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            start_new_session=True,  # timeout kill covers the whole tree
        )
        stdout, stderr = await self._communicate_bounded(process, cmd[0])
        if process.returncode != 0:
            raise SubmitError(
                f"{self.submit_binary} failed "
                f"(exit {process.returncode}): {stderr.decode(errors='replace')}"
            )
        return self.parse_submit_output(stdout.decode()), str(workdir)

    async def refresh_statuses(self, allocation_ids: list[str]) -> dict[str, str]:
        """allocation_id -> queued|running|finished|failed."""
        raise NotImplementedError

    async def remove_allocation(self, allocation_id: str) -> None:
        raise NotImplementedError

    @staticmethod
    async def _communicate_bounded(process, binary: str):
        """communicate() with the hard manager timeout: on expiry the
        process group is killed and ManagerTimeout propagates — a hung
        manager binary becomes a failed call, never a hung autoalloc tick
        loop (the caller's existing failure handling takes over)."""
        try:
            return await asyncio.wait_for(
                process.communicate(), timeout=MANAGER_TIMEOUT_SECS
            )
        except asyncio.TimeoutError:
            _MANAGER_TIMEOUTS.inc()
            # kill the whole session: a child of the manager binary (e.g.
            # a helper the site wrapped around sbatch) inheriting the
            # output pipe would otherwise keep the reaping communicate()
            # blocked until IT exits
            try:
                os.killpg(process.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                try:
                    process.kill()
                except ProcessLookupError:
                    pass
            # reap so the transport doesn't leak; the group was KILLed,
            # so this returns promptly
            await process.communicate()
            raise ManagerTimeout(
                f"{binary} did not answer within {MANAGER_TIMEOUT_SECS:.0f}s"
                " (killed)"
            ) from None

    async def _run(self, *cmd) -> tuple[int, str]:
        process = await asyncio.create_subprocess_exec(
            *cmd,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
            start_new_session=True,
        )
        stdout, _ = await self._communicate_bounded(process, cmd[0])
        return process.returncode, stdout.decode(errors="replace")


class PbsHandler(QueueHandler):
    manager = "pbs"
    submit_binary = "qsub"

    def build_script(
        self, queue_id: int, params: QueueParams, workdir: Path | None = None
    ) -> str:
        worker_cmd = _worker_command(self.server_dir, queue_id, params)
        lines = [
            "#!/bin/bash",
            f"#PBS -N hq-alloc-{queue_id}",
            f"#PBS -l select={params.workers_per_alloc}",
            f"#PBS -l walltime={_format_walltime(params.time_limit_secs)}",
        ]
        if workdir is not None:
            lines += [
                f"#PBS -o {workdir / 'stdout'}",
                f"#PBS -e {workdir / 'stderr'}",
            ]
        lines += [
            "export HQ_ALLOC_QUEUE=%d" % queue_id,
            'export HQ_ALLOC_ID="$PBS_JOBID"',
        ]
        if workdir is not None:
            # adoption trail: a crash between submit and its journal
            # record can find (and reconcile) this allocation by workdir
            lines.append(f"echo $$ > {shlex.quote(str(workdir / 'pid'))}")
        node_cmd = _node_command(params, worker_cmd)
        if params.workers_per_alloc > 1:
            lines.append(
                f"pbsdsh -- bash -l -c {shlex.quote(node_cmd)}"
            )
        else:
            lines.append(node_cmd)
        return "\n".join(lines) + "\n"

    def parse_submit_output(self, stdout: str) -> str:
        allocation_id = stdout.strip().splitlines()[-1].strip()
        if not allocation_id:
            raise SubmitError("qsub returned no job id")
        return allocation_id

    async def refresh_statuses(self, allocation_ids):
        out: dict[str, str] = {}
        if not allocation_ids:
            return out
        code, text = await self._run("qstat", "-f", *allocation_ids)
        current = None
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("Job Id:"):
                current = line.split(":", 1)[1].strip()
            elif line.startswith("job_state") and current:
                state = line.split("=")[-1].strip()
                out[current] = {
                    "Q": "queued", "H": "queued", "R": "running",
                    "F": "finished", "E": "running",
                }.get(state, "failed")
        for aid in allocation_ids:
            out.setdefault(aid, "finished")  # vanished from qstat
        return out

    async def remove_allocation(self, allocation_id: str) -> None:
        await self._run("qdel", allocation_id)


class SlurmHandler(QueueHandler):
    manager = "slurm"
    submit_binary = "sbatch"

    def build_script(
        self, queue_id: int, params: QueueParams, workdir: Path | None = None
    ) -> str:
        worker_cmd = _worker_command(self.server_dir, queue_id, params)
        lines = [
            "#!/bin/bash",
            f"#SBATCH --job-name=hq-alloc-{queue_id}",
            f"#SBATCH --nodes={params.workers_per_alloc}",
            f"#SBATCH --time={_format_walltime(params.time_limit_secs)}",
        ]
        if workdir is not None:
            lines += [
                f"#SBATCH --output={workdir / 'stdout'}",
                f"#SBATCH --error={workdir / 'stderr'}",
            ]
        lines += [
            "export HQ_ALLOC_QUEUE=%d" % queue_id,
            'export HQ_ALLOC_ID="$SLURM_JOB_ID"',
        ]
        if workdir is not None:
            lines.append(f"echo $$ > {shlex.quote(str(workdir / 'pid'))}")
        node_cmd = _node_command(params, worker_cmd)
        if params.workers_per_alloc > 1:
            lines.append(f"srun --overlap bash -c {shlex.quote(node_cmd)}")
        else:
            lines.append(node_cmd)
        return "\n".join(lines) + "\n"

    def parse_submit_output(self, stdout: str) -> str:
        # "Submitted batch job 12345"
        for token in reversed(stdout.split()):
            if token.isdigit():
                return token
        raise SubmitError(f"cannot parse sbatch output: {stdout!r}")

    async def refresh_statuses(self, allocation_ids):
        out: dict[str, str] = {}
        if not allocation_ids:
            return out
        code, text = await self._run(
            "sacct", "-j", ",".join(allocation_ids), "-o", "JobID,State",
            "--noheader", "--parsable2",
        )
        for line in text.splitlines():
            parts = line.strip().split("|")
            if len(parts) < 2 or "." in parts[0]:
                continue
            jid, state = parts[0], parts[1].split()[0] if parts[1] else ""
            out[jid] = {
                "PENDING": "queued",
                "RUNNING": "running",
                "COMPLETED": "finished",
                "COMPLETING": "running",
                "CANCELLED": "failed",
                "FAILED": "failed",
                "TIMEOUT": "finished",
            }.get(state, "failed" if state else "queued")
        for aid in allocation_ids:
            out.setdefault(aid, "finished")
        return out

    async def remove_allocation(self, allocation_id: str) -> None:
        await self._run("scancel", allocation_id)


# fault plan injected into a chaos-"raise" local spawn: the worker boots,
# registers, then SIGKILLs itself on its first heartbeat send — the
# deterministic "worker boots then dies" crash-loop surface
_BOOT_DIE_PLAN = json.dumps({
    "rules": [
        {"site": "worker.send", "op": "heartbeat", "at": 1, "action": "kill"}
    ]
})


class LocalHandler(QueueHandler):
    """Spawn real worker processes on the server's own host.

    The whole elasticity loop (demand query -> submit -> worker register ->
    drain -> cancel) runs without PBS/Slurm — in CI, in `bench.py
    --elasticity-smoke`, and on single-node deployments. Each "allocation"
    is one detached process group running `workers_per_alloc` workers; the
    allocation id is ``local-<pgid>``, so liveness/cancellation work by
    pid across server restarts (allocation-exact restore reconciles
    against `os.kill(pid, 0)` exactly like qstat/sacct).

    FaultPlan chaos surface (site ``autoalloc.spawn``, see utils/chaos.py):
    ``drop`` = allocation recorded but never spawned (stuck queued),
    ``hang`` = the process runs but no worker ever starts (zombie:
    reaches `running`, never registers), ``raise`` = the worker registers
    then dies (crash loop). Site ``autoalloc.submit`` (all managers):
    ``raise`` fails the submit.
    """

    manager = "local"
    submit_binary = "bash"

    def __init__(self, server_dir: str, work_dir: Path):
        super().__init__(server_dir, work_dir)
        self._procs: dict[str, asyncio.subprocess.Process] = {}
        self._reapers: set[asyncio.Task] = set()
        self._stuck_seq = 0

    def build_script(
        self, queue_id: int, params: QueueParams, workdir: Path | None = None,
        spawn_action: str | None = None,
    ) -> str:
        worker_cmd = _worker_command(self.server_dir, queue_id, params)
        lines = ["#!/bin/bash"]
        if workdir is not None:
            lines.append(f"echo $$ > {shlex.quote(str(workdir / 'pid'))}")
        lines += [
            "export HQ_ALLOC_QUEUE=%d" % queue_id,
            'export HQ_ALLOC_ID="local-$$"',
        ]
        if spawn_action == "hang":
            # allocation "runs" but no worker ever registers: the zombie
            # reaper's prey
            lines.append("exec sleep 100000")
            return "\n".join(lines) + "\n"
        if spawn_action == "raise":
            lines.append(
                f"export HQ_FAULT_PLAN={shlex.quote(_BOOT_DIE_PLAN)}"
            )
            # fast heartbeat so the boot-die fires right after registration
            worker_cmd = worker_cmd + " --heartbeat 0.5"
        node_cmd = _node_command(params, worker_cmd)
        for _ in range(max(params.workers_per_alloc, 1)):
            lines.append(f"( {node_cmd} ) &")
        lines.append("wait")
        return "\n".join(lines) + "\n"

    def parse_submit_output(self, stdout: str) -> str:  # pragma: no cover
        raise SubmitError("local allocations are spawned, not submitted")

    def _worker_env(self) -> dict:
        """Environment for spawned workers: the server's own fault plan
        must NOT leak into them (each process loads its own plan);
        HQ_LOCAL_WORKER_FAULT_PLAN explicitly opts workers into one."""
        env = dict(os.environ)
        env.pop("HQ_FAULT_PLAN", None)
        worker_plan = env.pop("HQ_LOCAL_WORKER_FAULT_PLAN", None)
        if worker_plan:
            env["HQ_FAULT_PLAN"] = worker_plan
        return env

    async def submit_allocation(
        self, queue_id: int, params: QueueParams, dry_run: bool = False
    ) -> tuple[str, str]:
        workdir = self._create_allocation_dir(queue_id, params)
        if chaos.ACTIVE and chaos.decide(
            "autoalloc.submit", op=self.manager
        ) == "raise":
            raise SubmitError("chaos: injected local submit failure")
        spawn_action = (
            chaos.decide("autoalloc.spawn", op=self.manager)
            if chaos.ACTIVE else None
        )
        script = self.build_script(
            queue_id, params, workdir, spawn_action=spawn_action
        )
        path = workdir / "hq-submit.sh"
        path.write_text(script)
        os.chmod(path, 0o755)
        if dry_run:
            return f"dry-run:{path}", str(workdir)
        if spawn_action == "drop":
            # recorded but never spawned: stuck queued forever (models a
            # batch queue that accepts the job and never schedules it)
            self._stuck_seq += 1
            return f"local-q{self._stuck_seq}", str(workdir)
        with open(workdir / "stdout", "wb") as out, \
                open(workdir / "stderr", "wb") as err:
            process = await asyncio.create_subprocess_exec(
                "/bin/bash", str(path),
                stdout=out, stderr=err,
                start_new_session=True,  # killpg covers workers + hooks
                env=self._worker_env(),
            )
        allocation_id = f"local-{process.pid}"
        self._procs[allocation_id] = process
        # reap on exit so finished allocations never linger as OS
        # zombies; the strong ref keeps the reaper from being GC'd
        # before it runs (the loop holds tasks weakly)
        task = asyncio.ensure_future(process.wait())
        self._reapers.add(task)
        task.add_done_callback(self._reapers.discard)
        return allocation_id, str(workdir)

    @staticmethod
    def _pid_of(allocation_id: str) -> int | None:
        if not allocation_id.startswith("local-"):
            return None
        tail = allocation_id[len("local-"):]
        return int(tail) if tail.isdigit() else None

    async def refresh_statuses(self, allocation_ids):
        out: dict[str, str] = {}
        for allocation_id in allocation_ids:
            pid = self._pid_of(allocation_id)
            if pid is None:
                # a chaos-stuck (never-spawned) allocation stays queued
                out[allocation_id] = "queued"
                continue
            process = self._procs.get(allocation_id)
            if process is not None and process.returncode is not None:
                out[allocation_id] = (
                    "finished" if process.returncode == 0 else "failed"
                )
                # terminal: drop the Process ref, or allocation churn on a
                # long-lived server grows _procs without bound
                self._procs.pop(allocation_id, None)
                continue
            if process is not None:
                out[allocation_id] = "running"
                continue
            # adopted/restored allocation: pid liveness is the manager
            try:
                os.kill(pid, 0)
                out[allocation_id] = "running"
            except ProcessLookupError:
                out[allocation_id] = "finished"
            except PermissionError:
                out[allocation_id] = "running"
        return out

    async def remove_allocation(self, allocation_id: str) -> None:
        pid = self._pid_of(allocation_id)
        self._procs.pop(allocation_id, None)
        if pid is None:
            return
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def make_handler(manager: str, server_dir: str, work_dir: Path) -> QueueHandler:
    if manager == "pbs":
        return PbsHandler(server_dir, work_dir)
    if manager == "slurm":
        return SlurmHandler(server_dir, work_dir)
    if manager == "local":
        return LocalHandler(server_dir, work_dir)
    raise ValueError(
        f"unknown manager {manager!r} (expected pbs, slurm or local)"
    )
